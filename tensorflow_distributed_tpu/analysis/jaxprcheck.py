"""Trace-level contract pass: collective & upcast census vs goldens.

The lint layer (analysis/lint.py) reads SOURCE; this layer reads the
PROGRAM. Each audited program — the LM / MoE / pipelined train steps
and the serve decode step — is traced with ``jax.make_jaxpr``
(precedent: parallel/pipeline.py's variant_residual_mask) and reduced
to a census of the two quantities that silently drift:

- **collectives**: psum / all_gather / ppermute / all_to_all /
  reduce_scatter equation counts, sub-jaxprs included. A PR that
  accidentally adds an all-gather to the decode step, or doubles the
  pipeline's ppermutes, changes a number here and fails loudly —
  instead of showing up as an ICI regression three sessions later.
- **upcasts**: ``convert_element_type`` equations widening a float
  (bfloat16→float32, float32→float64). bf16 paths legitimately upcast
  in a few places (loss accumulation, norm statistics, optimizer
  math); the census pins HOW MANY, so a silently-f32 matmul chain
  shows up as a count jump.

Budgets live in ``analysis/goldens/census.json`` (committed).
Regenerate after an INTENTIONAL change with::

    python -m tensorflow_distributed_tpu.analysis.jaxprcheck --update

and review the diff like any other golden. Plain runs compare and exit
nonzero on drift (wired into scripts/lint.sh → scripts/t1.sh; the
same comparison is a test in tests/test_analysis.py).

Census counts are pinned against THIS container's jax; a jax upgrade
that re-lowers a primitive is a legitimate regeneration, and the diff
shows exactly what moved.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _force_cpu_topology() -> None:
    """The 8-device virtual CPU setup, exactly like tests/conftest:
    flags must land before the backend is first USED (this
    environment's sitecustomize imports jax at interpreter start, so
    "before jax import" is not an option — what matters is that no
    backend exists yet). Called from main() ONLY: importing this
    module as a library must not re-platform the process (a TPU tool
    reusing census_of/iter_eqns keeps its devices). Under pytest,
    conftest already applied the same values; re-applying is a no-op.
    """
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized: use what the caller chose

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "goldens", "census.json")

COLLECTIVE_PREFIXES = (
    "psum", "all_gather", "ppermute", "pmin", "pmax",
    "all_to_all", "reduce_scatter", "pgather",
)


# --- jaxpr walking -----------------------------------------------------

def _jaxprs_in(value) -> Iterator:
    """Yield any (Closed)Jaxpr reachable from an eqn param value."""
    if hasattr(value, "jaxpr") and hasattr(value, "consts"):
        yield value.jaxpr          # ClosedJaxpr
    elif hasattr(value, "eqns"):
        yield value                # Jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _jaxprs_in(v)


def iter_eqns(jaxpr) -> Iterator:
    """Every equation, sub-jaxprs (pjit / scan / cond / shard_map /
    remat / custom_vjp bodies) included."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _jaxprs_in(v):
                yield from iter_eqns(sub)


def census_of(closed_jaxpr) -> Dict[str, Dict[str, int]]:
    """{"collectives": {prim: n}, "upcasts": {"bfloat16->float32": n}}"""
    collectives: Dict[str, int] = {}
    upcasts: Dict[str, int] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name.startswith(COLLECTIVE_PREFIXES):
            collectives[name] = collectives.get(name, 0) + 1
        elif name == "convert_element_type":
            old = np.dtype(eqn.invars[0].aval.dtype)
            new = np.dtype(eqn.params["new_dtype"])
            if (jnp.issubdtype(old, jnp.floating)
                    and jnp.issubdtype(new, jnp.floating)
                    and new.itemsize > old.itemsize):
                key = f"{old.name}->{new.name}"
                upcasts[key] = upcasts.get(key, 0) + 1
    return {"collectives": dict(sorted(collectives.items())),
            "upcasts": dict(sorted(upcasts.items()))}


# --- the audited programs ----------------------------------------------

_B, _L, _V = 4, 16, 64  # toy shapes; the census tracks structure, not size


def _clm_batch():
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    ds = synthetic_clm(n=max(2 * _B, 32), seq_len=_L, vocab_size=_V)
    return ds.batch(np.arange(_B))


def _mesh(data: int = 1, pipe: int = 1, model: int = 1):
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    need = data * pipe * model
    devs = jax.devices()[:need]
    if len(devs) < need:
        raise RuntimeError(
            f"census needs {need} devices, have {len(devs)} — run via "
            f"the CLI (it forces an 8-device CPU topology) or under "
            f"tests/conftest.py")
    return make_mesh(MeshConfig(data=data, pipe=pipe, model=model), devs)


def _train_jaxpr(model_name: str, health_every: int = 0,
                 health_taps: bool = False):
    """The REAL jitted LM train step (same builders as train/loop.py),
    traced: bf16 compute so the upcast census watches the path that
    matters, dropout 0 so the trace is rng-schedule-free.

    ``health_every``/``health_taps`` build the health-instrumented
    variant (observe/health.py): its golden entry pins that enabling
    telemetry adds NO collectives — the vitals are local reductions,
    and a regression that sneaks an allreduce into the cadence branch
    fails here, not in an ICI profile three sessions later."""
    import optax

    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, make_moe_loss, mlm_batch_shardings)

    mesh = _mesh()
    factory = (transformer.moe_lm if model_name == "moe_lm"
               else transformer.gpt_lm)
    model = factory(mesh=mesh, size="tiny", dropout_rate=0.0,
                    compute_dtype=jnp.bfloat16,
                    health_taps=health_taps)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, _L), np.int32), mesh, seed=0)
    loss = (make_moe_loss() if model_name == "moe_lm"
            else make_mlm_loss())
    step = make_train_step(mesh, loss=loss,
                           batch_shardings=mlm_batch_shardings(mesh),
                           health_every=health_every)
    return jax.make_jaxpr(step)(state, _clm_batch())


def _pipelined_jaxpr(health_every: int = 0):
    """The 1F1B pipelined step on a pipe=2 mesh — the program whose
    ppermute schedule the census exists to pin. The health variant
    proves the telemetry adds zero ppermutes/psums to the schedule."""
    import optax

    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    mesh = _mesh(data=1, pipe=2)
    model = pipelined_lm(mesh, num_microbatches=2, dropout_rate=0.0,
                         compute_dtype=jnp.bfloat16, n_layers=2,
                         max_len=_L)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, _L), np.int32), mesh)
    step = make_1f1b_train_step(model, mesh, health_every=health_every)
    return jax.make_jaxpr(step)(state, _clm_batch())


#: The overlap census build: data=2 mesh, tiny model, a bucket bound
#: and scatter threshold small enough that the tiny tree splits into
#: SEVERAL scatter buckets — the golden pins one psum_scatter + one
#: all_gather PER BUCKET (plus the replicated-leaf psum and the metric
#: pmeans), so a refactor that fuses, drops, or doubles a bucket's
#: collectives fails here by count.
_OVERLAP_DATA = 2
_OVERLAP_BUCKET_BYTES = 8192
_OVERLAP_MIN_SIZE = 256


def _overlap_jaxpr(model_name: str):
    """The explicit overlap train step (parallel/overlap.py) on a
    data=2 mesh: bucketed psum_scatter -> ZeRO-1 sharded update ->
    bucketed all_gather, traced via the REAL builder. Model built
    mesh-less (the forward runs inside the step's shard_map; see the
    builder's docstring), state built with zero1 slots at the same
    scatter threshold the step plans with."""
    import optax

    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.parallel.overlap import (
        make_explicit_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, make_moe_loss, mlm_batch_shardings)

    mesh = _mesh(data=_OVERLAP_DATA)
    factory = (transformer.moe_lm if model_name == "moe_lm"
               else transformer.gpt_lm)
    model = factory(mesh=None, size="tiny", dropout_rate=0.0,
                    compute_dtype=jnp.bfloat16, tp_partitioning=False)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, _L), np.int32), mesh, seed=0,
                               opt_fsdp=True,
                               fsdp_min_size=_OVERLAP_MIN_SIZE)
    loss = (make_moe_loss() if model_name == "moe_lm"
            else make_mlm_loss())
    step = make_explicit_train_step(
        mesh, state, loss=loss,
        batch_shardings=mlm_batch_shardings(mesh), grad_sync="overlap",
        bucket_bytes=_OVERLAP_BUCKET_BYTES,
        fsdp_min_size=_OVERLAP_MIN_SIZE, jit=False)
    return jax.make_jaxpr(step)(state, _clm_batch())


def _serve_model(kv_cache_quant: str = "none"):
    """The tiny bf16 causal LM + zeroed slot cache the serve censuses
    trace against (kv_cache_quant="int8" produces the quantized cache
    layout — int8 K/V leaves with f32 scale leaves beside them)."""
    from tensorflow_distributed_tpu.models.transformer import (
        CausalLM, tiny_config)

    num_slots = 4
    model = CausalLM(tiny_config(causal=True,
                                 compute_dtype=jnp.bfloat16,
                                 kv_cache_quant=kv_cache_quant))
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    tok = jnp.zeros((num_slots, 1), jnp.int32)
    pos = jnp.zeros((num_slots, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda p, t, q: model.apply({"params": p}, t, decode=True,
                                    positions=q,
                                    mutable=["cache"])[1]["cache"],
        params, tok, pos)
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    return model, params, cache, num_slots


def _serve_decode_jaxpr(kv_cache_quant: str = "none"):
    """THE decode program serve/engine.py dispatches every step: one
    greedy token for every slot at its own depth. The int8 variant
    (``serve_decode_int8``) pins that KV-cache quantization adds NO
    collectives and only a bounded number of dtype converts — the
    quantize-on-write/scale-adjusted-attend math is entirely local."""
    from tensorflow_distributed_tpu.models.generate import decode_token

    model, params, cache, num_slots = _serve_model(kv_cache_quant)

    def run(params, cache, tok, pos):
        # Mirrors serve/engine.py::_compiled_step: greedy token + the
        # per-slot finiteness flag (NaN containment sensor) — the
        # golden pins that the flag adds ZERO collectives.
        last, cache = decode_token(model, params, cache, tok, pos)
        ok = jnp.isfinite(last).all(axis=-1)
        return (cache, jnp.argmax(last, axis=-1).astype(jnp.int32),
                ok)

    return jax.make_jaxpr(run)(params, cache,
                               jnp.zeros((num_slots,), jnp.int32),
                               jnp.zeros((num_slots,), jnp.int32))


#: The verify census build: k proposals per slot, matching
#: serve/engine.py::_compiled_verify's shape discipline (toks
#: [S, k+1] = pending + proposals; one forward, argmax chain + ok).
_VERIFY_K = 4


def _serve_verify_jaxpr():
    """THE speculative verify program (serve/engine.py::
    _compiled_verify): all k proposals scored in one forward over the
    slot cache. The golden pins that speculation's verify adds ZERO
    collectives next to serve_decode — it is the same local attend
    over k + 1 positions."""
    model, params, cache, num_slots = _serve_model()
    k = _VERIFY_K

    def run(params, cache, toks, pos):
        positions = pos[:, None] + jnp.arange(k + 1)[None, :]
        logits, state = model.apply(
            {"params": params, "cache": cache}, toks, decode=True,
            positions=positions, mutable=["cache"])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ok = jnp.isfinite(logits).all(axis=(-1, -2))
        return state["cache"], nxt, ok

    return jax.make_jaxpr(run)(
        params, cache, jnp.zeros((num_slots, k + 1), jnp.int32),
        jnp.zeros((num_slots,), jnp.int32))


#: The paged-census page size (tiny max_len 128 -> 8 pages per slot).
_PAGE_SIZE = 16


def _serve_paged_model():
    """The tiny bf16 causal LM over a PAGED slot cache (serve/paging):
    [num_pages, page_size, ...] pool leaves + per-slot page tables."""
    from tensorflow_distributed_tpu.models.transformer import (
        CausalLM, tiny_config)

    num_slots = 4
    cfg = tiny_config(causal=True, compute_dtype=jnp.bfloat16)
    maxp = cfg.max_len // _PAGE_SIZE
    cfg = dataclasses.replace(cfg, kv_page_size=_PAGE_SIZE,
                              kv_num_pages=1 + num_slots * maxp)
    model = CausalLM(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    tables = jnp.zeros((num_slots, maxp), jnp.int32)
    tok = jnp.zeros((num_slots, 1), jnp.int32)
    pos = jnp.zeros((num_slots, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda p, t, q, g: model.apply({"params": p}, t, decode=True,
                                       positions=q, page_table=g,
                                       mutable=["cache"])[1]["cache"],
        params, tok, pos, tables)
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    return model, params, cache, tables, num_slots


def _serve_decode_paged_jaxpr():
    """THE paged decode program (serve/paging/engine.py::
    _compiled_step_paged): the dense decode plus the page-table gather
    — the golden pins that paging adds ZERO collectives (the gather is
    a local addressing change, not communication)."""
    model, params, cache, tables, num_slots = _serve_paged_model()

    def run(params, cache, tok, pos, tables):
        logits, state = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            decode=True, positions=pos[:, None], page_table=tables,
            mutable=["cache"])
        last = logits[:, -1, :]
        ok = jnp.isfinite(last).all(axis=-1)
        return (state["cache"],
                jnp.argmax(last, axis=-1).astype(jnp.int32), ok)

    return jax.make_jaxpr(run)(params, cache,
                               jnp.zeros((num_slots,), jnp.int32),
                               jnp.zeros((num_slots,), jnp.int32),
                               tables)


def _serve_verify_paged_jaxpr():
    """THE paged speculative verify (serve/paging/engine.py::
    _compiled_verify_paged) — zero collectives, like the dense one."""
    model, params, cache, tables, num_slots = _serve_paged_model()
    k = _VERIFY_K

    def run(params, cache, toks, pos, tables):
        positions = pos[:, None] + jnp.arange(k + 1)[None, :]
        logits, state = model.apply(
            {"params": params, "cache": cache}, toks, decode=True,
            positions=positions, page_table=tables, mutable=["cache"])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ok = jnp.isfinite(logits).all(axis=(-1, -2))
        return state["cache"], nxt, ok

    return jax.make_jaxpr(run)(
        params, cache, jnp.zeros((num_slots, k + 1), jnp.int32),
        jnp.zeros((num_slots,), jnp.int32), tables)


def _serve_prefill_paged_jaxpr():
    """THE paged tail-prefill program (serve/paging/engine.py::
    _compiled_prefill_paged, bucket 16): writes the uncached tail
    through the slot's page table at an offset, attends the cached
    prefix pages, emits the greedy first token — zero collectives."""
    model, params, cache, tables, _num_slots = _serve_paged_model()
    bucket = 16

    def run(params, cache, prompt, positions, table, true_len):
        logits, state = model.apply(
            {"params": params, "cache": cache}, prompt, decode=True,
            positions=positions, page_table=table, mutable=["cache"])
        last = jax.lax.dynamic_index_in_dim(
            logits, true_len - 1, axis=1, keepdims=False)
        return (state["cache"],
                jnp.argmax(last, axis=-1).astype(jnp.int32))

    return jax.make_jaxpr(run)(
        params, cache, jnp.zeros((1, bucket), jnp.int32),
        jnp.zeros((1, bucket), jnp.int32), tables[:1],
        jnp.asarray(1, jnp.int32))


# --- tensor-parallel serve censuses ------------------------------------
#
# GSPMD inserts the TP collectives during PARTITIONING, after the jaxpr
# — jax.make_jaxpr sees none of them, so the TP entries census the
# COMPILED HLO text instead (the same artifact the AOT planner costs).
# The op names below are HLO's, not jaxpr primitives; the "-start"
# variants catch an async split, which counts the same program once.

HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")

_SERVE_TP = 2  # the model-axis width the TP censuses pin


def _hlo_collectives(hlo: str) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for op in HLO_COLLECTIVES:
        n = hlo.count(f" {op}(") + hlo.count(f" {op}-start(")
        if n:
            counts[op] = n
    return dict(sorted(counts.items()))


def _serve_tp_model(kv_cache_quant: str = "none"):
    """The tiny bf16 causal LM over a [data=1, model=2] mesh — the
    layout ``--serve.mesh-model 2`` builds (serve/run.py): params
    placed via the partition metadata (heads/MLP width sharded over
    "model"), slot cache head-sharded by serve.engine.shard_cache."""
    import flax.linen as nn

    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.parallel.sharding import (
        param_sharding)
    from tensorflow_distributed_tpu.serve.engine import zero_cache

    num_slots = 4
    mesh = _mesh(model=_SERVE_TP)
    model = transformer.gpt_lm(mesh, size="tiny",
                               compute_dtype=jnp.bfloat16,
                               kv_cache_quant=kv_cache_quant)
    sample = jnp.zeros((1, 8), jnp.int32)
    abstract = jax.eval_shape(lambda k: model.init(k, sample),
                              jax.random.key(0))
    variables = jax.jit(
        lambda k: nn.meta.unbox(model.init(k, sample)),
        out_shardings=param_sharding(mesh, abstract))(jax.random.key(0))
    params = variables["params"]
    cache = zero_cache(model, params, num_slots)
    return model, params, cache, num_slots


def _serve_decode_tp_census(kv_cache_quant: str = "none"):
    """THE tensor-parallel decode step: the golden pins the per-step
    collective schedule (attention out-proj + MLP down-proj psums and
    the logits gather land as all-reduce/all-gather here) — NONZERO by
    construction, and a count jump means a program change re-gathers
    the sharded cache or activations every token."""
    from tensorflow_distributed_tpu.models.generate import decode_token

    model, params, cache, num_slots = _serve_tp_model(kv_cache_quant)

    def run(params, cache, tok, pos):
        last, cache = decode_token(model, params, cache, tok, pos)
        ok = jnp.isfinite(last).all(axis=-1)
        return (cache, jnp.argmax(last, axis=-1).astype(jnp.int32),
                ok)

    args = (params, cache, jnp.zeros((num_slots,), jnp.int32),
            jnp.zeros((num_slots,), jnp.int32))
    hlo = jax.jit(run).lower(*args).compile().as_text()
    return {"collectives": _hlo_collectives(hlo),
            "upcasts": census_of(jax.make_jaxpr(run)(*args))["upcasts"]}


def _serve_verify_tp_census():
    """THE tensor-parallel speculative verify — same sharded attend
    over k + 1 positions; its collective schedule must match the
    decode step's shape (per-dispatch, not per-token)."""
    model, params, cache, num_slots = _serve_tp_model()
    k = _VERIFY_K

    def run(params, cache, toks, pos):
        positions = pos[:, None] + jnp.arange(k + 1)[None, :]
        logits, state = model.apply(
            {"params": params, "cache": cache}, toks, decode=True,
            positions=positions, mutable=["cache"])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ok = jnp.isfinite(logits).all(axis=(-1, -2))
        return state["cache"], nxt, ok

    args = (params, cache, jnp.zeros((num_slots, k + 1), jnp.int32),
            jnp.zeros((num_slots,), jnp.int32))
    hlo = jax.jit(run).lower(*args).compile().as_text()
    return {"collectives": _hlo_collectives(hlo),
            "upcasts": census_of(jax.make_jaxpr(run)(*args))["upcasts"]}


PROGRAMS = {
    "gpt_train": lambda: _train_jaxpr("gpt_lm"),
    "moe_train": lambda: _train_jaxpr("moe_lm"),
    "pipelined_train": _pipelined_jaxpr,
    "serve_decode": _serve_decode_jaxpr,
    # Health-instrumented variants (observe.health: cadence 10, taps
    # on the dense family): the budgets pin that device telemetry
    # adds NO collectives next to the plain entries above.
    "gpt_train_health": lambda: _train_jaxpr(
        "gpt_lm", health_every=10, health_taps=True),
    "moe_train_health": lambda: _train_jaxpr(
        "moe_lm", health_every=10),
    "pipelined_train_health": lambda: _pipelined_jaxpr(health_every=10),
    # Explicit overlap grad-sync (parallel/overlap.py): the budgets
    # pin the bucketed reduce-scatter/all-gather schedule per bucket
    # count (see _overlap_jaxpr's constants).
    "gpt_train_overlap": lambda: _overlap_jaxpr("gpt_lm"),
    "moe_train_overlap": lambda: _overlap_jaxpr("moe_lm"),
    # Fast-path serving (speculative verify + int8 KV cache): both pin
    # ZERO collectives — per-token cost work must stay local — and the
    # int8 entry bounds the quantize/dequantize convert count.
    "serve_verify": _serve_verify_jaxpr,
    "serve_decode_int8": lambda: _serve_decode_jaxpr("int8"),
    # Paged KV serving (serve/paging): the paged decode/verify/prefill
    # executables pin ZERO collectives — page-table addressing is a
    # local gather/scatter, never communication.
    "serve_decode_paged": _serve_decode_paged_jaxpr,
    "serve_verify_paged": _serve_verify_paged_jaxpr,
    "serve_prefill_paged": _serve_prefill_paged_jaxpr,
    # Tensor-parallel serving (--serve.mesh-model 2): censused from
    # the compiled HLO (GSPMD inserts these collectives after the
    # jaxpr) — the ONLY entries whose collective budget is NONZERO,
    # pinning the per-step schedule the sharded replica pays.
    "serve_decode_tp": _serve_decode_tp_census,
    "serve_verify_tp": _serve_verify_tp_census,
}


def census(programs: Optional[Sequence[str]] = None
           ) -> Dict[str, Dict[str, Dict[str, int]]]:
    """Trace the named programs (default: all) and return their
    censuses, keyed like the golden file."""
    names = list(programs) if programs else sorted(PROGRAMS)
    out = {}
    for name in names:
        result = PROGRAMS[name]()
        # TP entries return a READY census (collectives counted from
        # compiled HLO — a jaxpr walk cannot see GSPMD's insertions);
        # everything else returns a jaxpr to walk here.
        out[name] = (result if isinstance(result, dict)
                     else census_of(result))
    return out


def load_golden() -> Dict:
    with open(GOLDEN_PATH) as f:
        return json.load(f)


def diff_censuses(golden: Dict, current: Dict,
                  required: Optional[Sequence[str]] = None) -> list:
    """Human-readable drift lines; empty when within budget.

    ``required`` names the programs this run was asked to trace
    (None = a full run, which must cover every golden entry): a
    golden program missing from a FULL run is drift — a deleted or
    renamed PROGRAMS entry must not silently disarm its budget.
    """
    lines = []
    req = set(golden) if required is None else set(required)
    for prog in sorted(set(golden) | set(current)):
        if prog not in golden:
            lines.append(f"{prog}: not in golden (new program? run "
                         f"--update)")
            continue
        if prog not in current:
            if prog in req:
                lines.append(
                    f"{prog}: in the golden but missing from the run "
                    f"(deleted/renamed in PROGRAMS? its budget is no "
                    f"longer checked)")
            continue  # partial run: only compare what was traced
        for section in ("collectives", "upcasts"):
            g = golden[prog].get(section, {})
            c = current[prog].get(section, {})
            for key in sorted(set(g) | set(c)):
                gv, cv = g.get(key, 0), c.get(key, 0)
                if gv != cv:
                    lines.append(
                        f"{prog}: {section}[{key}] {gv} -> {cv}")
    return lines


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.analysis.jaxprcheck",
        description="collective/upcast census of the audited programs "
                    "vs the committed golden budgets")
    parser.add_argument("--update", action="store_true",
                        help="rewrite the golden file with the current "
                             "census (review the diff!)")
    parser.add_argument("--programs", default="",
                        help=f"comma-separated subset of "
                             f"{sorted(PROGRAMS)}")
    args = parser.parse_args(argv)
    _force_cpu_topology()
    names = ([n.strip() for n in args.programs.split(",") if n.strip()]
             if args.programs else None)
    unknown = set(names or ()) - set(PROGRAMS)
    if unknown:
        print(f"jaxprcheck: unknown programs {sorted(unknown)}; have "
              f"{sorted(PROGRAMS)}", file=sys.stderr)
        return 2
    current = census(names)
    for prog, c in current.items():
        print(f"{prog}: collectives={c['collectives']} "
              f"upcasts={c['upcasts']}")
    if args.update:
        if names:
            merged = load_golden() if os.path.exists(GOLDEN_PATH) else {}
            merged.update(current)
            current = dict(sorted(merged.items()))
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            json.dump(current, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"jaxprcheck: wrote {GOLDEN_PATH}")
        return 0
    if not os.path.exists(GOLDEN_PATH):
        print(f"jaxprcheck: no golden at {GOLDEN_PATH}; run with "
              f"--update to create it", file=sys.stderr)
        return 1
    drift = diff_censuses(load_golden(), current, required=names)
    if drift:
        for line in drift:
            print(f"jaxprcheck: DRIFT {line}", file=sys.stderr)
        print("jaxprcheck: census drift — if intentional, regenerate "
              "with --update and commit the diff", file=sys.stderr)
        return 1
    print("jaxprcheck: within budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
