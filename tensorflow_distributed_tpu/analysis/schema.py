"""Telemetry schema contract checker + RECORDS.md drift gate.

Usage::

    python -m tensorflow_distributed_tpu.analysis.schema [paths...]
    python -m tensorflow_distributed_tpu.analysis.schema --update

Runs the telemetry contract rules (``analysis/rules/telemetry.py`` —
producer emit sites and the four cross-process consumers, checked
against ``observe/schemas.py``) over ``paths`` (default: the package),
then gates ``RECORDS.md`` against the registry's rendering: the doc
is GENERATED from the schemas, so a hand edit or a schema change
without regeneration is drift and fails the run (mirroring the census
goldens). ``--update`` rewrites RECORDS.md in place.

Exit status: 0 clean, 1 findings or drift, 2 usage/parse errors.
Pure stdlib + the stdlib-only ``observe/schemas.py`` — no jax.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from tensorflow_distributed_tpu.analysis.lint import (
    PACKAGE_ROOT, lint_paths)
from tensorflow_distributed_tpu.analysis.rules import Finding, telemetry
from tensorflow_distributed_tpu.observe import schemas

RECORDS_MD = os.path.join(os.path.dirname(PACKAGE_ROOT), "RECORDS.md")

_SCHEMA_RULES = frozenset({
    telemetry.RULE_KIND, telemetry.RULE_FIELD,
    telemetry.RULE_REQUIRED, telemetry.RULE_READ,
})


def schema_findings(paths: Sequence[str]) -> List[Finding]:
    """The telemetry-contract subset of a lint run over ``paths``."""
    return [f for f in lint_paths(paths) if f.rule in _SCHEMA_RULES]


def records_md_drift(path: str = RECORDS_MD) -> bool:
    """True when RECORDS.md does not match the registry's rendering."""
    want = schemas.render_records_md()
    try:
        with open(path, "r", encoding="utf-8") as f:
            have = f.read()
    except OSError:
        return True
    return have != want


def update_records_md(path: str = RECORDS_MD) -> str:
    with open(path, "w", encoding="utf-8") as f:
        f.write(schemas.render_records_md())
    return path


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.analysis.schema",
        description="telemetry schema contract: emit sites and "
                    "consumers vs observe/schemas.py, plus the "
                    "RECORDS.md drift gate")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories (default: the "
                             "package itself)")
    parser.add_argument("--update", action="store_true",
                        help="regenerate RECORDS.md from the schema "
                             "registry and exit")
    args = parser.parse_args(argv)
    if args.update:
        print(f"schema: wrote {update_records_md()}")
        return 0
    paths = args.paths or [PACKAGE_ROOT]
    try:
        findings = schema_findings(paths)
    except (OSError, SyntaxError) as e:
        print(f"schema: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.render())
    rc = 0
    if findings:
        print(f"schema: {len(findings)} finding"
              f"{'s' if len(findings) != 1 else ''}", file=sys.stderr)
        rc = 1
    if not args.paths and records_md_drift():
        print("schema: DRIFT — RECORDS.md does not match "
              "observe/schemas.py; regenerate with --update",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
