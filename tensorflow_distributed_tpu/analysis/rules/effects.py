"""effect-under-trace: Python side effects inside traced functions.

A traced function's Python body runs ONCE, at trace time — and again
at unpredictable retrace points (new shapes, cache eviction). A
``print`` there logs once per compile, not once per step (use
``jax.debug.print``); ``time.time()`` measures tracing, not execution,
and freezes a host timestamp into the compiled program; ``input`` /
``breakpoint`` hang remote compiles. All of them "work" on the first
run and then lie.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE = "effect-under-trace"

EFFECT_CALLS = frozenset({
    "print", "input", "breakpoint",
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "datetime.datetime.utcnow",
})

HINTS = {
    "print": "use jax.debug.print for per-execution output",
    "time.time": "trace-time timestamp frozen into the program",
    "time.perf_counter": "measures tracing, not device execution",
    "time.monotonic": "measures tracing, not device execution",
    "time.sleep": "sleeps once per compile, never per step",
}


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        q = qualname(node.func)
        if q not in EFFECT_CALLS:
            continue
        if not ctx.in_traced_context(node):
            continue
        if ctx.suppressed(node, RULE):
            continue
        hint = HINTS.get(q, "runs at trace time, not per step")
        yield ctx.finding(
            node, RULE,
            f"{q}() inside a traced function executes once per "
            f"compile, not once per step ({hint})")
