"""jit-in-loop: building a jitted program inside a loop.

``jax.jit`` / ``pjit`` return a NEW callable with an EMPTY compile
cache each time they are called: constructing one inside a loop throws
the cached executable away every iteration and retraces + recompiles —
seconds of XLA work where the author expected microseconds of
dispatch. (The C++ fast path also keys on the wrapper's identity, so
even a warm persistent cache still pays tracing.) Hoist the ``jax.jit``
call out of the loop; per-iteration shapes that genuinely need
distinct programs should go through an explicit cache
(``functools.lru_cache`` over a static key — see serve/engine.py).

Also flagged: ``jax.named_call``-free tracing entry points that
recompile per call when built in a loop (``jax.make_jaxpr``,
``jax.eval_shape`` are cheap tracers, NOT flagged).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE = "jit-in-loop"

JIT_BUILDERS = frozenset({
    "jax.jit", "jit", "jax.pjit", "pjit",
    "jax.experimental.pjit.pjit", "jax.pmap", "pmap",
})


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and qualname(node.func) in JIT_BUILDERS):
            continue
        if not ctx.in_loop(node):
            continue
        if ctx.in_traced_context(node):
            # jit-under-jit inside a traced loop body is inlined at
            # trace time, not recompiled per runtime iteration.
            continue
        if ctx.suppressed(node, RULE):
            continue
        yield ctx.finding(
            node, RULE,
            f"{qualname(node.func)} constructed inside a loop: a fresh "
            f"wrapper retraces and recompiles every iteration — hoist "
            f"it out of the loop (or cache it under a static key)")
