"""graftcheck rule registry.

Each rule module exposes ``check(ctx: ModuleContext) -> Iterator[
Finding]`` and one or more rule-name constants. Suppress a finding
inline with ``# graftcheck: disable=<rule>[,<rule>] -- <reason>`` on
the flagged statement (or the comment line directly above it).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List

from tensorflow_distributed_tpu.analysis.rules import (
    argvproto, donation, durability, effects, hostsync, jitloop,
    prngreuse, telemetry)
from tensorflow_distributed_tpu.analysis.rules.common import (  # noqa: F401
    Finding, ModuleContext)

# name -> (one-line description, check function). Checks are shared
# per module: hostsync's check emits both of its rule names.
CATALOG: Dict[str, str] = {
    hostsync.RULE_TRACE:
        "device_get/.item()/float()/np.asarray inside a traced "
        "function (trace-time error or silently frozen constant)",
    hostsync.RULE_LOOP:
        "hidden host-device sync in the inner train/decode loops "
        "(blocks dispatch every step)",
    prngreuse.RULE:
        "PRNGKey consumed twice without split/fold_in (identical "
        "randomness)",
    jitloop.RULE:
        "jax.jit/pjit constructed inside a loop (retrace + recompile "
        "per iteration)",
    donation.RULE:
        "buffer read after donate_argnums handed it to XLA "
        "(use-after-free on device)",
    effects.RULE:
        "print/time.time/... under trace (runs per compile, not per "
        "step)",
    telemetry.RULE_KIND:
        "emit of a record kind with no schema in observe/schemas.py",
    telemetry.RULE_FIELD:
        "emit with a field its record schema does not declare",
    telemetry.RULE_REQUIRED:
        "emit provably missing a required schema field",
    telemetry.RULE_READ:
        "telemetry consumer reads a field no producer declares",
    durability.RULE_RAW:
        "raw open(w/a) on a declared cross-process path family "
        "(use utils.atomicio)",
    durability.RULE_FSYNC:
        "os.replace/rename onto a durable path with no fsync "
        "(crash can publish an empty file)",
    argvproto.RULE:
        "parent-constructed child flag that config.py does not parse",
}

CHECKS: List[Callable[[ModuleContext], Iterator[Finding]]] = [
    hostsync.check,
    prngreuse.check,
    jitloop.check,
    donation.check,
    effects.check,
    telemetry.check,
    durability.check,
    argvproto.check,
]


def check_module(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    for check in CHECKS:
        findings.extend(check(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
