"""Durability lint — cross-process files go through utils/atomicio.

``utils/atomicio.py`` declares the path families other processes
read (export snapshots, inboxes, journals, trace files, the
device-mask, checkpoint manifests, …) and owns the tmp+fsync+rename
idiom. Two rules hold the tree to it:

* ``raw-write-to-shared-path`` — a direct ``open(path, "w"|"a")``
  whose path expression matches a declared family, outside
  utils/atomicio.py. Use ``atomic_write_json`` /
  ``atomic_write_jsonl`` / ``durable_append`` instead — or suppress
  with a reason when raw is the point (flightrec's straight-through
  postmortem dump; the journal's persistent hot-path handle).
* ``missing-fsync-on-durable-path`` — an ``os.replace``/``os.rename``
  onto a family path in a function with no ``os.fsync``: the rename
  is atomic but the CONTENTS may still be in the page cache, so a
  crash can publish an empty complete-looking file.

Path matching is syntactic on purpose (source text of the path
expression, plus one resolve hop through a local ``name = <expr>``
assignment): conservative, jax-free, and cheap.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, FuncInfo, ModuleContext, qualname)

RULE_RAW = "raw-write-to-shared-path"
RULE_FSYNC = "missing-fsync-on-durable-path"

_WRITE_MODES = re.compile(r"[wax]|r\+")


def _families():
    from tensorflow_distributed_tpu.utils.atomicio import PATH_FAMILIES
    return PATH_FAMILIES


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _src(ctx: ModuleContext, node: ast.AST) -> str:
    try:
        return ast.get_source_segment(ctx.source, node) or ""
    except Exception:
        return ""


def _resolved_srcs(ctx: ModuleContext, expr: ast.AST) -> List[str]:
    """Source text of ``expr``, plus up to three hops through local
    ``name = <rhs>`` assignments in the enclosing function (module
    level otherwise) — enough to see through ``tmp = path + ".tmp"``."""
    srcs = [_src(ctx, expr)]
    fn = ctx.func_of(expr)
    scope_root: ast.AST = fn.node if fn is not None else ctx.tree
    cur = expr
    for _ in range(3):
        if not isinstance(cur, ast.Name):
            break
        target_rhs: Optional[ast.AST] = None
        for node in ast.walk(scope_root):
            if isinstance(node, ast.Assign) \
                    and getattr(node, "lineno", 0) <= getattr(
                        cur, "lineno", 0):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == cur.id:
                        target_rhs = node.value
        if target_rhs is None:
            break
        srcs.append(_src(ctx, target_rhs))
        cur = target_rhs
    return srcs


def _family_of(ctx: ModuleContext, expr: ast.AST) -> Optional[str]:
    npath = _norm(ctx.path)
    srcs = _resolved_srcs(ctx, expr)
    for family, file_re, expr_re in _families():
        if file_re and not re.search(file_re, npath):
            continue
        if any(re.search(expr_re, s, re.IGNORECASE) for s in srcs if s):
            return family
    return None


def _has_fsync(ctx: ModuleContext, around: ast.AST) -> bool:
    fn = ctx.func_of(around)
    root: ast.AST = fn.node if fn is not None else ctx.tree
    for node in ast.walk(root):
        if isinstance(node, ast.Call) \
                and qualname(node.func) == "os.fsync":
            return True
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if _norm(ctx.path).endswith("utils/atomicio.py"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = qualname(node.func)
        if callee == "open" and node.args:
            mode = ""
            if len(node.args) >= 2 and isinstance(
                    node.args[1], ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if not _WRITE_MODES.search(mode):
                continue
            family = _family_of(ctx, node.args[0])
            if family is not None and not ctx.suppressed(node, RULE_RAW):
                yield ctx.finding(
                    node, RULE_RAW,
                    f"raw open(..., {mode!r}) on '{family}' path — use "
                    f"utils.atomicio (atomic_write_json / "
                    f"durable_append)")
        elif callee in ("os.replace", "os.rename") \
                and len(node.args) >= 2:
            family = _family_of(ctx, node.args[1])
            if family is None:
                continue
            if _has_fsync(ctx, node):
                continue
            if not ctx.suppressed(node, RULE_FSYNC):
                yield ctx.finding(
                    node, RULE_FSYNC,
                    f"{callee} onto '{family}' path without fsync — a "
                    f"crash can publish an empty file; use "
                    f"utils.atomicio.atomic_write_json")
