"""use-after-donation: reading a buffer after donating it.

``donate_argnums`` hands an argument's device buffers to XLA for
in-place reuse: after the call, the Python object still exists but its
buffers are dead. Touching it again is at best a
``RuntimeError: invalid buffer``, at worst (through an executable that
aliased the pages — the PR 2 ``launder_buffers`` SIGSEGV) silent
corruption or a crash deep inside the runtime.

Detection is name-based and intra-module:

- a variable bound from ``jax.jit(..., donate_argnums=...)`` donates
  those positional args at every call site;
- a variable bound from one of the repo's donating step factories
  (``DONATING_FACTORIES`` below — all donate arg 0, the TrainState)
  donates arg 0, unless the call passes ``donate=False`` or
  ``jit=False`` (the raw, undonated body);
- at each call site, the donated NAME is tracked through the enclosing
  function in statement order: any later read before a rebinding is a
  finding. A donating call inside a loop whose donated name is never
  rebound in that loop donates the same dead buffer again on the next
  iteration — also a finding.

Donor bindings are flow-sensitive per scope: a function inherits the
donor names bound in its lexically enclosing scopes, its parameters
shadow them, and rebinding a name from a non-donating expression
clears its donor status — so a scope that uses ``step`` for an
unrelated callable is not polluted by another scope's
``step = make_train_step(...)``.

The safe idiom is the same-statement rebind the train loop uses:
``state, metrics = step_fn(state, batch)``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE = "use-after-donation"

# The repo's step builders that return a donating jitted callable
# (audited in this PR): every one donates argnum 0 — the TrainState —
# by default. Keyed by bare name so both plain and module-qualified
# imports match.
DONATING_FACTORIES = {
    "make_train_step": (0,),
    "make_multi_step": (0,),
    "make_local_sgd_train_step": (0,),
    "make_1f1b_train_step": (0,),
}


def _donated_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions for a binding RHS, or None when not donating."""
    q = qualname(call.func)
    base = q.rsplit(".", 1)[-1]
    if q in ("jax.jit", "jit", "jax.pjit", "pjit"):
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    nums = tuple(e.value for e in v.elts
                                 if isinstance(e, ast.Constant))
                    return nums or None
        return None
    if base in DONATING_FACTORIES:
        for kw in call.keywords:
            if kw.arg in ("donate", "jit") and isinstance(
                    kw.value, ast.Constant) and kw.value.value is False:
                return None
        return DONATING_FACTORIES[base]
    return None


def _own_donor_bindings(scope: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """Donor names bound by Assigns DIRECTLY in ``scope`` (nested
    function bodies excluded) — the seed a nested scope inherits."""
    out: Dict[str, Tuple[int, ...]] = {}
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            nums = _donated_argnums(node.value)
            if nums is not None:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out[target.id] = nums
        stack.extend(ast.iter_child_nodes(node))
    return out


def _inherited_donors(ctx: ModuleContext, scope: ast.AST
                      ) -> Dict[str, Tuple[int, ...]]:
    """Donor bindings visible to ``scope`` from its lexically
    enclosing scopes (module outward-in, so inner bindings win),
    minus names shadowed by the scope's own parameters."""
    chain: List[ast.AST] = [ctx.tree]
    fi = next((f for f in ctx.functions if f.node is scope), None)
    if fi is not None:
        enclosing = []
        cur = fi.scope
        while cur is not None:
            enclosing.append(cur.node)
            cur = cur.scope
        chain.extend(reversed(enclosing))
    donors: Dict[str, Tuple[int, ...]] = {}
    for s in chain:
        donors.update(_own_donor_bindings(s))
    args = getattr(scope, "args", None)
    if args is not None:
        for a in (args.args + args.posonlyargs + args.kwonlyargs
                  + [x for x in (args.vararg, args.kwarg) if x]):
            donors.pop(a.arg, None)
    return donors


def _enclosing_loop(ctx: ModuleContext, node: ast.AST) -> Optional[ast.AST]:
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        cur = ctx.parent(cur)
    return None


def check(ctx: ModuleContext) -> Iterator[Finding]:
    scopes: List[ast.AST] = [ctx.tree] + [fi.node for fi in ctx.functions
                                          if not isinstance(fi.node,
                                                            ast.Lambda)]
    for scope in scopes:
        yield from _check_scope(ctx, scope)


def _check_scope(ctx: ModuleContext, scope: ast.AST) -> Iterator[Finding]:
    # Ordered traversal in EXECUTION order, not source order: an
    # Assign evaluates its value (loads, then the donation) before
    # binding its targets, so the safe same-statement rebind
    # ``state, m = step_fn(state, batch)`` clears the donation it
    # just recorded. ``donors`` is flow-sensitive: it starts from the
    # bindings inherited from enclosing scopes and is updated as
    # Assigns execute — a rebind from a non-donating expression clears
    # donor status, so shared names don't cross-contaminate.
    donors: Dict[str, Tuple[int, ...]] = _inherited_donors(ctx, scope)
    donated: Dict[str, ast.AST] = {}   # name -> donating call node

    def visit(node: ast.AST) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate scope, separate pass
        if isinstance(node, ast.Assign):
            yield from visit(node.value)
            nums = (_donated_argnums(node.value)
                    if isinstance(node.value, ast.Call) else None)
            for target in node.targets:
                yield from visit(target)
                for n in ast.walk(target):
                    if isinstance(n, ast.Name):
                        if nums is not None and n is target:
                            donors[n.id] = nums
                        else:
                            donors.pop(n.id, None)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                yield from visit(node.value)
            yield from visit(node.target)
            if isinstance(node.target, ast.Name):
                donors.pop(node.target.id, None)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load) and node.id in donated:
                call = donated[node.id]
                if not ctx.suppressed(node, RULE):
                    # Pop only on an EMITTED finding (one per
                    # donation, no cascades); a suppressed read must
                    # not consume the budget and hide later real ones.
                    donated.pop(node.id)
                    yield ctx.finding(
                        node, RULE,
                        f"{node.id!r} read after being donated at line "
                        f"{call.lineno} — its buffers were handed to "
                        f"XLA and may already be reused")
            elif isinstance(node.ctx, (ast.Store, ast.Del)):
                donated.pop(node.id, None)
            return
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in donors):
            for child in ast.iter_child_nodes(node):
                yield from visit(child)   # argument loads come first
            for i in donors[node.func.id]:
                if i < len(node.args) and isinstance(node.args[i],
                                                     ast.Name):
                    name = node.args[i].id
                    donated[name] = node
                    loop = _enclosing_loop(ctx, node)
                    if loop is not None \
                            and not _stored_in(ctx, loop, name) \
                            and not ctx.suppressed(node, RULE):
                        yield ctx.finding(
                            node, RULE,
                            f"{name!r} is donated here but never "
                            f"rebound in the enclosing loop — the next "
                            f"iteration donates a dead buffer")
            return
        for child in ast.iter_child_nodes(node):
            yield from visit(child)

    body = scope.body if isinstance(scope.body, list) else [scope.body]
    for stmt in body:
        yield from visit(stmt)


def _stored_in(ctx: ModuleContext, loop: ast.AST, name: str) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)) and node.id == name:
            return True
    return False
