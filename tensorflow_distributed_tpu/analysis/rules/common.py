"""Shared AST scaffolding for graftcheck rules.

Pure stdlib — the lint tier must run without importing jax (it lints
the code that imports jax; it must never pay, or require, a jax
initialization itself). Everything here is deliberately syntactic and
conservative: name resolution walks lexical scopes only, call graphs
are intra-module, and unresolvable constructs are treated as "not
proven hazardous" rather than guessed at — a linter that cries wolf
gets suppressed wholesale and then catches nothing.

Core concepts:

- **traced context**: code that executes under a jax trace. A function
  is traced when it is decorated with jit/pjit (bare or via partial),
  syntactically passed to a tracing entry point (``jax.jit``,
  ``shard_map``, ``lax.scan``, ``jax.grad``, ...), referenced from the
  body of a traced function (intra-module call graph), or lexically
  nested inside one.
- **hot context**: host-side code inside the inner train/decode loops.
  A node is hot when it sits lexically inside a ``for``/``while`` loop
  of a hot module, or inside a function transitively referenced from
  such a loop (``cadence``/``_inspect`` in train/loop.py are the
  canonical cases: no loop of their own, called every step).
- **suppressions**: ``# graftcheck: disable=<rule>[,<rule>...]``
  anywhere on the flagged statement's lines or on the comment line
  directly above it; ``-- reason`` text after the rule list is
  encouraged and ignored by the parser.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")

# Entry points whose function-valued arguments run under trace.
TRACING_CALLS = frozenset({
    "jax.jit", "jit", "jax.pjit", "pjit", "jax.make_jaxpr", "make_jaxpr",
    "jax.eval_shape", "eval_shape",
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "lax.scan", "jax.lax.cond", "lax.cond",
    "jax.lax.while_loop", "lax.while_loop", "jax.lax.fori_loop",
    "lax.fori_loop", "jax.lax.map", "lax.map", "jax.lax.associative_scan",
    "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jvp",
    "jax.linearize", "jax.linear_transpose",
    "jax.checkpoint", "jax.remat", "jax.custom_vjp", "jax.custom_jvp",
})

# Decorators that make the decorated function a traced root.
JIT_DECORATORS = frozenset({"jax.jit", "jit", "jax.pjit", "pjit"})


def qualname(node: ast.AST) -> str:
    """Dotted name of an expression (``jax.lax.scan``), or "" when the
    expression is not a plain attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


class FuncInfo:
    """One function/lambda definition with its lexical scope link."""

    __slots__ = ("node", "name", "scope", "traced", "hot", "refs",
                 "loop_refs")

    def __init__(self, node: ast.AST, name: str,
                 scope: Optional["FuncInfo"]):
        self.node = node
        self.name = name
        self.scope = scope          # enclosing FuncInfo (None = module)
        self.traced = False
        self.hot = False
        self.refs: Set[str] = set()       # names referenced in body
        self.loop_refs: Set[str] = set()  # ... within loop subtrees

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"FuncInfo({self.name!r}, traced={self.traced}, "
                f"hot={self.hot})")


def _own_body_walk(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions or lambdas (those are their own FuncInfos)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class ModuleContext:
    """Parsed module + the analyses every rule shares."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parent: Dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parent[id(child)] = node
        self.functions: List[FuncInfo] = []
        self._fn_by_node: Dict[int, FuncInfo] = {}
        self._collect_functions()
        self._collect_refs()
        self._mark_traced()
        self.suppressions = self._collect_suppressions()

    # --- structure -----------------------------------------------------

    def _collect_functions(self) -> None:
        def visit(node: ast.AST, scope: Optional[FuncInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    fi = FuncInfo(child, child.name, scope)
                    self.functions.append(fi)
                    self._fn_by_node[id(child)] = fi
                    visit(child, fi)
                elif isinstance(child, ast.Lambda):
                    fi = FuncInfo(child, "<lambda>", scope)
                    self.functions.append(fi)
                    self._fn_by_node[id(child)] = fi
                    visit(child, fi)
                elif isinstance(child, ast.ClassDef):
                    # Methods resolve through the class to the
                    # enclosing function/module scope (graftcheck has
                    # no instance-attribute call graph).
                    visit(child, scope)
                else:
                    visit(child, scope)

        visit(self.tree, None)

    def _collect_refs(self) -> None:
        for fi in self.functions:
            for node in _own_body_walk(fi.node):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    fi.refs.add(node.id)
                if isinstance(node, (ast.For, ast.While)):
                    for sub in ast.walk(node):
                        if (isinstance(sub, ast.Name)
                                and isinstance(sub.ctx, ast.Load)):
                            fi.loop_refs.add(sub.id)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def func_of(self, node: ast.AST) -> Optional[FuncInfo]:
        """The innermost function/lambda containing ``node``."""
        cur = self.parent(node)
        while cur is not None:
            fi = self._fn_by_node.get(id(cur))
            if fi is not None:
                return fi
            cur = self.parent(cur)
        return None

    def resolve(self, name: str, scope: Optional[FuncInfo]
                ) -> Optional[FuncInfo]:
        """Lexical-scope name lookup: functions defined in ``scope``,
        then outward to module level. First match wins."""
        while True:
            for fi in self.functions:
                if fi.name == name and fi.scope is scope:
                    return fi
            if scope is None:
                return None
            scope = scope.scope

    # --- traced contexts -----------------------------------------------

    def _decorator_is_jit(self, dec: ast.AST) -> bool:
        if qualname(dec) in JIT_DECORATORS:
            return True
        if isinstance(dec, ast.Call):
            fq = qualname(dec.func)
            if fq in JIT_DECORATORS:
                return True
            if fq in ("partial", "functools.partial") and dec.args:
                return qualname(dec.args[0]) in JIT_DECORATORS
        return False

    def _fn_args_of_call(self, call: ast.Call) -> Iterator[ast.AST]:
        """Expressions in a tracing call that may denote the traced
        function: positional/keyword args directly, and through one
        ``partial(...)`` wrapper."""
        args = list(call.args) + [kw.value for kw in call.keywords]
        for a in args:
            yield a
            if isinstance(a, ast.Call) and qualname(a.func) in (
                    "partial", "functools.partial"):
                yield from a.args

    def _mark_traced(self) -> None:
        # Roots: jit decorators and arguments of tracing entry points.
        for fi in self.functions:
            node = fi.node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._decorator_is_jit(d)
                       for d in node.decorator_list):
                    fi.traced = True
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and qualname(node.func) in TRACING_CALLS):
                continue
            caller = self.func_of(node)
            for arg in self._fn_args_of_call(node):
                if isinstance(arg, ast.Lambda):
                    fi = self._fn_by_node.get(id(arg))
                    if fi is not None:
                        fi.traced = True
                elif isinstance(arg, ast.Name):
                    fi = self.resolve(arg.id, caller)
                    if fi is not None:
                        fi.traced = True
        # Propagate: functions referenced from a traced body, and
        # functions nested inside a traced function, are traced.
        changed = True
        while changed:
            changed = False
            for fi in self.functions:
                if fi.traced:
                    continue
                if fi.scope is not None and fi.scope.traced:
                    fi.traced = True
                    changed = True
                    continue
                for other in self.functions:
                    if other.traced and fi.name in other.refs \
                            and self.resolve(fi.name, other) is fi:
                        fi.traced = True
                        changed = True
                        break

    def in_traced_context(self, node: ast.AST) -> bool:
        fi = self.func_of(node)
        while fi is not None:
            if fi.traced:
                return True
            fi = fi.scope
        return False

    # --- hot contexts ---------------------------------------------------

    def in_loop(self, node: ast.AST) -> bool:
        """Is ``node`` lexically inside a for/while loop (stopping at
        the enclosing function boundary)?"""
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While)):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            cur = self.parent(cur)
        return False

    def mark_hot(self) -> None:
        """Flag functions transitively referenced from loop bodies
        (the host-side per-step helpers of the train/decode loops),
        plus every METHOD: the intra-module resolver tracks plain
        names only, so ``self.engine.step()`` inside a scheduler loop
        can't be followed — in a hot module, assume any method may be
        a per-step entry point (the serve engine's are) rather than
        silently exempting them."""
        for fi in self.functions:
            if isinstance(self.parent(fi.node), ast.ClassDef) \
                    and not fi.traced:
                fi.hot = True
            for name in fi.loop_refs:
                target = self.resolve(name, fi)
                if target is not None and not target.traced:
                    target.hot = True
        # Module-level loops (scripts) reference module-level functions.
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.For, ast.While)) \
                    and self.func_of(node) is None:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Load):
                        target = self.resolve(sub.id, None)
                        if target is not None and not target.traced:
                            target.hot = True
        changed = True
        while changed:
            changed = False
            for fi in self.functions:
                if fi.hot or fi.traced:
                    continue
                if fi.scope is not None and fi.scope.hot:
                    fi.hot = True
                    changed = True
                    continue
                for other in self.functions:
                    if other.hot and fi.name in other.refs \
                            and self.resolve(fi.name, other) is fi:
                        fi.hot = True
                        changed = True
                        break

    def in_hot_context(self, node: ast.AST) -> bool:
        """Inside a loop, or inside a function reachable from one."""
        if self.in_loop(node):
            return True
        fi = self.func_of(node)
        while fi is not None:
            if fi.hot:
                return True
            fi = fi.scope
        return False

    # --- suppressions ---------------------------------------------------

    def _collect_suppressions(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                out[i] = rules
        return out

    def suppressed(self, node: ast.AST, rule: str) -> bool:
        """Suppressed when any line of the flagged STATEMENT — or the
        contiguous comment block directly above it — carries the rule
        (or "all"). Statement-level on purpose: a finding on an inner
        expression of a multi-line call is silenced by annotating the
        statement, like every other line-comment linter."""
        stmt: ast.AST = node
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parent(cur)
        if cur is not None:
            stmt = cur
        first = getattr(stmt, "lineno", getattr(node, "lineno", 0))
        last = getattr(stmt, "end_lineno", first) or first

        def hit(ln: int) -> bool:
            rules = self.suppressions.get(ln)
            return bool(rules and (rule in rules or "all" in rules))

        if any(hit(ln) for ln in range(first, last + 1)):
            return True
        # Walk the comment block above (a trailing suppression on a
        # CODE line above belongs to that line, not to this statement).
        ln = first - 1
        while ln >= 1 and self.lines[ln - 1].strip().startswith("#"):
            if hit(ln):
                return True
            ln -= 1
        return False

    def finding(self, node: ast.AST, rule: str, message: str
                ) -> Finding:
        return Finding(self.path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), rule, message)


def call_qual(node: ast.AST) -> Tuple[Optional[ast.Call], str]:
    """(call node, dotted callee) when ``node`` is a Call, else
    (None, "")."""
    if isinstance(node, ast.Call):
        return node, qualname(node.func)
    return None, ""
