"""Telemetry schema contract — producer and consumer checks.

The cross-process telemetry contract lives in ``observe/schemas.py``:
every ``event=`` record kind with its declared field table. Two AST
passes hold the tree to it:

* **Producers** — every ``emit("kind", field=...)`` call (and every
  ``{"event": "kind", ...}`` dict literal, which covers the stdout
  run log and the supervisor's journal records) is checked against
  the kind's schema: undeclared kind, undeclared field, or a missing
  required field (only provable when the call has no ``**`` splat)
  is a finding. ``recovery`` records additionally get their literal
  ``kind=`` discriminator checked against ``RECOVERY_KINDS``.
* **Consumers** — in the four cross-process readers
  (``observe/report.py``, ``observe/regress.py``,
  ``observe/fleetview.py``, ``fleet/router.py``), every literal
  ``rec.get("field")`` / ``rec["field"]`` read must name a field some
  producer declares (any kind, the common tags, the nested payload
  shapes, or an open family pattern) — a consumer can never read a
  field no producer can write.

Dynamic emits (``emit(kind_var, **fields)``) are invisible to the
static pass on purpose; ``MetricsRegistry(validate=True)`` (armed by
``--check``) covers them at runtime with the same tables.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Tuple

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE_KIND = "undeclared-record-kind"
RULE_FIELD = "undeclared-record-field"
RULE_REQUIRED = "missing-required-field"
RULE_READ = "undeclared-consumer-read"

_EMIT_NAMES = frozenset({"emit", "emit_event"})

#: The cross-process readers the consumer pass holds to the contract.
CONSUMER_SUFFIXES = ("observe/report.py", "observe/regress.py",
                     "observe/fleetview.py", "fleet/router.py")


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _schemas():
    # Lazy: keeps rule registration import-light and avoids any
    # analysis <-> observe import cycle at module load.
    from tensorflow_distributed_tpu.observe import schemas
    return schemas


def _literal_kwargs(call: ast.Call) -> Tuple[List[Tuple[str, ast.AST]], bool]:
    literal: List[Tuple[str, ast.AST]] = []
    splat = False
    for kw in call.keywords:
        if kw.arg is None:
            splat = True
        else:
            literal.append((kw.arg, kw.value))
    return literal, splat


def _check_fields(ctx: ModuleContext, node: ast.AST, kind: str,
                  fields: List[Tuple[str, ast.AST]], splat: bool
                  ) -> Iterator[Finding]:
    sch = _schemas()
    schema = sch.schema_for(kind)
    if schema is None:
        if not ctx.suppressed(node, RULE_KIND):
            yield ctx.finding(
                node, RULE_KIND,
                f"record kind '{kind}' has no schema in "
                f"observe/schemas.py")
        return
    allowed = sch.allowed_fields(kind)
    for name, value in fields:
        if name in allowed or schema.open_fields \
                or sch.matches_pattern(kind, name):
            continue
        if not ctx.suppressed(node, RULE_FIELD):
            yield ctx.finding(
                node, RULE_FIELD,
                f"'{kind}' record field '{name}' is not declared in "
                f"its schema")
    if not splat:
        present = {name for name, _ in fields}
        tag_names = {f.name for f in sch.COMMON_TAGS}
        for f in schema.fields:
            if f.required and f.name not in present \
                    and f.name not in tag_names:
                if not ctx.suppressed(node, RULE_REQUIRED):
                    yield ctx.finding(
                        node, RULE_REQUIRED,
                        f"'{kind}' record is missing required field "
                        f"'{f.name}'")
    if kind == "recovery":
        for name, value in fields:
            if name == "kind" and isinstance(value, ast.Constant) \
                    and isinstance(value.value, str) \
                    and value.value not in sch.RECOVERY_KINDS:
                if not ctx.suppressed(node, RULE_KIND):
                    yield ctx.finding(
                        node, RULE_KIND,
                        f"recovery kind '{value.value}' is not in "
                        f"observe/schemas.RECOVERY_KINDS")


def _check_producers(ctx: ModuleContext) -> Iterator[Finding]:
    if _norm(ctx.path).endswith("observe/schemas.py"):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            callee = qualname(node.func).rsplit(".", 1)[-1]
            if callee not in _EMIT_NAMES and callee != "_emit":
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # dynamic kind: runtime validation's job
            literal, splat = _literal_kwargs(node)
            yield from _check_fields(ctx, node, node.args[0].value,
                                     literal, splat)
        elif isinstance(node, ast.Dict):
            kind: Optional[str] = None
            fields: List[Tuple[str, ast.AST]] = []
            splat = False
            for key, value in zip(node.keys, node.values):
                if key is None:
                    splat = True
                    continue
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue
                if key.value == "event":
                    if isinstance(value, ast.Constant) \
                            and isinstance(value.value, str):
                        kind = value.value
                else:
                    fields.append((key.value, value))
            if kind is not None:
                yield from _check_fields(ctx, node, kind, fields, splat)


def _check_consumers(ctx: ModuleContext) -> Iterator[Finding]:
    npath = _norm(ctx.path)
    if not npath.endswith(CONSUMER_SUFFIXES):
        return
    sch = _schemas()
    universe = sch.consumer_universe()
    patterns = sch.consumer_patterns()

    def readable(name: str) -> bool:
        return name in universe or any(
            re.fullmatch(p, name) for p in patterns)

    for node in ast.walk(ctx.tree):
        name: Optional[str] = None
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            recv = qualname(node.func.value)
            if recv.startswith("os.environ"):
                continue
            name = node.args[0].value
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            name = node.slice.value
        if name is None or readable(name):
            continue
        if not ctx.suppressed(node, RULE_READ):
            yield ctx.finding(
                node, RULE_READ,
                f"consumer reads field '{name}' that no producer "
                f"declares (observe/schemas.py)")


def check(ctx: ModuleContext) -> Iterator[Finding]:
    yield from _check_producers(ctx)
    yield from _check_consumers(ctx)
