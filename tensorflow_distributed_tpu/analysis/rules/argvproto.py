"""Argv protocol contract — parent-written flags must parse.

The supervisor (``resilience/supervisor.py``) and the fleet
controller (``fleet/controller.py``) construct child argv: mesh
rewrites (``--mesh.data``), resume plumbing (``--resume``,
``--checkpoint-dir``), replica wiring (``--serve.inbox``,
``--observe.export-path``, …). The child parses them with the ONE
flag namespace ``config.py`` derives from ``TrainConfig``
(``config.known_flags()``). A flag the parent writes but the child
does not parse is a crash loop at restart time — exactly the
ps/worker-style implicit protocol this repo makes explicit.

One rule, ``unparsed-child-flag``:

* In the two argv-constructing modules, every ``--flag`` string
  literal must be in ``config.known_flags()`` — except arguments to
  ``add_argument`` (the module's OWN parser) and f-string prefixes
  (``f"--mesh.{name}"``), which are checked as namespace prefixes.
* Everywhere, ``config.child_flag("dotted_path")`` calls — the
  blessed spelling helper both parents share — get their argument
  verified the same way.

Imports config lazily; config.py is pure stdlib, so the pass stays
jax-free.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE = "unparsed-child-flag"

#: Modules that construct child argv — every literal flag in them is
#: part of the parent->child protocol.
ARGV_SUFFIXES = ("resilience/supervisor.py", "fleet/controller.py")

_FLAG_RE = re.compile(r"--[a-z][a-z0-9]*([.\-][a-z0-9]+)*")


def _known_flags():
    from tensorflow_distributed_tpu import config
    return config.known_flags()


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _in_add_argument(ctx: ModuleContext, node: ast.AST) -> bool:
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, ast.Call) \
                and qualname(cur.func).endswith("add_argument"):
            return True
        if isinstance(cur, ast.stmt):
            return False
        cur = ctx.parent(cur)
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    npath = _norm(ctx.path)
    argv_module = npath.endswith(ARGV_SUFFIXES)
    known = None
    for node in ast.walk(ctx.tree):
        # The blessed helper, checked in EVERY module.
        if isinstance(node, ast.Call) \
                and qualname(node.func).rsplit(".", 1)[-1] == "child_flag" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            if known is None:
                known = _known_flags()
            flag = "--" + node.args[0].value.replace("_", "-")
            if flag not in known and not ctx.suppressed(node, RULE):
                yield ctx.finding(
                    node, RULE,
                    f"child_flag({node.args[0].value!r}) -> '{flag}' "
                    f"is not parsed by config.py")
            continue
        if not argv_module:
            continue
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("--"):
            parent = ctx.parent(node)
            joined = isinstance(parent, ast.JoinedStr)
            if not joined and not _FLAG_RE.fullmatch(node.value):
                continue
            if joined and not re.fullmatch(r"--[a-z][a-z0-9.\-]*",
                                           node.value):
                continue
            if _in_add_argument(ctx, node):
                continue
            if known is None:
                known = _known_flags()
            if joined:
                # f"--mesh.{name}": the literal prefix must open a real
                # flag namespace.
                if any(f.startswith(node.value) for f in known):
                    continue
            elif node.value in known:
                continue
            if not ctx.suppressed(node, RULE):
                yield ctx.finding(
                    node, RULE,
                    f"flag literal '{node.value}' is not parsed by "
                    f"config.py (child would reject it)")
