"""host-sync rules: hidden host↔device synchronization.

Two contexts, two rules:

- ``host-sync-under-trace``: ``jax.device_get`` / ``.item()`` /
  ``float()``/``int()`` / ``np.asarray``/``np.array`` inside a traced
  function. On a tracer these either raise at trace time
  (``ConcretizationTypeError``) or silently freeze a value into the
  compiled program — both are bugs, and the frozen-constant kind
  compiles fine and corrupts quietly.

- ``host-sync-in-loop``: the same device reads in the HOST-side inner
  train/decode loops of the hot modules (``HOT_MODULES`` below). Each
  one blocks the dispatch pipeline on the device stream — the classic
  steps/sec cliff that profiles as "device idle". Intentional syncs
  (cadence-gated logging, eval, checkpoints, the final report) carry a
  ``# graftcheck: disable=host-sync-in-loop -- <why>`` suppression.

``float()``/``int()`` are only flagged under trace (where any
non-static argument is a hazard); in host loops they are ordinary
scalar math and the unambiguous primitives (``jax.device_get``,
``.item()``, ``np.asarray`` on device values) carry the signal.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE_TRACE = "host-sync-under-trace"
RULE_LOOP = "host-sync-in-loop"

# Modules whose for/while loops ARE the hot path (the inner train and
# decode loops). Everywhere else, host-side device reads are assumed
# cold (data loading, reporting, benchmarks' own timing harnesses).
HOT_MODULES = (
    "train/loop.py",
    "train/multistep.py",
    "serve/engine.py",
    "serve/scheduler.py",
    "serve/run.py",
)

DEVICE_GET_CALLS = frozenset({
    "jax.device_get", "jax.block_until_ready",
})
NP_MATERIALIZE_CALLS = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
})


def _is_hot_module(path: str) -> bool:
    # Separator-anchored: "observe/run.py" must not match the
    # "serve/run.py" suffix.
    p = path.replace("\\", "/")
    return any(p == suffix or p.endswith("/" + suffix)
               for suffix in HOT_MODULES)


def _literal(node: ast.AST) -> bool:
    return isinstance(node, (ast.Constant, ast.JoinedStr))


def check(ctx: ModuleContext) -> Iterator[Finding]:
    hot = _is_hot_module(ctx.path)
    if hot:
        ctx.mark_hot()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        q = qualname(node.func)
        name = ""
        if q in DEVICE_GET_CALLS:
            name = q
        elif q in NP_MATERIALIZE_CALLS:
            name = q
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            name = ".item()"
        traced = ctx.in_traced_context(node)
        if traced:
            if not name and q in ("float", "int") and len(node.args) == 1 \
                    and not node.keywords and not _literal(node.args[0]):
                name = f"{q}()"
            if name and not ctx.suppressed(node, RULE_TRACE):
                yield ctx.finding(
                    node, RULE_TRACE,
                    f"{name} inside a traced function: materializes a "
                    f"tracer (trace-time error) or freezes a host value "
                    f"into the compiled program")
            continue
        if hot and name and ctx.in_hot_context(node):
            if not ctx.suppressed(node, RULE_LOOP):
                yield ctx.finding(
                    node, RULE_LOOP,
                    f"{name} in the inner train/decode loop blocks the "
                    f"host on the device stream every step; gate it on "
                    f"a cadence or move it off the hot path")
