"""prng-reuse: the same PRNGKey consumed by more than one random op.

JAX keys are not stateful seeds: feeding one key to two samplers gives
correlated (usually identical) draws — silent statistical corruption,
no error anywhere. The contract is one consumption per key; every
further draw needs a ``jax.random.split`` / ``fold_in`` derivation.

The analysis is per-function and straight-line: track names bound from
key-producing expressions (``jax.random.key``/``PRNGKey``/``split``/
``fold_in`` and the repo's ``prng.*`` helpers), count consumptions
(the name fed to a ``jax.random`` sampler, or passed as a ``key=`` /
``rng=`` / ``rngs=`` argument), and reset the count when the name is
rebound. Loop bodies are visited twice — simulating the second
iteration — so the canonical bug (one key drawn from on every
iteration) counts as reuse unless the key is re-derived inside the
loop. Control flow is otherwise approximated linearly — both branches
of an ``if`` count, which can over-report mutually-exclusive
consumptions; suppress those with
``# graftcheck: disable=prng-reuse -- <why>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator

from tensorflow_distributed_tpu.analysis.rules.common import (
    Finding, ModuleContext, qualname)

RULE = "prng-reuse"

# jax.random.* that DERIVE keys rather than consume them.
DERIVERS = frozenset({
    "key", "PRNGKey", "split", "fold_in", "wrap_key_data", "key_data",
    "clone", "key_impl",
})
KEY_PRODUCER_CALLS = frozenset({
    "jax.random.key", "jax.random.PRNGKey", "random.key",
    "random.PRNGKey", "jax.random.split", "random.split",
    "jax.random.fold_in", "random.fold_in",
    "prng.root_key", "prng.init_key", "prng.step_key",
    "root_key", "init_key", "step_key",
})
KEY_KEYWORDS = frozenset({"key", "rng", "rngs", "dropout_key", "prng"})


def _names_in(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id


def _is_random_consumer(q: str) -> bool:
    """A ``jax.random.<sampler>`` (or bare ``random.<sampler>``) call
    that consumes its key argument."""
    for prefix in ("jax.random.", "random."):
        if q.startswith(prefix):
            return q[len(prefix):] not in DERIVERS
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for fi in ctx.functions:
        if isinstance(fi.node, ast.Lambda):
            continue
        yield from _check_function(ctx, fi.node)


def _check_function(ctx: ModuleContext, fn: ast.AST) -> Iterator[Finding]:
    # name -> consumption count since last (re)binding; only names we
    # SAW bound from a key producer are tracked, so ordinary variables
    # passed as key= (fresh per call, derived elsewhere) don't count.
    uses: Dict[str, int] = {}
    reported: set = set()   # call node ids (loop bodies visit twice)

    def bind(target: ast.AST) -> None:
        for name in _names_in(target):
            uses[name] = 0

    def consume(name_node: ast.Name, call: ast.Call) -> Iterator[Finding]:
        name = name_node.id
        if name not in uses:
            return
        uses[name] += 1
        if uses[name] > 1 and id(call) not in reported \
                and not ctx.suppressed(call, RULE):
            reported.add(id(call))
            yield ctx.finding(
                call, RULE,
                f"key {name!r} consumed again without an intervening "
                f"split/fold_in — identical randomness on every use")

    def visit(node: ast.AST) -> Iterator[Finding]:
        # Nested defs have their own pass (fresh scope).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return
        if isinstance(node, ast.Assign):
            # Value first: ``k = jax.random.normal(k)`` consumes the
            # old binding before creating the new one.
            yield from visit(node.value)
            produced = (isinstance(node.value, ast.Call)
                        and qualname(node.value.func)
                        in KEY_PRODUCER_CALLS)
            for target in node.targets:
                if produced:
                    bind(target)
                else:
                    # Any other rebinding clears tracking — we no
                    # longer know the name holds the same key value.
                    for name in _names_in(target):
                        uses.pop(name, None)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                yield from visit(node.value)
            uses.pop(getattr(node.target, "id", None), None)
            return
        if isinstance(node, ast.For):
            # The iterable evaluates once; target/body run per
            # iteration — visit them twice so a key bound OUTSIDE the
            # loop and drawn from INSIDE it counts as reuse (a key
            # re-derived in the body rebinds on the second pass and
            # stays clean).
            yield from visit(node.iter)
            for _ in range(2):
                for child in [node.target] + node.body:
                    yield from visit(child)
            for child in node.orelse:
                yield from visit(child)
            return
        if isinstance(node, ast.While):
            for _ in range(2):
                yield from visit(node.test)
                for child in node.body:
                    yield from visit(child)
            for child in node.orelse:
                yield from visit(child)
            return
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            # Any other binding form (for-target, with-as, unpack in
            # comprehensions): the name no longer provably holds the
            # same key.
            uses.pop(node.id, None)
            return
        for child in ast.iter_child_nodes(node):
            yield from visit(child)
        if isinstance(node, ast.Call):
            q = qualname(node.func)
            # Consumptions: key fed to a sampler positionally, or to
            # any call via a key-ish keyword (model.init/apply rngs).
            if _is_random_consumer(q):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        yield from consume(arg, node)
            for kw in node.keywords:
                if kw.arg in KEY_KEYWORDS:
                    if isinstance(kw.value, ast.Name):
                        yield from consume(kw.value, node)
                    elif isinstance(kw.value, ast.Dict):
                        for v in kw.value.values:
                            if isinstance(v, ast.Name):
                                yield from consume(v, node)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        yield from visit(stmt)
