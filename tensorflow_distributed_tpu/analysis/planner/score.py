"""AOT candidate scoring: the real compiler cost model, no execution.

For each candidate the scorer builds the REAL jitted train step (the
same ``train/step.py`` / ``train/pipeline_step.py`` builders the loop
dispatches) over an ABSTRACT sharded state
(train.state.abstract_train_state — zero bytes allocated, so shapes
too big or too broken to materialize here still score), then:

- ``lower()+compile()`` through observe.device.aot_lower_compile and
  reads flops / bytes / peak-HBM through observe.device.extract_costs
  — ONE extraction path shared with the compiled-program registry, so
  the jax-version key handling and the explicit-null degradation live
  in exactly one place. cost/memory analysis of the partitioned
  module is PER-DEVICE (verified on this container: an 8-way data
  mesh reports 1/8 the single-device flops), so parallelism shows up
  in the numbers without any hand-division.
- censuses the program's EXPLICIT collective traffic with
  analysis.jaxprcheck's walk (the pipeline's ppermute/psum schedule;
  GSPMD-inserted collectives never appear in a jaxpr — their cost
  rides the compiled module's bytes-accessed term instead).
- predicts step time with a roofline:
  ``max(flops/peak_flops, bytes/hbm_bw) + collective_bytes/ici_bw``.

Candidates whose peak-HBM estimate exceeds the budget are MARKED
infeasible (``feasible: false`` + reason) and ranked after the
feasible ones — never dropped. A candidate whose build/compile fails
degrades the same way: explicit-null cost fields plus the error.

The scoring math (:func:`roofline_ms`) and feasibility marking
(:func:`mark_feasibility`) are pure functions over plain dicts —
module import stays jax-free for the unit tier; everything jax lives
behind lazy imports in the build path.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Dict, List, Optional, Sequence

from tensorflow_distributed_tpu.analysis.planner.candidates import (
    Candidate, ModelFacts)

#: per-device (hbm_bytes/s, ici_bytes/s, hbm_capacity_bytes) for the
#: chips observe.mfu.PEAK_BF16_FLOPS knows; the flops peak itself is
#: NOT duplicated here — it comes from that table. Unknown kinds (CPU
#: hosts included) fall back to GENERIC_HW: arbitrary but fixed
#: ratios, fine for RANKING candidates against each other, never to
#: be read as wall-clock truth (planbench checks rank, not seconds).
TPU_HW = {
    "TPU v4": (1.2e12, 3.0e11, 32e9),
    "TPU v5 lite": (8.1e11, 1.6e11, 16e9),
    "TPU v5e": (8.1e11, 1.6e11, 16e9),
    "TPU v5": (2.765e12, 6.0e11, 95e9),
    "TPU v6 lite": (1.64e12, 3.2e11, 32e9),
}
GENERIC_HW = (1.0e11, 2.5e10, None)
GENERIC_PEAK_FLOPS = 1.0e12


@dataclasses.dataclass(frozen=True)
class Hardware:
    """Per-device peaks the roofline divides by (plus the HBM budget
    candidates are marked infeasible against; None = unknown/no
    budget). ``calibration_id`` names the measured profile the rates
    came from (analysis/planner/calibrate.py) — None means the static
    tables."""

    platform: str
    device_kind: str
    peak_flops: float
    hbm_bw: float
    ici_bw: float
    hbm_bytes: Optional[float] = None
    calibration_id: Optional[str] = None
    # Fixed per-dispatch launch cost a calibration profile measured
    # (0 for the static tables): rank-neutral at fixed scale, but the
    # difference between a ranking device and a wall-clock predictor.
    overhead_ms: float = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def detect_hardware(peak_tflops: float = 0.0, hbm_gbps: float = 0.0,
                    ici_gbps: float = 0.0,
                    hbm_budget_gb: float = 0.0,
                    calibration: Optional[Dict[str, Any]] = None
                    ) -> Hardware:
    """Peaks for ``jax.devices()[0]``: the known-TPU tables
    (observe.mfu.PEAK_BF16_FLOPS + TPU_HW), the device's own
    ``memory_stats`` for capacity when it reports one, a CALIBRATION
    profile (calibrate.load_calibration) beating the tables — measured
    effective rates beat a fixed ratio every time, and on unknown
    kinds they replace GENERIC_HW's arbitrary ones — and explicit
    overrides beating everything. A profile whose platform or device
    kind doesn't match the live device is IGNORED with a stderr note
    (a CPU fit must never masquerade as TPU truth)."""
    import jax

    from tensorflow_distributed_tpu.observe import mfu

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    platform = jax.default_backend()
    hbm_bw, ici_bw, hbm = TPU_HW.get(kind, GENERIC_HW)
    flops = mfu.PEAK_BF16_FLOPS.get(kind, GENERIC_PEAK_FLOPS)
    calibration_id = None
    overhead_ms = 0.0
    if calibration:
        cal_kind = calibration.get("device_kind")
        cal_platform = calibration.get("platform")
        if (cal_platform, cal_kind) != (platform, kind):
            print(f"planner: ignoring calibration profile for "
                  f"{cal_platform}/{cal_kind} on a live "
                  f"{platform}/{kind} device", file=sys.stderr)
        else:
            eff = calibration.get("effective", {})
            if isinstance(eff.get("peak_flops"), (int, float)):
                flops = float(eff["peak_flops"])
            if isinstance(eff.get("hbm_bw"), (int, float)):
                hbm_bw = float(eff["hbm_bw"])
            if isinstance(eff.get("ici_bw"), (int, float)):
                ici_bw = float(eff["ici_bw"])
            if isinstance(eff.get("overhead_ms"), (int, float)):
                overhead_ms = float(eff["overhead_ms"])
            calibration_id = calibration.get("calibration_id")
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats and isinstance(stats.get("bytes_limit"), (int, float)):
        hbm = float(stats["bytes_limit"])
    if peak_tflops:
        flops = peak_tflops * 1e12
    if hbm_gbps:
        hbm_bw = hbm_gbps * 1e9
    if ici_gbps:
        ici_bw = ici_gbps * 1e9
    if hbm_budget_gb:
        hbm = hbm_budget_gb * 1e9
    return Hardware(platform=platform, device_kind=kind,
                    peak_flops=flops, hbm_bw=hbm_bw, ici_bw=ici_bw,
                    hbm_bytes=hbm, calibration_id=calibration_id,
                    overhead_ms=overhead_ms)


# --- the scoring math (pure; unit-tested on canned dicts) --------------

def roofline_ms(costs: Dict[str, Any], collective_bytes: float,
                hw: Hardware, overlap: bool = False
                ) -> Dict[str, Optional[float]]:
    """Predicted per-step milliseconds from one program's cost dict:
    ``max(compute, memory) + collectives``. Null costs (a backend
    exposing no analysis) yield explicitly-null predictions — the
    candidate stays in the table, unranked, never invents a number.

    ``overlap=True`` (the explicit bucketed grad-sync strategy,
    parallel/overlap.py) applies the overlap discount: the bucketed
    reduce-scatter/all-gather schedule hides under backward compute,
    so the collective term stops being additive —
    ``max(compute, memory, collectives)`` instead of
    ``max(compute, memory) + collectives``. That is exactly the edge
    the planner needs to rank overlap against plain data/zero1, whose
    GSPMD-implicit allreduce rides the bytes term serially."""
    flops, moved = costs.get("flops"), costs.get("bytes_accessed")
    if not isinstance(flops, (int, float)) or not isinstance(
            moved, (int, float)):
        return {"compute_ms": None, "memory_ms": None,
                "collective_ms": None, "step_ms": None}
    compute = 1e3 * float(flops) / hw.peak_flops
    memory = 1e3 * float(moved) / hw.hbm_bw
    collective = 1e3 * float(collective_bytes or 0.0) / hw.ici_bw
    step = (max(compute, memory, collective) if overlap
            else max(compute, memory) + collective)
    # Calibrated per-dispatch overhead (0 for table hardware).
    step += getattr(hw, "overhead_ms", 0.0)
    return {"compute_ms": round(compute, 6),
            "memory_ms": round(memory, 6),
            "collective_ms": round(collective, 6),
            "step_ms": round(step, 6)}


def mark_feasibility(rows: List[Dict[str, Any]],
                     hbm_budget: Optional[float]) -> List[Dict[str, Any]]:
    """Flag each scored row against the per-device HBM budget.

    MARKS, never drops: ``feasible`` False + ``infeasible_reason`` on
    rows whose peak-HBM estimate exceeds the budget (and on rows that
    failed to build/compile, whose ``error`` is already set). Rows
    with a null peak estimate stay feasible — an unknown is not an
    overflow. Returns the same list, mutated, for chaining."""
    for row in rows:
        if row.get("error"):
            row["feasible"] = False
            row.setdefault("infeasible_reason",
                           "build/compile failed (see error)")
            continue
        peak = row.get("peak_hbm_bytes")
        if (hbm_budget and isinstance(peak, (int, float))
                and peak > hbm_budget):
            row["feasible"] = False
            row["infeasible_reason"] = (
                f"predicted peak HBM {int(peak)} B exceeds the "
                f"per-device budget {int(hbm_budget)} B")
        else:
            row.setdefault("feasible", True)
    return rows


def rank(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Feasible-and-scored first (by predicted step time), then
    feasible-but-unscored, then infeasible — nothing dropped."""
    def key(row):
        scored = isinstance(row.get("step_ms"), (int, float))
        return (0 if row.get("feasible") and scored else
                1 if row.get("feasible") else 2,
                row.get("step_ms") if scored else float("inf"),
                row.get("strategy", ""))
    return sorted(rows, key=key)


# --- candidate -> program -> costs (jax from here on) ------------------

def collective_traffic(closed_jaxpr) -> Dict[str, Any]:
    """{"counts": {prim: n}, "bytes": total} over every EXPLICIT
    collective equation (sub-jaxprs included — the jaxprcheck walk).
    Bytes are the per-shard result sizes, which is what actually
    crosses a link per ppermute hop / psum reduction operand."""
    import numpy as np

    from tensorflow_distributed_tpu.analysis.jaxprcheck import (
        COLLECTIVE_PREFIXES, iter_eqns)

    counts: Dict[str, int] = {}
    total = 0.0
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if not name.startswith(COLLECTIVE_PREFIXES):
            continue
        counts[name] = counts.get(name, 0) + 1
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                total += float(np.prod(aval.shape, dtype=np.float64)
                               * np.dtype(aval.dtype).itemsize)
    return {"counts": dict(sorted(counts.items())), "bytes": total}


def build_candidate_step(cand: Candidate, facts: ModelFacts,
                         batch: int, seq_len: int = 128,
                         size: str = "", dropout_rate: float = 0.0,
                         compute_dtype: str = "bfloat16",
                         moe_experts: int = 0,
                         abstract: bool = True):
    """(jitted step, state, abstract batch, mesh) for one candidate — the
    REAL builders on a real mesh over the first ``product(axes)``
    devices. ``abstract=True`` (scoring) keeps the state a
    sharding-annotated ShapeDtypeStruct tree — no allocation;
    ``abstract=False`` (planbench's execution sweep) materializes it
    through create_train_state so the SAME construction backs both
    the prediction and the measurement. Raises on an unbuildable
    candidate; the scorer degrades it to an error row."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.analysis.planner.candidates import (
        DEFAULT_SIZES)
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.state import (
        abstract_train_state, create_train_state)
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, make_moe_loss, mlm_batch_shardings)

    make_state = (abstract_train_state if abstract
                  else create_train_state)

    axes = cand.mesh
    n = 1
    for _, v in cand.axes:
        n *= v
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"candidate needs {n} devices, have {len(devs)}")
    mesh = make_mesh(MeshConfig(**axes), devs[:n])
    size = size or DEFAULT_SIZES[facts.family]
    dtype = jnp.bfloat16 if compute_dtype == "bfloat16" else jnp.float32
    if facts.family == "serve":
        if not abstract:
            raise ValueError(
                "serve candidates score abstractly: the decode "
                "program is ranked by AOT costs, never executed by "
                "the planner (launch the pick via its cli_args)")
        return _build_serve_decode(cand, mesh, batch, seq_len, size,
                                   dtype)
    sample = np.zeros((2, seq_len), np.int32)
    kw: Dict[str, Any] = dict(dropout_rate=dropout_rate,
                              compute_dtype=dtype, max_len=seq_len)
    tx = optax.adam(1e-3)
    sh = mlm_batch_shardings(mesh)
    if facts.family == "pipelined":
        from tensorflow_distributed_tpu.models.pipelined import (
            pipelined_lm)
        from tensorflow_distributed_tpu.train.pipeline_step import (
            make_1f1b_train_step)
        model = pipelined_lm(mesh, size=size,
                             num_microbatches=cand.microbatches, **kw)
        state = make_state(model, tx, sample, mesh,
                           opt_fsdp=cand.partition == "zero1")
        params_out = (jax.tree_util.tree_map(lambda s: s.sharding,
                                             state.params)
                      if cand.partition == "zero1" else None)
        step = make_1f1b_train_step(model, mesh, batch_shardings=sh,
                                    params_out_shardings=params_out)
    else:
        from tensorflow_distributed_tpu.models import transformer
        from tensorflow_distributed_tpu.train.step import (
            make_train_step)
        if facts.family == "moe" and moe_experts:
            kw["moe_experts"] = moe_experts
        factory = (transformer.moe_lm if facts.family == "moe"
                   else transformer.gpt_lm)
        overlap = cand.partition == "overlap"
        if overlap:
            # The explicit step's forward runs inside its shard_map —
            # mesh-less model, no activation pins (the builder's
            # docstring; same construction train.loop uses for
            # --grad-sync).
            kw["tp_partitioning"] = False
        model = factory(mesh=None if overlap else mesh, size=size,
                        **kw)
        state = make_state(model, tx, sample, mesh,
                           fsdp=cand.partition == "fsdp",
                           opt_fsdp=cand.partition in ("zero1",
                                                       "overlap"))
        params_out = (jax.tree_util.tree_map(lambda s: s.sharding,
                                             state.params)
                      if cand.partition in ("zero1", "overlap")
                      else None)
        loss = (make_moe_loss() if facts.family == "moe"
                else make_mlm_loss())
        if overlap:
            from tensorflow_distributed_tpu.parallel.overlap import (
                make_explicit_train_step)
            step = make_explicit_train_step(
                mesh, state, loss=loss, batch_shardings=sh,
                grad_sync="overlap", params_out_shardings=params_out)
        else:
            step = make_train_step(mesh, loss=loss, batch_shardings=sh,
                                   params_out_shardings=params_out)
    abatch = {
        k: jax.ShapeDtypeStruct(
            (batch, seq_len),
            np.int32 if k != "mask" else np.float32, sharding=sh[k])
        for k in ("tokens", "targets", "mask")}
    return step, state, abatch, mesh


def _build_serve_decode(cand: Candidate, mesh, num_slots: int,
                        max_len: int, size: str, dtype):
    """(decode step, abstract (params, cache), abstract (tok, pos),
    mesh) for one serve-family candidate — THE program
    serve/engine.py dispatches every token, over the layout
    --serve.mesh-model would build: params placed by the partition
    metadata, the slot cache's head axis (dim 2 of every >= 3-d leaf,
    serve.engine.shard_cache's rule) sharded over "model". Everything
    is ShapeDtypeStructs: candidates rank by compiled AOT costs with
    zero bytes allocated. ``batch`` arrives as the SLOT count (decode
    batch == slots), ``seq_len`` as the cache depth."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.models.generate import decode_token
    from tensorflow_distributed_tpu.parallel.sharding import (
        param_sharding)

    model = transformer.gpt_lm(mesh, size=size, dropout_rate=0.0,
                               compute_dtype=dtype, max_len=max_len)
    abstract_vars = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
        jax.random.key(0))
    aparams = jax.tree_util.tree_map(
        lambda leaf, sd: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                              sharding=sd),
        nn.meta.unbox(abstract_vars)["params"],
        param_sharding(mesh, abstract_vars)["params"])
    tp = dict(mesh.shape).get("model", 1)
    tok = jnp.zeros((num_slots, 1), jnp.int32)
    cache_shapes = jax.eval_shape(
        lambda p, t, q: model.apply({"params": p}, t, decode=True,
                                    positions=q,
                                    mutable=["cache"])[1]["cache"],
        aparams, tok, tok)

    def cache_sds(leaf):
        spec = (PartitionSpec(None, None, "model")
                if tp > 1 and leaf.ndim >= 3 else PartitionSpec())
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=NamedSharding(mesh, spec))

    acache = jax.tree_util.tree_map(cache_sds, cache_shapes)

    def step(state, batch):
        params, cache = state
        tok, pos = batch
        last, cache = decode_token(model, params, cache, tok, pos)
        ok = jnp.isfinite(last).all(axis=-1)
        return (cache, jnp.argmax(last, axis=-1).astype(jnp.int32),
                ok)

    rep = NamedSharding(mesh, PartitionSpec())
    slots = jax.ShapeDtypeStruct((num_slots,), np.int32, sharding=rep)
    # jit like the train-step builders do — aot_lower_compile wants a
    # lowerable callable.
    return jax.jit(step), (aparams, acache), (slots, slots), mesh


def score_candidate(cand: Candidate, facts: ModelFacts, batch: int,
                    hw: Hardware, seq_len: int = 128, size: str = "",
                    dropout_rate: float = 0.0,
                    compute_dtype: str = "bfloat16",
                    moe_experts: int = 0) -> Dict[str, Any]:
    """One candidate's score row: AOT costs + collective census +
    roofline prediction. Failures degrade to an explicit-null row
    with the error recorded — a broken candidate must not take down
    the plan (same contract as the program registry's registration)."""
    from tensorflow_distributed_tpu.observe.device import (
        COST_FIELDS, aot_lower_compile, extract_costs)

    row: Dict[str, Any] = {
        "mesh": cand.mesh, "strategy": cand.strategy,
        "partition": cand.partition,
        **{k: None for k in COST_FIELDS},
        "collectives": {}, "collective_bytes": 0.0,
        "lower_s": None, "compile_s": None,
    }
    if cand.microbatches:
        row["microbatches"] = cand.microbatches
    try:
        import jax

        step, state, abatch, _ = build_candidate_step(
            cand, facts, batch, seq_len=seq_len, size=size,
            dropout_rate=dropout_rate, compute_dtype=compute_dtype,
            moe_experts=moe_experts)
        traffic = collective_traffic(
            jax.make_jaxpr(step)(state, abatch))
        row["collectives"] = traffic["counts"]
        row["collective_bytes"] = traffic["bytes"]
        _, compiled, lower_s, compile_s = aot_lower_compile(
            step, (state, abatch))
        row.update(extract_costs(compiled))
        row["lower_s"] = round(lower_s, 4)
        row["compile_s"] = round(compile_s, 4)
    except Exception as e:  # degrade, never die: explicit-null row
        row["error"] = f"{type(e).__name__}: {e}"[:300]
    row.update(roofline_ms(row, row["collective_bytes"], hw,
                           overlap=cand.partition == "overlap"))
    return row


def score_candidates(cands: Sequence[Candidate], facts: ModelFacts,
                     batch: int, hw: Hardware, seq_len: int = 128,
                     size: str = "", dropout_rate: float = 0.0,
                     compute_dtype: str = "bfloat16",
                     moe_experts: int = 0,
                     hbm_budget: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
    """Score every candidate, mark HBM feasibility, rank."""
    rows = [score_candidate(c, facts, batch, hw, seq_len=seq_len,
                            size=size, dropout_rate=dropout_rate,
                            compute_dtype=compute_dtype,
                            moe_experts=moe_experts)
            for c in cands]
    budget = hbm_budget if hbm_budget is not None else hw.hbm_bytes
    return rank(mark_feasibility(rows, budget))
