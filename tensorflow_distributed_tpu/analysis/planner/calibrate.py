"""Cost-model calibration: fit effective device rates to MEASURED steps.

The planner's roofline (score.py) divides AOT cost analysis by fixed
per-chip peaks — the TPU_HW table for known kinds, GENERIC_HW's
arbitrary-but-fixed ratios everywhere else. Fine for RANKING, useless
as wall-clock truth (committed PLANBENCH: predicted 0.26 ms vs
measured 18.6 ms on this CPU host). This module closes the
predicted→measured gap the TF paper's runtime closes internally
(PAPERS.md 1605.08695) and pjit-era systems close with profiler-driven
tuning (2204.06514): fit EFFECTIVE flops/s, HBM bytes/s, and
collective bytes/s from measured ``(program costs, step time)`` pairs
by least squares over the roofline's own terms, write an atomic
``calibration.json`` (platform/device-kind tagged, git-sha stamped),
and let ``score.detect_hardware(calibration=...)`` prefer the profile
over the static tables.

The model is the roofline plus a per-dispatch overhead intercept::

    ms = overhead + max(1e3*flops/F, 1e3*bytes/B) + 1e3*coll_bytes/C

The intercept is what the static tables structurally CANNOT express:
every real dispatch pays a fixed launch/host cost (large on CPU, small
but nonzero on TPU), and without it no single rate fits a batch-16 and
a batch-64 step at once. It never changes candidate RANKING at fixed
scale — every candidate pays it — but it is the difference between a
ranking device and a wall-clock predictor. The model is nonlinear in
(F, B, C) through the max, so the fit alternates: assign each sample
to its binding term under the current rates, then (overhead, 1/F, 1/B)
solve jointly as a LINEAR least squares over the assigned design
matrix (3x3 normal equations, pure python), and C updates on the
residual the max-term leaves. Parameters a sample set cannot constrain
(no collective traffic -> C; every sample compute-bound -> B) keep
their previous value — an unconstrained parameter must not wander; a
negative intercept clamps to zero and the rates re-solve without it.

Sample sources:

- ``samples_from_planbench(path)``: the planbench sweep's candidate
  lines (benchmarks/planbench.py emits per-device ``flops`` /
  ``bytes_accessed`` / ``collective_bytes`` beside
  ``measured_step_ms_min``) — many programs, one measurement each;
- ``samples_from_metrics(path)``: a run's own metrics JSONL — join
  ``compile`` records (costs) with ``device_time`` records (measured
  ``device_ms_per_call`` from the xprof attribution) by program name.

Pure stdlib on purpose (module import is jax-free); the CLI::

    python -m tensorflow_distributed_tpu.analysis.planner.calibrate \
        --from-planbench PLANBENCH.json --out calibration.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json

CALIBRATION_VERSION = 1

#: the sample fields a fit consumes (measured_ms > 0 required;
#: flops/bytes numeric required; collective_bytes optional/0).
SAMPLE_FIELDS = ("flops", "bytes_accessed", "collective_bytes",
                 "measured_ms")


def _valid(samples: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    out = []
    for s in samples:
        f, b = s.get("flops"), s.get("bytes_accessed")
        m = s.get("measured_ms")
        if (isinstance(f, (int, float)) and isinstance(b, (int, float))
                and isinstance(m, (int, float)) and m > 0
                and (f > 0 or b > 0)):
            out.append({"flops": float(f), "bytes_accessed": float(b),
                        "collective_bytes": float(
                            s.get("collective_bytes") or 0.0),
                        "measured_ms": float(m),
                        "key": s.get("key") or s.get("program")})
    return out


def _ls_rate(units: List[float], ms: List[float]) -> Optional[float]:
    """The closed-form least squares for one roofline term: minimize
    sum((1e3 * u_i / R - y_i)^2) over R > 0. Returns units/second
    (None when the samples can't constrain it)."""
    num = sum(u * y for u, y in zip(units, ms))
    den = sum(u * u for u in units)
    if num <= 0 or den <= 0:
        return None
    inv = num / (1e3 * den)   # seconds-per-unit * 1e... (ms = 1e3*u/R)
    return 1.0 / inv if inv > 0 else None


def _predict_ms(s: Dict[str, Any], F: float, B: float,
                C: Optional[float], overhead: float = 0.0) -> float:
    compute = 1e3 * s["flops"] / F
    memory = 1e3 * s["bytes_accessed"] / B
    coll = (1e3 * s["collective_bytes"] / C
            if C and s["collective_bytes"] else 0.0)
    return overhead + max(compute, memory) + coll


def _solve_normal(rows: List[List[float]], ys: List[float]
                  ) -> Optional[List[float]]:
    """min ||A x - y||_2 by the normal equations (tiny n — 3 params),
    Gaussian elimination with partial pivoting. None when singular."""
    n = len(rows[0])
    a = [[sum(r[i] * r[j] for r in rows) for j in range(n)]
         + [sum(r[i] * y for r, y in zip(rows, ys))]
         for i in range(n)]
    for col in range(n):
        piv = max(range(col, n), key=lambda r: abs(a[r][col]))
        if abs(a[piv][col]) < 1e-30:
            return None
        a[col], a[piv] = a[piv], a[col]
        for r in range(n):
            if r != col:
                k = a[r][col] / a[col][col]
                a[r] = [v - k * w for v, w in zip(a[r], a[col])]
    return [a[i][n] / a[i][i] for i in range(n)]


def fit_rates(samples: Sequence[Dict[str, Any]], iters: int = 20
              ) -> Dict[str, Any]:
    """Alternating least squares under the overhead + max-roofline
    model (module docstring).

    Returns ``{"peak_flops", "hbm_bw", "ici_bw", "overhead_ms",
    "samples", "mean_abs_rel_err", "median_abs_rel_err"}`` — rates are
    effective units/second; ici_bw is None when no sample moved
    collective bytes. Raises ValueError on an empty/unusable sample
    set."""
    ss = _valid(samples)
    if not ss:
        raise ValueError("no usable calibration samples (need numeric "
                         "flops/bytes_accessed and measured_ms > 0)")
    ms = [s["measured_ms"] for s in ss]
    # Init: each rate fit as if ITS term alone explained every sample.
    F = _ls_rate([s["flops"] for s in ss], ms) or 1e9
    B = _ls_rate([s["bytes_accessed"] for s in ss], ms) or 1e9
    O = 0.0
    with_coll = [s for s in ss if s["collective_bytes"] > 0]
    C = (_ls_rate([s["collective_bytes"] for s in with_coll],
                  [s["measured_ms"] for s in with_coll])
         if with_coll else None)
    for _ in range(iters):
        coll_ms = [(1e3 * s["collective_bytes"] / C
                    if C and s["collective_bytes"] else 0.0)
                   for s in ss]
        resid = [max(m - c, 1e-9) for m, c in zip(ms, coll_ms)]
        compute_bound = [1e3 * s["flops"] / F
                         >= 1e3 * s["bytes_accessed"] / B for s in ss]
        # Joint LINEAR solve for (overhead, 1/F, 1/B) under the
        # current assignment. Columns only for constrained params: an
        # empty group would make its column all-zero (singular).
        cols = ["o"] + (["F"] if any(compute_bound) else []) \
            + (["B"] if not all(compute_bound) else [])
        rows = []
        for s, cb in zip(ss, compute_bound):
            row = []
            for c in cols:
                if c == "o":
                    row.append(1.0)
                elif c == "F":
                    row.append(1e3 * s["flops"] if cb else 0.0)
                else:
                    row.append(0.0 if cb
                               else 1e3 * s["bytes_accessed"])
            rows.append(row)
        sol = _solve_normal(rows, resid)
        if sol is not None and sol[0] < 0:
            # Negative intercept is nonphysical: clamp to zero and
            # re-solve the rates without it.
            sol2 = _solve_normal([r[1:] for r in rows], resid)
            sol = None if sol2 is None else [0.0] + sol2
        if sol is not None:
            vals = dict(zip(cols, sol))
            O = max(vals.get("o", 0.0), 0.0)
            if vals.get("F", 0.0) > 0:
                F = 1.0 / vals["F"]
            if vals.get("B", 0.0) > 0:
                B = 1.0 / vals["B"]
        if with_coll:
            # Collective rate on what overhead + max-term leave.
            rc = [max(s["measured_ms"] - O
                      - max(1e3 * s["flops"] / F,
                            1e3 * s["bytes_accessed"] / B), 1e-9)
                  for s in with_coll]
            C = _ls_rate([s["collective_bytes"] for s in with_coll],
                         rc) or C
    errs = sorted(abs(_predict_ms(s, F, B, C, O) - s["measured_ms"])
                  / s["measured_ms"] for s in ss)
    return {
        "peak_flops": F, "hbm_bw": B, "ici_bw": C,
        "overhead_ms": round(O, 6),
        "samples": len(ss),
        "mean_abs_rel_err": round(sum(errs) / len(errs), 4),
        "median_abs_rel_err": round(errs[len(errs) // 2], 4),
    }


def rel_errors(samples: Sequence[Dict[str, Any]], peak_flops: float,
               hbm_bw: float, ici_bw: Optional[float],
               overhead_ms: float = 0.0) -> List[float]:
    """Per-sample |predicted - measured| / measured under given rates
    (the calibbench gate compares these calibrated vs uncalibrated)."""
    return [abs(_predict_ms(s, peak_flops, hbm_bw, ici_bw, overhead_ms)
                - s["measured_ms"]) / s["measured_ms"]
            for s in _valid(samples)]


# --- profile IO --------------------------------------------------------

def make_profile(fit: Dict[str, Any], platform: str, device_kind: str,
                 source: str = "", devices: int = 0) -> Dict[str, Any]:
    """The calibration.json payload: effective rates + provenance.
    ``calibration_id`` is a short stable hash of platform/kind/rates —
    the id bench artifacts are stamped with, so the regress ledger can
    name exactly which profile predicted what."""
    from tensorflow_distributed_tpu.observe.registry import git_sha

    eff = {"peak_flops": fit["peak_flops"], "hbm_bw": fit["hbm_bw"],
           "ici_bw": fit["ici_bw"],
           "overhead_ms": fit.get("overhead_ms", 0.0)}
    blob = json.dumps([platform, device_kind, eff], sort_keys=True)
    cal_id = (f"{platform}-"
              f"{hashlib.sha256(blob.encode()).hexdigest()[:10]}")
    return {
        "version": CALIBRATION_VERSION,
        "calibration_id": cal_id,
        "platform": platform,
        "device_kind": device_kind,
        "git_sha": git_sha(),
        "source": source,
        "devices": devices,
        "effective": eff,
        "fit": {k: fit[k] for k in ("samples", "mean_abs_rel_err",
                                    "median_abs_rel_err")},
    }


def write_calibration(profile: Dict[str, Any], path: str) -> None:
    """Atomic (tmp+fsync+rename) so a poller — or a crashed fit —
    never reads a torn profile."""
    atomic_write_json(path, profile, indent=2, trailing_newline=True)


def load_calibration(path: str) -> Dict[str, Any]:
    """Read + shape-check a profile; raises ValueError on junk (a
    mis-pointed --plan-calibration must fail loudly, not silently
    un-calibrate the plan)."""
    with open(path) as f:
        profile = json.load(f)
    if not isinstance(profile, dict) or "effective" not in profile:
        raise ValueError(f"{path}: not a calibration profile "
                         f"(missing 'effective' rates)")
    if profile.get("version") != CALIBRATION_VERSION:
        raise ValueError(f"{path}: calibration version "
                         f"{profile.get('version')!r} != "
                         f"{CALIBRATION_VERSION}")
    return profile


# --- sample sources ----------------------------------------------------

def _load_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # the report's count-and-skip contract
    return out


def samples_from_planbench(path: str) -> List[Dict[str, Any]]:
    """(costs, measured) pairs from a planbench artifact's candidate
    lines — requires the per-candidate cost fields planbench emits
    (older artifacts without them yield no samples)."""
    samples = []
    for rec in _load_jsonl(path):
        if rec.get("metric") != "planbench_candidate":
            continue
        samples.append({
            "flops": rec.get("flops"),
            "bytes_accessed": rec.get("bytes_accessed"),
            "collective_bytes": rec.get("collective_bytes"),
            "measured_ms": rec.get("measured_step_ms_min"),
            "key": rec.get("key"),
        })
    return _valid(samples)


def samples_from_metrics(path: str) -> List[Dict[str, Any]]:
    """(costs, measured) pairs from a run's own metrics JSONL: each
    program's latest ``compile`` record (flops/bytes) joined with its
    latest ``device_time`` record (measured ms per call from the xprof
    attribution)."""
    costs: Dict[str, Dict[str, Any]] = {}
    measured: Dict[str, float] = {}
    for rec in _load_jsonl(path):
        if rec.get("event") == "compile" and rec.get("program"):
            costs[rec["program"]] = rec
        elif (rec.get("event") == "device_time" and rec.get("program")
                and isinstance(rec.get("device_ms_per_call"),
                               (int, float))):
            measured[rec["program"]] = float(rec["device_ms_per_call"])
    samples = []
    for program, ms in measured.items():
        c = costs.get(program)
        if c is None:
            continue
        samples.append({"flops": c.get("flops"),
                        "bytes_accessed": c.get("bytes_accessed"),
                        "collective_bytes": 0.0,
                        "measured_ms": ms, "key": program})
    return _valid(samples)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.analysis.planner"
             ".calibrate",
        description="fit effective device rates from measured step "
                    "times and write an atomic calibration.json the "
                    "planner roofline prefers over its static tables")
    parser.add_argument("--from-planbench", default="",
                        help="planbench artifact with per-candidate "
                        "cost fields (benchmarks/planbench.py --out)")
    parser.add_argument("--from-jsonl", default="",
                        help="run metrics JSONL: compile records "
                        "joined with xprof device_time records")
    parser.add_argument("--platform", default="",
                        help="override the platform tag (default: "
                        "read from the source artifact, else "
                        "'unknown')")
    parser.add_argument("--device-kind", default="",
                        help="override the device-kind tag")
    parser.add_argument("--out", default="calibration.json")
    args = parser.parse_args(argv)
    if bool(args.from_planbench) == bool(args.from_jsonl):
        parser.error("exactly one of --from-planbench / --from-jsonl")
    if args.from_planbench:
        samples = samples_from_planbench(args.from_planbench)
        source = f"planbench:{os.path.basename(args.from_planbench)}"
        tags = next((r for r in _load_jsonl(args.from_planbench)
                     if "platform" in r), {})
        platform = args.platform or tags.get("platform", "unknown")
        devices = int(tags.get("devices", 0) or 0)
    else:
        samples = samples_from_metrics(args.from_jsonl)
        source = f"metrics:{os.path.basename(args.from_jsonl)}"
        platform = args.platform or "unknown"
        devices = 0
    kind = args.device_kind
    if not kind:
        # The live device's kind, when a backend is reachable — the
        # profile must name what it measured.
        try:
            import jax
            kind = getattr(jax.devices()[0], "device_kind", "unknown")
            if not args.platform:
                platform = jax.default_backend()
        except Exception:
            kind = "unknown"
    try:
        fit = fit_rates(samples)
    except ValueError as e:
        print(f"calibrate: {e}", file=sys.stderr)
        return 1
    profile = make_profile(fit, platform, kind, source=source,
                           devices=devices)
    write_calibration(profile, args.out)
    eff = profile["effective"]
    print(f"calibrate: {fit['samples']} samples -> "
          f"eff_flops={eff['peak_flops']:.3g}/s "
          f"eff_hbm={eff['hbm_bw']:.3g}B/s "
          f"eff_ici={'%.3g' % eff['ici_bw'] if eff['ici_bw'] else '-'}"
          f"B/s  median_rel_err={fit['median_abs_rel_err']}")
    print(f"calibrate: wrote {args.out} "
          f"(id {profile['calibration_id']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
