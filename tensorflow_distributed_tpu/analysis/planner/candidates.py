"""Candidate enumeration: mesh factorizations x parallelism strategies.

Pure and import-light (stdlib only at module load; the shared
divisibility rules and the model-size facts are imported lazily), so
the enumeration/pruning logic unit-tests with stubbed constraints and
zero jax machinery.

A candidate is a full mesh-axes assignment plus a parameter-partition
choice. Hard constraints prune UP FRONT, each pruned shape keeping its
reason — the planner's report distinguishes "never valid" (pruned
here) from "valid but over the HBM budget" (marked infeasible at
scoring time, score.mark_feasibility).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Same axis order as parallel.mesh.MESH_AXES (not imported: that
# module loads jax; this one must not).
MESH_AXES = ("data", "pipe", "seq", "model", "expert")

#: planner family -> the model registry name the train CLI uses.
#: "serve" plans the gpt family's DECODE program over the serve
#: replica's own tensor-parallel mesh (--serve.mesh-model), not a
#: train step — enumerate_candidates and the scorer branch on it.
FAMILY_MODELS = {"gpt": "gpt_lm", "moe": "moe_lm",
                 "pipelined": "pipelined_lm", "serve": "gpt_lm"}
#: registry name -> TRAIN family (serve excluded: gpt_lm's inverse is
#: the train family; serve is an explicit planner choice, never an
#: inference from a model name).
MODEL_FAMILIES = {v: k for k, v in FAMILY_MODELS.items()
                  if k != "serve"}

#: the factory-default size per family (models/transformer.py
#: gpt_lm(size="small"), moe_lm(size="tiny"), pipelined_lm("tiny")).
DEFAULT_SIZES = {"gpt": "small", "moe": "tiny", "pipelined": "tiny",
                 "serve": "small"}

#: the TP widths the serve family enumerates (ISSUE: rank
#: --serve.mesh-model without executing; width 1 is the single-device
#: engine the others are ranked against).
SERVE_TP_WIDTHS = (1, 2, 4)

#: Partition-like strategy choices. "overlap" = zero1 slot sharding +
#: the explicit bucketed reduce-scatter/all-gather grad sync
#: (parallel/overlap.py; launches as --param-partition zero1
#: --grad-sync overlap). Pure-data meshes only — the explicit
#: shard_map formulation doesn't reproduce tensor/expert/pipe
#: schedules.
PARTITIONS = ("replicated", "fsdp", "zero1", "overlap")


def format_mesh(mesh: Dict[str, int]) -> str:
    """"data=8" / "data=4,model=2" / "single-device" — THE mesh
    formatter for planner output. One copy on purpose: planbench
    cross-references candidate keys built from plan output, so two
    formatters drifting apart would silently break its pick lookup."""
    parts = [f"{k}={v}" for k, v in mesh.items() if v != 1]
    return ",".join(parts) if parts else "single-device"


@dataclasses.dataclass(frozen=True)
class ModelFacts:
    """What enumeration needs to know about a model family/size —
    nothing else (the scoring layer builds the real model)."""

    family: str                 # gpt | moe | pipelined
    n_heads: int
    n_layers: int
    n_experts: int = 0          # 0 = dense (no expert axis)
    vocab_size: int = 0         # factory base vocab; 0 = unknown
    #                             (only the serve family prunes on it:
    #                             the TP head is vocab-parallel)

    def validate(self) -> None:
        if self.family not in FAMILY_MODELS:
            raise ValueError(
                f"unknown planner family {self.family!r}; have "
                f"{sorted(FAMILY_MODELS)}")
        if self.n_heads < 1 or self.n_layers < 1 or self.n_experts < 0:
            raise ValueError(
                f"bad model facts: heads={self.n_heads} "
                f"layers={self.n_layers} experts={self.n_experts}")


def model_facts(family: str, size: str = "",
                moe_experts: int = 0) -> ModelFacts:
    """Facts for a named family/size preset, read from the model
    factories' OWN constants (lazy imports — the sizes live with the
    factories), so pruning can never desynchronize from the real
    model the scorer builds."""
    if family not in FAMILY_MODELS:
        raise ValueError(f"unknown planner family {family!r}; have "
                         f"{sorted(FAMILY_MODELS)}")
    size = size or DEFAULT_SIZES[family]
    from tensorflow_distributed_tpu.models.transformer import (
        GPT2_SIZES, MOE_DEFAULT_EXPERTS, tiny_config)
    if size == "tiny":
        tiny = tiny_config()
        heads, layers = tiny.n_heads, tiny.n_layers
        vocab = tiny.vocab_size
        if family == "pipelined":
            # pipelined_lm bumps tiny's layer count so common stage
            # counts divide it — the same constant the factory uses.
            from tensorflow_distributed_tpu.models.pipelined import (
                PIPELINED_TINY_LAYERS)
            layers = PIPELINED_TINY_LAYERS
    elif size in GPT2_SIZES:
        from tensorflow_distributed_tpu.models.transformer import (
            gpt2_small_config)
        heads = GPT2_SIZES[size]["n_heads"]
        layers = GPT2_SIZES[size]["n_layers"]
        vocab = gpt2_small_config().vocab_size
    else:
        raise ValueError(f"unknown size {size!r}; have "
                         f"(tiny, {', '.join(GPT2_SIZES)})")
    experts = ((moe_experts or MOE_DEFAULT_EXPERTS)
               if family == "moe" else 0)
    return ModelFacts(family=family, n_heads=heads, n_layers=layers,
                      n_experts=experts, vocab_size=vocab)


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One launch configuration: a full mesh-axes assignment plus the
    parameter-partition mode (and, pipelined, the microbatch count)."""

    axes: Tuple[Tuple[str, int], ...]   # hashable (axis, size) pairs
    partition: str = "replicated"       # replicated | fsdp | zero1
    microbatches: int = 0               # pipelined only (0 = n/a)
    serve: bool = False                 # serve family: the mesh is the
    #                                     ENGINE's (--serve.mesh-model),
    #                                     not the train --mesh.*

    @staticmethod
    def make(axes: Dict[str, int], partition: str = "replicated",
             microbatches: int = 0, serve: bool = False) -> "Candidate":
        full = {a: int(axes.get(a, 1)) for a in MESH_AXES}
        return Candidate(axes=tuple(full.items()), partition=partition,
                         microbatches=microbatches, serve=serve)

    @property
    def mesh(self) -> Dict[str, int]:
        return dict(self.axes)

    @property
    def strategy(self) -> str:
        """Human name, e.g. "data", "fsdp+tensor", "data+pipe". The
        partition contributes its name (fsdp/zero1) or "data" for
        plain replicated data parallelism; each non-unit non-data
        axis contributes tensor/seq/pipe/expert."""
        mesh = self.mesh
        parts: List[str] = []
        if self.partition != "replicated":
            parts.append(self.partition)
        elif mesh["data"] > 1:
            parts.append("data")
        for axis, name in (("model", "tensor"), ("seq", "seq"),
                           ("pipe", "pipe"), ("expert", "expert")):
            if mesh[axis] > 1:
                parts.append(name)
        return "+".join(parts) if parts else "data"

    def cli_args(self) -> List[str]:
        """The train-CLI flags that launch this candidate."""
        if self.serve:
            # The serve engine builds its OWN mesh from this one knob
            # (serve/run.py validates heads/devices at launch); the
            # train --mesh.* flags are rejected under mode=serve.
            return ["--serve.mesh-model", str(self.mesh["model"])]
        out: List[str] = []
        for axis, size in self.axes:
            out += [f"--mesh.{axis}", str(size)]
        if self.partition == "overlap":
            # The overlap strategy IS zero1 slot sharding plus the
            # explicit grad-sync flag.
            out += ["--param-partition", "zero1",
                    "--grad-sync", "overlap"]
        elif self.partition != "replicated":
            out += ["--param-partition", self.partition]
        if self.microbatches:
            out += ["--pipeline-microbatches", str(self.microbatches)]
        return out


@dataclasses.dataclass(frozen=True)
class Pruned:
    """A shape rejected by a hard constraint — kept, with its reason,
    so the plan reports what was ruled out and why."""

    candidate: Candidate
    reason: str


def _default_infeasible(axes: Dict[str, int], devices: int,
                        batch: Optional[int]) -> Optional[str]:
    # The shared rules (lazy import: parallel.mesh loads jax; the
    # enumeration itself must stay stdlib-importable for the jax-free
    # unit tier, which stubs this callable).
    from tensorflow_distributed_tpu.parallel.mesh import mesh_infeasible
    return mesh_infeasible(axes, devices, batch)


def _family_infeasible(facts: ModelFacts, axes: Dict[str, int],
                       batch: int, microbatches: int) -> Optional[str]:
    """Family/model divisibility the mesh layer can't know."""
    if axes.get("model", 1) > 1 and facts.n_heads % axes["model"]:
        return (f"n_heads {facts.n_heads} not divisible by tensor "
                f"axis {axes['model']} (heads shard over 'model')")
    if axes.get("expert", 1) > 1:
        if not facts.n_experts:
            return "expert axis needs an MoE family"
        if (facts.n_experts % axes["expert"]
                or axes["expert"] > facts.n_experts):
            return (f"{facts.n_experts} experts not divisible by "
                    f"expert axis {axes['expert']}")
    if axes.get("pipe", 1) > 1:
        if facts.n_layers % axes["pipe"]:
            return (f"n_layers {facts.n_layers} not divisible by pipe "
                    f"axis {axes['pipe']} (layers slice into stages)")
        if microbatches < axes["pipe"]:
            return (f"microbatches {microbatches} < pipe "
                    f"{axes['pipe']}: every stage needs a microbatch "
                    f"in flight")
    if facts.family == "pipelined" and batch % max(microbatches, 1):
        return (f"global batch {batch} not divisible by "
                f"pipeline microbatches {microbatches}")
    return None


def _second_axes(facts: ModelFacts) -> Sequence[str]:
    """Which non-data axis the family's factorizations spread over
    (seq stays 1 — ring attention is a long-context knob, not a
    throughput layout, and the planner doesn't model its windows)."""
    if facts.family == "pipelined":
        return ("pipe",)
    if facts.family == "moe":
        return ("model", "expert")
    return ("model",)


def enumerate_candidates(
        facts: ModelFacts, devices: int, batch: int,
        strategies: Optional[Sequence[str]] = None,
        microbatches: int = 4,
        infeasible: Optional[Callable[..., Optional[str]]] = None,
        overlap_conflict: Optional[str] = None,
) -> Tuple[List[Candidate], List[Pruned]]:
    """All (mesh factorization x partition) candidates for a family.

    Returns ``(feasible, pruned)`` — pruned shapes keep their reasons.
    ``strategies`` restricts by strategy PART (e.g. ("data", "fsdp",
    "zero1") excludes every tensor/expert/pipe shape — what planbench
    uses on a container whose TP execution is skewed); a candidate
    survives only when every part of its strategy name is allowed.
    ``infeasible`` is the shared mesh rule
    (parallel.mesh.mesh_infeasible), injectable for jax-free tests.
    ``overlap_conflict`` (a reason string, or None) prunes every
    "overlap" candidate — --plan auto passes the run's
    config.overlap_grad_sync_conflict() so the plan never picks a
    layout whose launch the config would then reject (the standalone
    planner CLI plans layouts, not runs, and passes nothing).
    """
    facts.validate()
    if devices < 1:
        raise ValueError(f"devices must be >= 1, got {devices}")
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    check = infeasible or _default_infeasible
    allowed = set(strategies) if strategies else None
    feasible: List[Candidate] = []
    pruned: List[Pruned] = []
    if facts.family == "serve":
        # The serve replica's OWN mesh: always [data=1, model=N] — the
        # engine serves one replica; data-scaling is the fleet
        # router's job, not this mesh's. ``batch`` is the slot count
        # (replicated), so the mesh rules' batch-divisibility checks
        # don't apply; what does: devices and head divisibility.
        for width in SERVE_TP_WIDTHS:
            cand = Candidate.make({"data": 1, "model": width},
                                  serve=True)
            if width > devices:
                pruned.append(Pruned(cand, (
                    f"model={width} needs {width} devices, have "
                    f"{devices}")))
                continue
            if width > 1 and facts.n_heads % width:
                pruned.append(Pruned(cand, (
                    f"n_heads {facts.n_heads} not divisible by model "
                    f"axis {width} (heads shard over 'model')")))
                continue
            if width > 1 and facts.vocab_size % width:
                # The TP LM head is vocab-parallel (column-split over
                # "model"); an odd vocab like GPT-2's 50257 only
                # shards padded (--shard-vocab), which the serve
                # scorer does not model — prune, don't error-row.
                pruned.append(Pruned(cand, (
                    f"vocab {facts.vocab_size} not divisible by model "
                    f"axis {width} (the LM head is vocab-parallel; "
                    f"--shard-vocab pads it)")))
                continue
            if allowed is not None and not (
                    set(cand.strategy.split("+")) <= allowed):
                pruned.append(Pruned(cand, (
                    f"strategy {cand.strategy!r} excluded by "
                    f"--strategies")))
                continue
            feasible.append(cand)
        return feasible, pruned
    second_axes = _second_axes(facts)
    for second in second_axes:
        for k in range(1, devices + 1):
            if devices % k:
                continue
            if k == 1 and second != second_axes[0]:
                continue  # the pure-data shape: keep one copy only
            data = devices // k
            axes = {"data": data, second: k}
            # Pipelined runs its schedule at any pipe >= 1; the
            # microbatch count never drops below the stage count.
            mb = (max(microbatches, k) if facts.family == "pipelined"
                  else 0)
            for partition in PARTITIONS:
                cand = Candidate.make(axes, partition, microbatches=mb)
                if partition == "fsdp" and facts.family == "pipelined":
                    pruned.append(Pruned(cand, (
                        "fsdp does not compose with pipelined_lm "
                        "(stage params are shard_map-managed; "
                        "config.validate rejects it)")))
                    continue
                if partition == "overlap":
                    if facts.family == "pipelined":
                        pruned.append(Pruned(cand, (
                            "overlap grad-sync applies to the "
                            "standard jitted step; the hand-scheduled "
                            "pipeline owns its own collective "
                            "schedule")))
                        continue
                    if k > 1:
                        pruned.append(Pruned(cand, (
                            f"overlap grad-sync needs a pure data "
                            f"mesh; {second}={k} > 1")))
                        continue
                    if overlap_conflict:
                        pruned.append(Pruned(cand, (
                            f"overlap grad-sync: {overlap_conflict}")))
                        continue
                if partition != "replicated" and data == 1:
                    pruned.append(Pruned(cand, (
                        f"{partition} shards over the data axis; "
                        f"data=1 replicates — identical to the "
                        f"plain candidate")))
                    continue
                reason = (check(axes, devices, batch)
                          or _family_infeasible(facts, axes, batch,
                                                mb))
                if reason:
                    pruned.append(Pruned(cand, reason))
                    continue
                if allowed is not None and not (
                        set(cand.strategy.split("+")) <= allowed):
                    pruned.append(Pruned(cand, (
                        f"strategy {cand.strategy!r} excluded by "
                        f"--strategies")))
                    continue
                feasible.append(cand)
    return feasible, pruned
