"""Auto-layout planner: model + chip count in, launch config out.

The layout-assignment problem the reference scripts solved by EDITING
THREE SCRIPT COPIES (ps/worker roles and task indices were literally
the only diff), Mesh-TensorFlow posed as a per-model search, and the
pjit/TPUv4 paper solved with expert judgment — closed here with the
compiler's own cost model:

1. **Enumerate** (:mod:`candidates`): every mesh factorization x
   parallelism strategy (data / fsdp / zero1 / tensor / expert / pipe
   and their products) valid for the family, device count, and global
   batch. Hard constraints — batch divisibility over the data axis
   (the SAME rule the elastic supervisor applies,
   parallel.mesh.pick_data_width/mesh_infeasible), head divisibility
   over "model", expert divisibility over "expert", layer/microbatch
   divisibility over "pipe" — prune up front, each with its reason
   recorded.
2. **Score** (:mod:`score`): for each survivor, build the REAL jitted
   train step (the same train/step.py / train/pipeline_step.py
   builders the loop uses) over a sharding-annotated ABSTRACT state
   (train.state.abstract_train_state — zero bytes allocated),
   ``lower()+compile()`` it WITHOUT executing, and read XLA's own
   ``cost_analysis``/``memory_analysis`` through the same extraction
   the compiled-program registry uses (observe.device.extract_costs).
   Predicted step time is a roofline:
   ``max(flops/peak_flops, bytes/hbm_bw) + collective_bytes/ici_bw``
   with the collective traffic censused from the program's jaxpr
   (analysis.jaxprcheck's walk). Candidates whose peak-HBM estimate
   exceeds the budget are MARKED infeasible, never silently dropped.
3. **Emit** (:mod:`plan`): a ranked table + ``plan.json``::

       python -m tensorflow_distributed_tpu.analysis.planner \
           --family gpt --devices 8 --batch-size 128

   and ``--plan auto`` on the train CLI, which runs the same search
   and launches with the winner's ``--mesh.*``/``--param-partition``
   config, recording a ``plan`` JSONL record through observe so the
   choice is auditable (observe.report renders it as the "Plan"
   section).

Gated by benchmarks/planbench.py -> PLANBENCH.json: on a CPU-feasible
sweep every feasible candidate is actually executed and the planner's
top pick must land within 15% of the best measured step time, with
the predicted peak-HBM ordering matching ``memory_analysis``'s.
"""

from tensorflow_distributed_tpu.analysis.planner.candidates import (  # noqa: F401
    Candidate, ModelFacts, enumerate_candidates, model_facts)
from tensorflow_distributed_tpu.analysis.planner.plan import (  # noqa: F401
    apply_auto, load_plan, make_plan, render_table, write_plan)
from tensorflow_distributed_tpu.analysis.planner.score import (  # noqa: F401
    Hardware, detect_hardware, mark_feasibility, roofline_ms,
    score_candidates)
