"""``python -m tensorflow_distributed_tpu.analysis.planner`` entry."""

import sys

from tensorflow_distributed_tpu.analysis.planner.plan import main

if __name__ == "__main__":
    sys.exit(main())
