"""Plan emission: the ranked table, ``plan.json``, and ``--plan auto``.

Standalone::

    python -m tensorflow_distributed_tpu.analysis.planner \
        --family gpt --devices 8 --batch-size 128

prints every candidate ranked by predicted step time (mesh, strategy,
predicted ms, peak-HBM, compile wall; infeasible candidates marked,
never dropped) and writes ``plan.json``. On a CPU host the requested
``--devices`` forces the virtual host-platform topology the same way
jaxprcheck's CLI does; on a TPU host the real devices are used.

Train-CLI integration: ``--plan auto`` (train.loop) calls
:func:`apply_auto` before the mesh is built — the winning candidate's
``--mesh.*`` axes, ``--param-partition``, and (pipelined) microbatch
count replace the defaults, and the choice is emitted as a ``plan``
JSONL record through observe so it is auditable next to the run's
step records (observe.report renders the "Plan" section from it).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, Dict, Optional, Sequence

from tensorflow_distributed_tpu.analysis.planner import candidates as cand_lib
from tensorflow_distributed_tpu.analysis.planner import score as score_lib

PLAN_VERSION = 1


def make_plan(family: str, devices: int, batch_size: int,
              size: str = "", seq_len: int = 0,
              strategies: Optional[Sequence[str]] = None,
              microbatches: int = 4, moe_experts: int = 0,
              dropout_rate: float = 0.0,
              compute_dtype: str = "bfloat16",
              hw: Optional[score_lib.Hardware] = None,
              hbm_budget: Optional[float] = None,
              overlap_conflict: Optional[str] = None,
              calibration: str = "") -> Dict[str, Any]:
    """Enumerate + score + rank: the whole planning pass, as a dict
    (the ``plan.json`` schema). ``chosen`` is the best feasible scored
    candidate, or None when nothing is feasible. ``overlap_conflict``
    prunes the overlap strategy with that reason (see
    enumerate_candidates — apply_auto threads the run's knob
    conflicts). ``calibration`` is a calibration.json path
    (calibrate.py): its measured effective rates replace the static
    roofline peaks (ignored when an explicit ``hw`` is passed)."""
    facts = cand_lib.model_facts(family, size, moe_experts=moe_experts)
    seq_len = seq_len or 128
    feasible, pruned = cand_lib.enumerate_candidates(
        facts, devices, batch_size, strategies=strategies,
        microbatches=microbatches, overlap_conflict=overlap_conflict)
    if hw is None:
        cal = None
        if calibration:
            from tensorflow_distributed_tpu.analysis.planner.calibrate \
                import load_calibration
            cal = load_calibration(calibration)
        hw = score_lib.detect_hardware(calibration=cal)
    rows = score_lib.score_candidates(
        feasible, facts, batch_size, hw, seq_len=seq_len, size=size,
        dropout_rate=dropout_rate, compute_dtype=compute_dtype,
        moe_experts=moe_experts, hbm_budget=hbm_budget)
    chosen = next((r for r in rows if r.get("feasible")
                   and isinstance(r.get("step_ms"), (int, float))),
                  None)
    return {
        "version": PLAN_VERSION,
        "family": family,
        "model": cand_lib.FAMILY_MODELS[family],
        "size": size or cand_lib.DEFAULT_SIZES[family],
        "devices": devices,
        "batch_size": batch_size,
        "seq_len": seq_len,
        "facts": dataclasses.asdict(facts),
        "hardware": hw.as_dict(),
        "hbm_budget_bytes": (hbm_budget if hbm_budget is not None
                             else hw.hbm_bytes),
        "candidates": rows,
        "pruned": [{"mesh": p.candidate.mesh,
                    "partition": p.candidate.partition,
                    "strategy": p.candidate.strategy,
                    "reason": p.reason} for p in pruned],
        "chosen": chosen,
    }


def render_table(plan: Dict[str, Any]) -> str:
    """The human table: one ranked row per candidate, the pruned
    shapes with reasons below it."""
    from tensorflow_distributed_tpu.observe.device import human_bytes

    lines = [f"plan: {plan['family']}/{plan['size']} on "
             f"{plan['devices']} device(s) "
             f"({plan['hardware']['device_kind']}), global batch "
             f"{plan['batch_size']}, seq {plan['seq_len']}"]
    lines.append(f"{'rank':<5} {'mesh':<24} {'strategy':<14} "
                 f"{'step_ms':>9} {'peak_hbm':>10} {'compile_s':>9} "
                 f"{'feasible':>9}")
    for i, row in enumerate(plan["candidates"], 1):
        ms = ("-" if row.get("step_ms") is None
              else f"{row['step_ms']:.3f}")
        comp = ("-" if row.get("compile_s") is None
                else f"{row['compile_s']:.2f}")
        feas = "yes" if row.get("feasible") else "NO"
        lines.append(
            f"{i:<5} {cand_lib.format_mesh(row['mesh']):<24} "
            f"{row['strategy']:<14} {ms:>9} "
            f"{human_bytes(row.get('peak_hbm_bytes')):>10} {comp:>9} "
            f"{feas:>9}")
        note = row.get("infeasible_reason") or row.get("error")
        if note:
            lines.append(f"      ^ {note}")
    if plan["pruned"]:
        lines.append("pruned (hard constraints):")
        for p in plan["pruned"]:
            lines.append(f"  {cand_lib.format_mesh(p['mesh']):<24} "
                         f"{p['strategy']:<14} {p['reason']}")
    if plan["chosen"] is not None:
        lines.append(
            f"chosen: {cand_lib.format_mesh(plan['chosen']['mesh'])} "
            f"[{plan['chosen']['strategy']}] predicted "
            f"{plan['chosen']['step_ms']} ms/step")
    else:
        lines.append("chosen: NONE (no feasible scored candidate)")
    return "\n".join(lines)


def write_plan(plan: Dict[str, Any], path: str) -> None:
    with open(path, "w") as f:
        json.dump(plan, f, indent=2, sort_keys=False)
        f.write("\n")


def load_plan(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def plan_record(plan: Dict[str, Any]) -> Dict[str, Any]:
    """The compact, auditable summary emitted as the ``plan`` JSONL
    record (and rendered by observe.report's "Plan" section)."""
    chosen = plan.get("chosen") or {}
    rows = plan.get("candidates", [])
    return {
        "family": plan["family"],
        "size": plan["size"],
        "devices": plan["devices"],
        "batch_size": plan["batch_size"],
        "mesh": chosen.get("mesh"),
        "strategy": chosen.get("strategy"),
        "partition": chosen.get("partition"),
        "predicted_step_ms": chosen.get("step_ms"),
        "predicted_peak_hbm_bytes": chosen.get("peak_hbm_bytes"),
        "candidates": len(rows),
        "feasible": sum(1 for r in rows if r.get("feasible")),
        "infeasible": sum(1 for r in rows if not r.get("feasible")),
        "pruned": len(plan.get("pruned", [])),
        # Which roofline predicted: None = static tables, else the
        # calibration profile's id (the train loop's plan_drift record
        # and the bench stamps carry the same id).
        "calibration_id": (plan.get("hardware") or {}).get(
            "calibration_id"),
    }


def apply_auto(cfg) -> Dict[str, Any]:
    """``--plan auto``: plan for the run's model/devices/batch and
    REWRITE ``cfg`` (mesh axes, param_partition, pipelined
    microbatches) to the winner. Called by train.loop before the mesh
    is built; config.validate has already vetted the combination.
    Returns the ``plan`` record for the run's sinks. Raises when no
    candidate is feasible — launching on a known-infeasible layout
    would just move the failure into XLA."""
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.parallel.mesh import (
        alive_devices, is_chief)

    family = cand_lib.MODEL_FAMILIES[cfg.model]
    devices = len(alive_devices())
    plan = make_plan(
        family, devices, cfg.batch_size, size=cfg.model_size,
        seq_len=cfg.seq_len, microbatches=cfg.pipeline_microbatches,
        moe_experts=cfg.moe_experts, dropout_rate=cfg.dropout_rate,
        compute_dtype=cfg.compute_dtype,
        hbm_budget=(cfg.plan_hbm_budget_gb * 1e9
                    if cfg.plan_hbm_budget_gb else None),
        # Knobs the overlap launch would reject (non-elementwise
        # optimizer, grad clip, ce_chunk, ...) prune the strategy here
        # — picking it would just crash the re-validate after the plan.
        overlap_conflict=cfg.overlap_grad_sync_conflict(),
        calibration=cfg.plan_calibration)
    if is_chief():
        print(render_table(plan), flush=True)
    chosen = plan["chosen"]
    if chosen is None:
        raise ValueError(
            f"--plan auto: no feasible candidate for {family} on "
            f"{devices} device(s) with batch {cfg.batch_size} — see "
            f"the table above for per-candidate reasons")
    cfg.mesh = MeshConfig(**chosen["mesh"])
    if chosen["partition"] == "overlap":
        # The overlap strategy launches as zero1 slots + the explicit
        # bucketed grad sync (Candidate.cli_args says the same).
        cfg.param_partition = "zero1"
        cfg.grad_sync = "overlap"
    else:
        cfg.param_partition = chosen["partition"]
    if family == "pipelined" and chosen.get("microbatches"):
        cfg.pipeline_microbatches = chosen["microbatches"]
    return plan_record(plan)


def init_backend(n_devices: int = 0, tag: str = "planner") -> str:
    """Backend init for the planner-facing CLIs (this module's main
    and benchmarks/planbench — ONE copy of the dance): force the
    virtual CPU host-platform device count to the requested size (the
    jaxprcheck CLI precedent — flags must land before the backend is
    first USED), and fall back to CPU when the configured accelerator
    can't come up (the bench.py precedent). Returns the effective
    platform."""
    if n_devices and "--xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax
    try:
        jax.devices()
    except RuntimeError as e:
        print(f"[{tag}] accelerator backend unavailable "
              f"({str(e).splitlines()[0]}); retrying on CPU",
              file=sys.stderr, flush=True)
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # a backend initialized after all — use it
        jax.devices()
    return jax.default_backend()


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.analysis.planner",
        description="cost-model-driven auto-layout: rank every valid "
                    "mesh x strategy for a model family and device "
                    "count, scored by AOT-compiling the real train "
                    "step (no execution)")
    parser.add_argument("--family", required=True,
                        choices=sorted(cand_lib.FAMILY_MODELS))
    parser.add_argument("--devices", type=int, default=0,
                        help="device count to plan for (default: all "
                        "visible; on CPU forces that many virtual "
                        "devices)")
    parser.add_argument("--batch-size", type=int, default=128,
                        help="global batch the plan must divide "
                        "(serve family: the decode slot count)")
    parser.add_argument("--size", default="",
                        help="family size preset (tiny or the GPT-2 "
                        "ladder; default: the family's factory "
                        "default)")
    parser.add_argument("--seq-len", type=int, default=0)
    parser.add_argument("--microbatches", type=int, default=4,
                        help="pipelined: microbatch floor (raised to "
                        "the pipe width when needed)")
    parser.add_argument("--moe-experts", type=int, default=0)
    parser.add_argument("--strategies", default="",
                        help="comma-separated strategy parts to allow "
                        "(data,fsdp,zero1,tensor,expert,pipe); "
                        "default all")
    parser.add_argument("--compute-dtype", default="bfloat16",
                        choices=("bfloat16", "float32"))
    parser.add_argument("--hbm-budget-gb", type=float, default=0.0,
                        help="per-device HBM budget (default: the "
                        "device's own memory_stats limit when it "
                        "reports one)")
    parser.add_argument("--peak-tflops", type=float, default=0.0)
    parser.add_argument("--hbm-gbps", type=float, default=0.0)
    parser.add_argument("--ici-gbps", type=float, default=0.0)
    parser.add_argument("--calibration", default="",
                        help="calibration.json (calibrate.py): "
                        "measured effective rates replace the static "
                        "table peaks; explicit --peak-tflops/"
                        "--hbm-gbps/--ici-gbps still win")
    parser.add_argument("--out", default="plan.json",
                        help="plan JSON path ('' = don't write)")
    args = parser.parse_args(argv)
    init_backend(args.devices)
    import jax
    devices = args.devices or len(jax.devices())
    if devices > len(jax.devices()):
        print(f"planner: asked to plan {devices} devices but only "
              f"{len(jax.devices())} are visible (backend initialized "
              f"before the CLI could force a CPU topology?)",
              file=sys.stderr)
        return 2
    cal = None
    if args.calibration:
        from tensorflow_distributed_tpu.analysis.planner.calibrate \
            import load_calibration
        cal = load_calibration(args.calibration)
    hw = score_lib.detect_hardware(
        peak_tflops=args.peak_tflops, hbm_gbps=args.hbm_gbps,
        ici_gbps=args.ici_gbps, hbm_budget_gb=args.hbm_budget_gb,
        calibration=cal)
    strategies = ([s.strip() for s in args.strategies.split(",")
                   if s.strip()] or None)
    plan = make_plan(
        args.family, devices, args.batch_size, size=args.size,
        seq_len=args.seq_len, strategies=strategies,
        microbatches=args.microbatches, moe_experts=args.moe_experts,
        compute_dtype=args.compute_dtype, hw=hw,
        hbm_budget=(args.hbm_budget_gb * 1e9 if args.hbm_budget_gb
                    else None))
    print(render_table(plan))
    if args.out:
        write_plan(plan, args.out)
        print(f"planner: wrote {args.out}")
    return 0 if plan["chosen"] is not None else 1


if __name__ == "__main__":
    sys.exit(main())
