"""graftcheck lint driver.

Usage::

    python -m tensorflow_distributed_tpu.analysis.lint [paths...]
    python -m tensorflow_distributed_tpu.analysis.lint --list-rules

Paths may be files or directories (recursed for ``*.py``); the default
is the package itself — the self-hosting configuration tier-1 runs via
``scripts/lint.sh``. Exit status: 0 clean, 1 findings, 2 usage/parse
errors. Pure stdlib: linting must never require (or pay for) a jax
import.

Suppressions: ``# graftcheck: disable=<rule>[,<rule>] -- <reason>`` on
the flagged statement's lines or the comment line directly above. The
reason text is for the reviewer; write one.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Iterable, List, Optional, Sequence

from tensorflow_distributed_tpu.analysis.rules import (
    CATALOG, Finding, ModuleContext, check_module)

PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith((".", "__pycache__")))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            yield path


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    """Lint one module's source text (the unit-test entry point)."""
    return check_module(ModuleContext(path, source))


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        findings.extend(lint_source(source, path))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.analysis.lint",
        description="graftcheck: static analysis for the TPU stack's "
                    "jax footguns (host syncs, key reuse, jit-in-loop, "
                    "use-after-donation, effects under trace)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories (default: the "
                             "package itself)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)
    if args.list_rules:
        width = max(len(name) for name in CATALOG)
        for name, desc in sorted(CATALOG.items()):
            print(f"{name:<{width}}  {desc}")
        return 0
    paths = args.paths or [PACKAGE_ROOT]
    try:
        findings = lint_paths(paths)
    except (OSError, SyntaxError) as e:
        print(f"graftcheck: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.render())
    if findings:
        print(f"graftcheck: {len(findings)} finding"
              f"{'s' if len(findings) != 1 else ''} "
              f"(suppress intentional ones with "
              f"'# graftcheck: disable=<rule> -- <reason>')",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
