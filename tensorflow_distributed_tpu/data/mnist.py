"""MNIST data layer: idx parsing, splits, and process-sharded batching.

TPU-native replacement for the reference's
``input_data.read_data_sets(...)`` + ``mnist.train.next_batch(batch)``
path (mnist_python_m.py:133,291; mnist_single.py:14-15), with two
deliberate upgrades, both flagged in SURVEY.md N13:

1. **Disjoint per-process sharding.** The reference's workers each
   sampled MNIST independently at random — the same image could be in
   both replicas' batches of one sync step. Here the global batch is
   partitioned: process p takes rows [p*B/P, (p+1)*B/P) of each global
   batch, so an N-way run consumes exactly the same sample stream as a
   1-way run (the basis of the N-vs-1 parity tests).
2. **No network download.** The reference downloaded idx.gz files from
   the internet at startup (even the ps did, mnist_python_m.py:133).
   This loader parses idx files already on disk, and falls back to a
   deterministic synthetic digit set in zero-egress environments.

The numpy path below is the reference implementation; the C++ host
runtime (``tensorflow_distributed_tpu.native``, native/tfd_native.cc)
backs the idx parse here and the threaded batch gather in the
uint8-storage variant of this data path (data/u8.py, selected with
``data_backend="u8_native"`` or used directly by bench.py).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Tuple

import numpy as np

from tensorflow_distributed_tpu.data.batcher import Batcher

# idx magic numbers: 0x801 = unsigned-byte 1-D (labels),
# 0x803 = unsigned-byte 3-D (images).
_IDX_LABELS_MAGIC = 2049
_IDX_IMAGES_MAGIC = 2051

_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def parse_idx(raw: bytes) -> np.ndarray:
    """Parse idx-format bytes (the format the reference's loader consumed)."""
    if len(raw) < 8:
        raise ValueError("idx: truncated header")
    magic = struct.unpack(">i", raw[:4])[0]
    if magic == _IDX_LABELS_MAGIC:
        (n,) = struct.unpack(">i", raw[4:8])
        data = np.frombuffer(raw, dtype=np.uint8, count=n, offset=8)
        return data.copy()
    if magic == _IDX_IMAGES_MAGIC:
        n, rows, cols = struct.unpack(">iii", raw[4:16])
        data = np.frombuffer(raw, dtype=np.uint8, count=n * rows * cols,
                             offset=16)
        return data.reshape(n, rows, cols).copy()
    raise ValueError(f"idx: unknown magic {magic}")


def _read_idx_file(path: str) -> np.ndarray:
    # Fast path: the C++ runtime parses idx(.gz) off the GIL
    # (native/tfd_native.cc tfd_idx_read); numpy fallback otherwise.
    from tensorflow_distributed_tpu.native import runtime as native
    if native.available():
        try:
            return native.idx_read(path)
        except (IOError, KeyError):
            pass
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return parse_idx(f.read())


@dataclasses.dataclass
class Dataset:
    """One split: images float32 [N,28,28,1] in [0,1]; labels int32 [N]."""

    images: np.ndarray
    labels: np.ndarray
    name: str = "mnist"

    def __post_init__(self):
        assert self.images.shape[0] == self.labels.shape[0]

    def __len__(self) -> int:
        return self.images.shape[0]

    def take(self, n: int) -> "Dataset":
        return Dataset(self.images[:n], self.labels[:n], self.name)


def _to_splits(train_images, train_labels, test_images, test_labels,
               validation_size: int, name: str
               ) -> Tuple[Dataset, Dataset, Dataset]:
    """Split exactly like the reference loader: the first
    ``validation_size`` (5000) training rows become the validation split —
    which is what the reference validates on, not the test split
    (mnist_python_m.py:313, SURVEY.md Appendix B.8)."""
    if validation_size >= len(train_images):
        # Fail at the real cause — downstream the Batcher would raise
        # a misleading "dataset smaller than one global batch" on the
        # empty train split.
        raise ValueError(
            f"validation_size {validation_size} leaves no training "
            f"rows ({name} train split has {len(train_images)}); "
            "lower --validation-size")
    val = Dataset(train_images[:validation_size], train_labels[:validation_size],
                  name)
    train = Dataset(train_images[validation_size:],
                    train_labels[validation_size:], name)
    test = Dataset(test_images, test_labels, name)
    return train, val, test


def _prep_images(u8: np.ndarray) -> np.ndarray:
    return (u8.astype(np.float32) / 255.0)[..., None]


def load_mnist(data_dir: str, validation_size: int = 5000
               ) -> Tuple[Dataset, Dataset, Dataset]:
    """Load real MNIST idx files from ``data_dir`` (plain or .gz)."""
    arrays = {}
    for key, fname in _FILES.items():
        for cand in (os.path.join(data_dir, fname),
                     os.path.join(data_dir, fname + ".gz")):
            if os.path.exists(cand):
                arrays[key] = _read_idx_file(cand)
                break
        else:
            raise FileNotFoundError(
                f"MNIST file {fname}[.gz] not found in {data_dir}. "
                "This environment has no network egress; place idx files "
                "there or use dataset='synthetic'.")
    return _to_splits(
        _prep_images(arrays["train_images"]), arrays["train_labels"].astype(np.int32),
        _prep_images(arrays["test_images"]), arrays["test_labels"].astype(np.int32),
        validation_size, "mnist")


# --- synthetic digits (zero-egress fallback) -----------------------------
# 7x5 bitmap glyphs for 0-9; rendered with random placement, scaling noise
# and pixel noise into 28x28. Learnable to >99% by the reference CNN, so
# accuracy-bar integration tests stay meaningful without the real files.
_GLYPHS = [
    "01110 10001 10011 10101 11001 10001 01110",  # 0
    "00100 01100 00100 00100 00100 00100 01110",  # 1
    "01110 10001 00001 00010 00100 01000 11111",  # 2
    "11111 00010 00100 00010 00001 10001 01110",  # 3
    "00010 00110 01010 10010 11111 00010 00010",  # 4
    "11111 10000 11110 00001 00001 10001 01110",  # 5
    "00110 01000 10000 11110 10001 10001 01110",  # 6
    "11111 00001 00010 00100 01000 01000 01000",  # 7
    "01110 10001 10001 01110 10001 10001 01110",  # 8
    "01110 10001 10001 01111 00001 00010 01100",  # 9
]


def _glyph_array(d: int) -> np.ndarray:
    rows = _GLYPHS[d].split()
    return np.array([[int(c) for c in r] for r in rows], dtype=np.float32)


def synthetic_mnist(n_train: int = 12000, n_test: int = 2000,
                    validation_size: int = 1000, seed: int = 0
                    ) -> Tuple[Dataset, Dataset, Dataset]:
    """Deterministic MNIST-shaped synthetic digit dataset."""
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    images = np.zeros((n, 28, 28), dtype=np.float32)
    glyphs = [np.kron(_glyph_array(d), np.ones((3, 3), np.float32))
              for d in range(10)]  # 21x15
    for i in range(n):
        g = glyphs[labels[i]]
        inten = rng.uniform(0.75, 1.0)
        oy = rng.integers(0, 28 - g.shape[0] + 1)
        ox = rng.integers(0, 28 - g.shape[1] + 1)
        images[i, oy:oy + g.shape[0], ox:ox + g.shape[1]] = g * inten
    images += rng.normal(0.0, 0.05, size=images.shape).astype(np.float32)
    images = np.clip(images, 0.0, 1.0)[..., None]
    return _to_splits(images[:n_train], labels[:n_train],
                      images[n_train:], labels[n_train:],
                      validation_size, "synthetic")


def load_dataset(dataset: str, data_dir: str, seed: int = 0,
                 validation_size: int = 5000
                 ) -> Tuple[Dataset, Dataset, Dataset]:
    """Dispatch over every vision dataset family. Real datasets
    ('mnist', 'cifar10') fall back to their synthetic twins with a
    warning when files are absent (zero-egress environments)."""
    from tensorflow_distributed_tpu.data import cifar

    if dataset == "synthetic":
        return synthetic_mnist(seed=seed)
    if dataset == "mnist":
        try:
            return load_mnist(data_dir, validation_size)
        except FileNotFoundError as e:
            print(f"[data] {e} — falling back to synthetic digits.")
            # Honor explicit small splits; cap at the synthetic
            # twin's own default (its train set is far smaller
            # than real MNIST, so the real-dataset default of 5000
            # would eat half of it).
            return synthetic_mnist(seed=seed,
                                   validation_size=min(validation_size,
                                                       1000))
    if dataset == "cifar10":
        try:
            return cifar.load_cifar10(data_dir, validation_size)
        except FileNotFoundError as e:
            print(f"[data] {e} — falling back to synthetic cifar10.")
            return cifar.synthetic_cifar10(
                seed=seed, validation_size=min(validation_size, 1000))
    if dataset == "cifar10_synthetic":
        return cifar.synthetic_cifar10(seed=seed)
    if dataset == "imagenet_synthetic":
        return cifar.synthetic_imagenet(seed=seed)
    raise ValueError(f"unknown dataset {dataset!r}")


class ShardedBatcher(Batcher):
    """(images, labels) batches over a Dataset — the generic
    data.batcher.Batcher with a vision gather. The trailing partial
    batch of each epoch is always dropped: SPMD steps need static
    shapes (XLA recompiles per shape)."""

    def __init__(self, ds: Dataset, global_batch: int, seed: int = 0,
                 num_processes: int = 1, process_index: int = 0):
        self.ds = ds
        super().__init__(
            n_items=len(ds), global_batch=global_batch,
            gather=lambda idx: (ds.images[idx], ds.labels[idx]),
            seed=seed, num_processes=num_processes,
            process_index=process_index)
