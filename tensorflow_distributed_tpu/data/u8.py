"""uint8-backed dataset storage + natively-gathered batching.

The reference's loader kept MNIST as float arrays after parsing
(SURVEY.md N13) and paid a fresh float gather + feed_dict copy per step
(N14). This variant keeps uint8 bytes resident (4x less steady-state
host RAM; the transient peak still pays the float parse until the
loaders grow a direct-to-u8 path) and materializes each batch with the
C++ threaded gather
(native/tfd_native.cc::tfd_gather_u8_f32) — u8 -> f32 normalize fanned
across host cores, off the GIL — falling back to numpy where the
native library is unavailable.

Batch *order* comes from the shared ``data.batcher.Batcher``
permutation, so the sample stream is bit-identical to the float
``ShardedBatcher``'s regardless of backend; only the gather mechanics
differ. (The C++ ``NativePrefetcher`` with its own shuffle is for
throughput paths that don't need the deterministic stream.)
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tensorflow_distributed_tpu.data.batcher import Batcher
from tensorflow_distributed_tpu.data.mnist import Dataset


@dataclasses.dataclass
class U8Dataset:
    """images uint8 [N, ...]; labels int32 [N]; float = u8 * scale."""

    images: np.ndarray
    labels: np.ndarray
    scale: float = 1.0 / 255.0
    name: str = "u8"

    def __post_init__(self):
        assert self.images.dtype == np.uint8
        assert self.images.shape[0] == self.labels.shape[0]

    def __len__(self) -> int:
        return self.images.shape[0]

    @classmethod
    def from_float(cls, ds: Dataset) -> "U8Dataset":
        """Quantize a float [0,1] Dataset to u8 storage. Lossless for
        data that was u8 on disk (real MNIST/CIFAR); <=0.5/255 rounding
        error for synthetic floats."""
        u8 = np.clip(np.rint(ds.images * 255.0), 0, 255).astype(np.uint8)
        return cls(u8, np.ascontiguousarray(ds.labels, np.int32),
                   name=ds.name)

    def gather(self, idx: np.ndarray):
        from tensorflow_distributed_tpu.native import runtime as native
        images = native.gather_u8_f32(self.images, idx, self.scale)
        return images, self.labels[idx]

    def gather_raw(self, idx: np.ndarray):
        """uint8 batch, no conversion — for pipelines that normalize on
        device (transfer 1/4 the bytes; train.multistep.preprocess)."""
        return self.images[idx], self.labels[idx]


class U8ShardedBatcher(Batcher):
    """Same stream contract as data.mnist.ShardedBatcher, native gather.
    ``raw=True`` yields uint8 batches (device-side normalization)."""

    def __init__(self, ds: U8Dataset, global_batch: int, seed: int = 0,
                 num_processes: int = 1, process_index: int = 0,
                 raw: bool = False):
        self.ds = ds
        super().__init__(n_items=len(ds), global_batch=global_batch,
                         gather=ds.gather_raw if raw else ds.gather,
                         seed=seed, num_processes=num_processes,
                         process_index=process_index)
