"""Data layer: dataset loading + sharded host->device batching.

Replaces the reference's ``tensorflow.examples.tutorials.mnist.input_data``
loader and per-step feed_dict path (SURVEY.md N13/N14).
"""

from tensorflow_distributed_tpu.data.mnist import (  # noqa: F401
    Dataset,
    ShardedBatcher,
    load_dataset,
    load_mnist,
    parse_idx,
    synthetic_mnist,
)
from tensorflow_distributed_tpu.data.prefetch import prefetch_to_mesh  # noqa: F401
