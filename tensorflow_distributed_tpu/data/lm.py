"""Synthetic masked-LM data (zero-egress stand-in for a text corpus).

Sequences carry learnable local structure: each sequence interleaves two
period-2 token streams (a at even positions, b at odd positions, with
occasional within-period substitutions), so a masked position is
recoverable from unmasked neighbors by attention — enough signal for
integration tests and benchmarks to show real learning, none of the IO
of a corpus. Deterministic per seed.

Batch layout (matches the transformer's activation sharding): every
array is [B, L] — ``tokens`` (input with [MASK]=vocab_size at masked
positions), ``targets`` (original ids), ``mask`` (1.0 at masked
positions). Sharded P("data", "seq") by the MLM batch sharding.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

from tensorflow_distributed_tpu.data.batcher import Batcher


@dataclasses.dataclass
class LmDataset:
    tokens: np.ndarray    # [N, L] inputs with masks applied
    targets: np.ndarray   # [N, L] original ids
    # [N, L] float {0,1}; None = all-ones, synthesized per batch (the
    # CLM case — storing a corpus-sized constant would waste 4 bytes
    # per token of host RAM).
    mask: "np.ndarray | None"
    vocab_size: int

    def __len__(self) -> int:
        return self.tokens.shape[0]

    def batch(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        # Storage may be narrow (uint8 byte corpora); models take int32.
        tokens = self.tokens[idx].astype(np.int32, copy=False)
        targets = self.targets[idx].astype(np.int32, copy=False)
        mask = (np.ones(targets.shape, np.float32) if self.mask is None
                else self.mask[idx])
        return {"tokens": tokens, "targets": targets, "mask": mask}


def synthetic_mlm(n: int = 2048, seq_len: int = 128, vocab_size: int = 64,
                  mask_rate: float = 0.15, seed: int = 0) -> LmDataset:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, vocab_size, size=(n, 1))
    b = rng.integers(0, vocab_size, size=(n, 1))
    seq = np.where(np.arange(seq_len)[None, :] % 2 == 0, a, b)
    # Sparse substitutions so the task isn't pure copy.
    noise = rng.random((n, seq_len)) < 0.02
    seq = np.where(noise, rng.integers(0, vocab_size, size=(n, seq_len)), seq)
    seq = seq.astype(np.int32)

    mask = (rng.random((n, seq_len)) < mask_rate)
    # Guarantee at least one masked position per row.
    none_masked = ~mask.any(axis=1)
    mask[none_masked, 0] = True
    tokens = np.where(mask, vocab_size, seq).astype(np.int32)  # [MASK] id
    return LmDataset(tokens=tokens, targets=seq,
                     mask=mask.astype(np.float32), vocab_size=vocab_size)


def synthetic_clm(n: int = 2048, seq_len: int = 128, vocab_size: int = 64,
                  seed: int = 0) -> LmDataset:
    """Synthetic causal-LM data: each sequence is an arithmetic token
    progression x_t = (start + stride*t) mod V with sparse substitution
    noise. Predicting x_{t+1} requires inferring the per-sequence
    stride from earlier tokens — learnable only through (causal)
    attention, so integration tests show real next-token learning.

    Reuses the {tokens, targets, mask} layout: seq_len+1 tokens are
    generated so targets (the inputs shifted left one) are genuine
    continuations at every position — the mask is all-ones.
    """
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab_size, size=(n, 1))
    stride = rng.integers(1, 6, size=(n, 1))
    t = np.arange(seq_len + 1)[None, :]
    seq = ((start + stride * t) % vocab_size).astype(np.int32)
    noise = rng.random((n, seq_len + 1)) < 0.02
    seq = np.where(noise, rng.integers(0, vocab_size,
                                       size=(n, seq_len + 1)), seq)
    seq = seq.astype(np.int32)
    return LmDataset(tokens=seq[:, :-1], targets=seq[:, 1:],
                     mask=np.ones((n, seq_len), np.float32),
                     vocab_size=vocab_size)


def text_clm(path: str, seq_len: int = 128, seed: int = 0,
             val_fraction: float = 0.1) -> tuple:
    """Byte-level causal-LM datasets from a LOCAL text/binary file —
    a real corpus path with zero egress and zero tokenizer downloads:
    the vocabulary is the 256 byte values (char-level GPT, the nanoGPT
    recipe). Returns (train, val) LmDatasets in the same
    {tokens, targets, mask} layout as the synthetic generators.

    The file is split into non-overlapping (seq_len + 1)-byte windows;
    the last seq_len bytes of each window are the targets of the first
    seq_len. Windows are deterministically shuffled per ``seed``, and
    the LAST ``val_fraction`` of the shuffle is held out — a random
    split, so train and val share the same distribution even for files
    whose style drifts start to end.
    """
    data = np.fromfile(path, dtype=np.uint8)
    win = seq_len + 1
    n = len(data) // win
    if n < 2:
        raise ValueError(
            f"{path!r}: {len(data)} bytes < 2 windows of {win} "
            f"(need seq_len+1 bytes per sequence)")
    # Stay uint8 on the host (1 byte/token; batch() casts per batch)
    # and skip the all-ones mask entirely — a 2 GB corpus costs ~2 GB
    # here, not ~16.
    seq = data[:n * win].reshape(n, win)
    order = np.random.default_rng(seed).permutation(n)
    seq = seq[order]
    n_val = max(1, int(n * val_fraction))

    def make(rows):
        return LmDataset(tokens=rows[:, :-1], targets=rows[:, 1:],
                         mask=None, vocab_size=256)

    return make(seq[:-n_val]), make(seq[-n_val:])


class LmBatcher(Batcher):
    """{tokens, targets, mask} batches over an LmDataset — the generic
    data.batcher.Batcher with an LM gather."""

    def __init__(self, ds: LmDataset, global_batch: int, seed: int = 0,
                 num_processes: int = 1, process_index: int = 0):
        self.ds = ds
        super().__init__(
            n_items=len(ds), global_batch=global_batch, gather=ds.batch,
            seed=seed, num_processes=num_processes,
            process_index=process_index)
