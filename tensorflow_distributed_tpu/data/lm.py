"""Synthetic masked-LM data (zero-egress stand-in for a text corpus).

Sequences carry learnable local structure: each sequence interleaves two
period-2 token streams (a at even positions, b at odd positions, with
occasional within-period substitutions), so a masked position is
recoverable from unmasked neighbors by attention — enough signal for
integration tests and benchmarks to show real learning, none of the IO
of a corpus. Deterministic per seed.

Batch layout (matches the transformer's activation sharding): every
array is [B, L] — ``tokens`` (input with [MASK]=vocab_size at masked
positions), ``targets`` (original ids), ``mask`` (1.0 at masked
positions). Sharded P("data", "seq") by the MLM batch sharding.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

from tensorflow_distributed_tpu.data.batcher import Batcher


@dataclasses.dataclass
class LmDataset:
    tokens: np.ndarray    # [N, L] inputs with masks applied
    targets: np.ndarray   # [N, L] original ids
    # [N, L] float {0,1}; None = all-ones, synthesized per batch (the
    # CLM case — storing a corpus-sized constant would waste 4 bytes
    # per token of host RAM).
    mask: "np.ndarray | None"
    vocab_size: int

    def __len__(self) -> int:
        return self.tokens.shape[0]

    def batch(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        # Storage may be narrow (uint8 byte corpora); models take int32.
        tokens = self.tokens[idx].astype(np.int32, copy=False)
        targets = self.targets[idx].astype(np.int32, copy=False)
        mask = (np.ones(targets.shape, np.float32) if self.mask is None
                else self.mask[idx])
        return {"tokens": tokens, "targets": targets, "mask": mask}


def synthetic_mlm(n: int = 2048, seq_len: int = 128, vocab_size: int = 64,
                  mask_rate: float = 0.15, seed: int = 0) -> LmDataset:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, vocab_size, size=(n, 1))
    b = rng.integers(0, vocab_size, size=(n, 1))
    seq = np.where(np.arange(seq_len)[None, :] % 2 == 0, a, b)
    # Sparse substitutions so the task isn't pure copy.
    noise = rng.random((n, seq_len)) < 0.02
    seq = np.where(noise, rng.integers(0, vocab_size, size=(n, seq_len)), seq)
    seq = seq.astype(np.int32)

    mask = (rng.random((n, seq_len)) < mask_rate)
    # Guarantee at least one masked position per row.
    none_masked = ~mask.any(axis=1)
    mask[none_masked, 0] = True
    tokens = np.where(mask, vocab_size, seq).astype(np.int32)  # [MASK] id
    return LmDataset(tokens=tokens, targets=seq,
                     mask=mask.astype(np.float32), vocab_size=vocab_size)


def synthetic_clm(n: int = 2048, seq_len: int = 128, vocab_size: int = 64,
                  seed: int = 0) -> LmDataset:
    """Synthetic causal-LM data: each sequence is an arithmetic token
    progression x_t = (start + stride*t) mod V with sparse substitution
    noise. Predicting x_{t+1} requires inferring the per-sequence
    stride from earlier tokens — learnable only through (causal)
    attention, so integration tests show real next-token learning.

    Reuses the {tokens, targets, mask} layout: seq_len+1 tokens are
    generated so targets (the inputs shifted left one) are genuine
    continuations at every position — the mask is all-ones.
    """
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab_size, size=(n, 1))
    stride = rng.integers(1, 6, size=(n, 1))
    t = np.arange(seq_len + 1)[None, :]
    seq = ((start + stride * t) % vocab_size).astype(np.int32)
    noise = rng.random((n, seq_len + 1)) < 0.02
    seq = np.where(noise, rng.integers(0, vocab_size,
                                       size=(n, seq_len + 1)), seq)
    seq = seq.astype(np.int32)
    return LmDataset(tokens=seq[:, :-1], targets=seq[:, 1:],
                     mask=np.ones((n, seq_len), np.float32),
                     vocab_size=vocab_size)


def train_or_load_bpe(path: str, vocab_size: int):
    """Byte-level BPE trained ON the local corpus (HF ``tokenizers``,
    which is baked into this image — no downloads, no egress).
    UTF-8 TEXT files only (the trainer reads UTF-8; binary corpora
    use tokenizer="byte") — text_clm validates that up front, and
    within that contract ByteLevel pre-tokenization is lossless.

    The trained vocab caches next to the corpus as
    ``<path>.bpe<V>.<contenthash>.json`` — keyed by CONTENT, so
    editing the corpus retrains instead of silently reusing a vocab
    whose alphabet may not cover the new text (BPE has no unk token
    here; unseen symbols would be silently dropped). The save is
    atomic (tmp + os.replace): concurrent processes on a shared
    filesystem at worst train redundantly, never read torn JSON."""
    import hashlib
    import os

    from tokenizers import Tokenizer, decoders, models, pre_tokenizers
    from tokenizers import trainers

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    cache = f"{path}.bpe{vocab_size}.{h.hexdigest()[:12]}.json"
    if os.path.exists(cache):
        return Tokenizer.from_file(cache)
    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    tok.train([path], trainers.BpeTrainer(vocab_size=vocab_size,
                                          special_tokens=[],
                                          show_progress=False))
    tmp = f"{cache}.tmp.{os.getpid()}"
    tok.save(tmp)
    os.replace(tmp, cache)
    return tok


def _encode_corpus(path: str, tok) -> np.ndarray:
    """Encode the corpus line-by-line into a compact uint16 buffer
    (array.array, ~2 bytes/token transient — not a list of boxed
    ints). newline="" disables universal-newline translation so the
    encoder sees exactly the bytes the trainer saw (CRLF preserved);
    errors="strict" + the text_clm validation guarantee UTF-8.
    Encoding per line (overlong lines chunked at 1 MiB) only forbids
    merges across those boundaries — standard and deterministic."""
    import array

    ids = array.array("H")
    lim = 1 << 20
    with open(path, "r", encoding="utf-8", errors="strict",
              newline="") as f:
        for line in f:
            for i in range(0, len(line), lim):
                ids.extend(tok.encode(line[i:i + lim]).ids)
    return np.frombuffer(ids.tobytes(), dtype=np.uint16).copy()


def text_codec(path: str, tokenizer: str = "byte",
               bpe_vocab_size: int = 8192):
    """(encode: str -> list[int], decode: ids -> str, vocab_size)
    applying the SAME tokenization text_clm applies to the corpus at
    ``path`` — the generation-side counterpart (cli --mode generate
    encodes the prompt and decodes the continuation with this)."""
    if tokenizer == "byte":
        return (lambda s: list(s.encode("utf-8")),
                lambda ids: bytes(int(i) & 0xFF for i in ids).decode(
                    "utf-8", errors="replace"),
                256)
    if tokenizer == "bpe":
        tok = train_or_load_bpe(path, bpe_vocab_size)
        return (lambda s: tok.encode(s).ids,
                lambda ids: tok.decode([int(i) for i in ids]),
                tok.get_vocab_size())
    raise ValueError(f"tokenizer {tokenizer!r}; have ('byte', 'bpe')")


def text_clm(path: str, seq_len: int = 128, seed: int = 0,
             val_fraction: float = 0.1, tokenizer: str = "byte",
             bpe_vocab_size: int = 8192) -> tuple:
    """Causal-LM datasets from a LOCAL text/binary file — a real corpus
    path with zero egress. Two tokenizations:

    - "byte" (default): the vocabulary is the 256 byte values
      (char-level GPT, the nanoGPT recipe) — works on ANY file.
    - "bpe": a byte-level BPE of ``bpe_vocab_size`` merges trained on
      THIS corpus (train_or_load_bpe) — the subword path real LM
      training uses; ~3-4x more text per window at the same seq_len.

    Returns (train, val) LmDatasets in the same {tokens, targets, mask}
    layout as the synthetic generators. The token stream is split into
    non-overlapping (seq_len + 1)-token windows; the last seq_len
    tokens of each window are the targets of the first seq_len.
    Windows are deterministically shuffled per ``seed``, and the LAST
    ``val_fraction`` of the shuffle is held out — a random split, so
    train and val share the same distribution even for files whose
    style drifts start to end.
    """
    if tokenizer == "byte":
        data = np.fromfile(path, dtype=np.uint8)
        vocab = 256
    elif tokenizer == "bpe":
        if not 2 <= bpe_vocab_size <= 65536:
            raise ValueError(
                f"bpe_vocab_size must be in [2, 65536] (uint16 storage),"
                f" got {bpe_vocab_size}")
        import codecs

        dec = codecs.getincrementaldecoder("utf-8")()
        try:
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    dec.decode(chunk)      # O(chunk) memory
                dec.decode(b"", final=True)
        except UnicodeDecodeError as e:
            raise ValueError(
                f"{path!r} is not valid UTF-8 ({e}); "
                "tokenizer='bpe' needs a text corpus — use "
                "tokenizer='byte' for binary files") from None
        tok = train_or_load_bpe(path, bpe_vocab_size)
        data = _encode_corpus(path, tok)
        # The trained vocab can come out smaller than requested on
        # tiny corpora; the MODEL vocab must cover every emitted id
        # (guarded: the too-small error below fires before max() on
        # a near-empty stream).
        vocab = int(tok.get_vocab_size())
        if len(data):
            vocab = max(vocab, int(data.max()) + 1)
    else:
        raise ValueError(f"tokenizer {tokenizer!r}; have ('byte', 'bpe')")
    win = seq_len + 1
    n = len(data) // win
    if n < 2:
        raise ValueError(
            f"{path!r}: {len(data)} tokens < 2 windows of {win} "
            f"(need seq_len+1 tokens per sequence)")
    # Stay narrow on the host (1-2 bytes/token; batch() casts per
    # batch) and skip the all-ones mask entirely — a 2 GB corpus costs
    # ~2 GB here, not ~16.
    seq = data[:n * win].reshape(n, win)
    order = np.random.default_rng(seed).permutation(n)
    seq = seq[order]
    n_val = max(1, int(n * val_fraction))

    def make(rows):
        return LmDataset(tokens=rows[:, :-1], targets=rows[:, 1:],
                         mask=None, vocab_size=vocab)

    return make(seq[:-n_val]), make(seq[-n_val:])


class LmBatcher(Batcher):
    """{tokens, targets, mask} batches over an LmDataset — the generic
    data.batcher.Batcher with an LM gather."""

    def __init__(self, ds: LmDataset, global_batch: int, seed: int = 0,
                 num_processes: int = 1, process_index: int = 0):
        self.ds = ds
        super().__init__(
            n_items=len(ds), global_batch=global_batch, gather=ds.batch,
            seed=seed, num_processes=num_processes,
            process_index=process_index)
