"""CIFAR-10 + synthetic image datasets for the ResNet scale-out configs.

No reference counterpart (the reference's only dataset is MNIST via
``input_data.read_data_sets``, mnist_python_m.py:133); this exists so the
ResNet-20/CIFAR-10 and ResNet-50/ImageNet BASELINE.json configs run on
the same Dataset/ShardedBatcher contract as MNIST (SURVEY.md N13
upgrade: disjoint per-process sharding, no network egress).

CIFAR-10 binary format (the "cifar-10-batches-bin" distribution):
each record is 1 label byte + 3072 image bytes (1024 R, 1024 G, 1024 B,
row-major 32x32); files data_batch_{1..5}.bin (train) and test_batch.bin.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from tensorflow_distributed_tpu.data.mnist import Dataset, _to_splits

_RECORD = 1 + 3 * 32 * 32
_TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
_TEST_FILE = "test_batch.bin"


def parse_cifar_batch(raw: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Parse one .bin file -> (images u8 [N,32,32,3], labels i32 [N])."""
    if len(raw) % _RECORD != 0:
        raise ValueError(f"cifar: file size {len(raw)} not a multiple of "
                         f"record size {_RECORD}")
    rec = np.frombuffer(raw, dtype=np.uint8).reshape(-1, _RECORD)
    labels = rec[:, 0].astype(np.int32)
    # CHW planes -> HWC.
    images = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).copy()
    return images, labels


def load_cifar10(data_dir: str, validation_size: int = 5000
                 ) -> Tuple[Dataset, Dataset, Dataset]:
    """Load the binary CIFAR-10 distribution from ``data_dir`` (directly
    or under a cifar-10-batches-bin/ subdir)."""
    wanted = _TRAIN_FILES + [_TEST_FILE]
    for base in (data_dir, os.path.join(data_dir, "cifar-10-batches-bin")):
        present = [f for f in wanted if os.path.exists(os.path.join(base, f))]
        if present:
            break
    if not present:
        raise FileNotFoundError(
            f"CIFAR-10 .bin files not found under {data_dir}. This "
            "environment has no network egress; place the binary "
            "distribution there or use dataset='cifar10_synthetic'.")
    if len(present) != len(wanted):
        # A partial copy must NOT fall through to the synthetic fallback
        # (load_dataset catches FileNotFoundError) — that would silently
        # train on synthetic data while the user believes it's CIFAR-10.
        missing = sorted(set(wanted) - set(present))
        raise ValueError(
            f"CIFAR-10 under {base} is incomplete: missing {missing}")
    ims, labs = [], []
    for fname in _TRAIN_FILES:
        with open(os.path.join(base, fname), "rb") as f:
            i, l = parse_cifar_batch(f.read())
        ims.append(i)
        labs.append(l)
    train_images = np.concatenate(ims).astype(np.float32) / 255.0
    train_labels = np.concatenate(labs)
    with open(os.path.join(base, _TEST_FILE), "rb") as f:
        ti, tl = parse_cifar_batch(f.read())
    return _to_splits(train_images, train_labels,
                      ti.astype(np.float32) / 255.0, tl,
                      validation_size, "cifar10")


def synthetic_images(n_train: int, n_test: int, validation_size: int,
                     shape: Tuple[int, int, int], num_classes: int,
                     seed: int, name: str
                     ) -> Tuple[Dataset, Dataset, Dataset]:
    """Deterministic learnable synthetic image classification set.

    Each class is a fixed smooth color template; samples are the
    template plus noise — separable by a convnet but not trivially
    (noise sigma 0.35 vs unit-range templates).
    """
    rng = np.random.default_rng(seed)
    h, w, c = shape
    n = n_train + n_test
    # Coarse templates upsampled 4x then cropped — ceil-divide so any
    # (even non-multiple-of-4, or < 4) h/w yields the exact shape asked.
    templates = rng.uniform(0.0, 1.0, size=(num_classes, -(-h // 4),
                                            -(-w // 4), c)).astype(np.float32)
    templates = np.kron(templates, np.ones(
        (1, 4, 4, 1), np.float32))[:, :h, :w, :]  # smooth upsample
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = templates[labels]
    # f32 noise generated directly — a float64 temporary here would
    # triple peak host memory for the ImageNet-shaped set.
    images += 0.35 * rng.standard_normal(images.shape, dtype=np.float32)
    images = np.clip(images, 0.0, 1.0)
    return _to_splits(images[:n_train], labels[:n_train],
                      images[n_train:], labels[n_train:],
                      validation_size, name)


def synthetic_cifar10(n_train: int = 8000, n_test: int = 1000,
                      validation_size: int = 1000, seed: int = 0
                      ) -> Tuple[Dataset, Dataset, Dataset]:
    return synthetic_images(n_train, n_test, validation_size,
                            (32, 32, 3), 10, seed, "cifar10_synthetic")


def synthetic_imagenet(n_train: int = 2048, n_test: int = 512,
                       validation_size: int = 512, seed: int = 0,
                       image_size: int = 224, num_classes: int = 1000
                       ) -> Tuple[Dataset, Dataset, Dataset]:
    """ImageNet-shaped synthetic data for the ResNet-50 config. Small N
    by default — this exists to exercise shapes/throughput, not accuracy."""
    return synthetic_images(n_train, n_test, validation_size,
                            (image_size, image_size, 3), num_classes, seed,
                            "imagenet_synthetic")
