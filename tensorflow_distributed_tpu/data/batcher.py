"""Generic epoch-shuffled, process-disjoint batcher.

One implementation of the sharded-batch contract (SURVEY.md N13
upgrade) shared by every dataset family:

- Each global batch of size B is a contiguous slice of a seeded
  per-epoch permutation shared by all processes (same seed -> identical
  permutation everywhere, no coordination traffic).
- Process p materializes rows [p*B/P, (p+1)*B/P) — its local shard.
  A 1-process run therefore consumes the identical sample stream,
  enabling exact N-vs-1 equivalence tests.
- ``forever(start_step)`` fast-forwards (cheaply — skipped batches are
  never gathered) so a checkpoint-resumed run continues the exact
  sample stream instead of replaying from epoch 0.

Dataset families plug in via ``gather``: a callable mapping an index
array to the host batch pytree (tuple, dict, ...).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import numpy as np


class Batcher:
    def __init__(self, n_items: int, global_batch: int,
                 gather: Callable[[np.ndarray], Any], seed: int = 0,
                 num_processes: int = 1, process_index: int = 0):
        if global_batch % max(num_processes, 1) != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{num_processes} processes")
        if n_items < global_batch:
            raise ValueError("dataset smaller than one global batch")
        self.n_items = n_items
        self.global_batch = global_batch
        self.gather = gather
        self.seed = seed
        self.num_processes = num_processes
        self.process_index = process_index
        self.local_batch = global_batch // max(num_processes, 1)
        self.steps_per_epoch = n_items // global_batch

    def _perm(self, epoch_idx: int) -> np.ndarray:
        return np.random.default_rng((self.seed, epoch_idx)).permutation(
            self.n_items)

    def epoch(self, epoch_idx: int, start: int = 0) -> Iterator[Any]:
        perm = self._perm(epoch_idx)
        for s in range(start, self.steps_per_epoch):
            lo = s * self.global_batch + self.process_index * self.local_batch
            yield self.gather(perm[lo:lo + self.local_batch])

    def forever(self, start_step: int = 0) -> Iterator[Any]:
        e, skip = divmod(start_step, self.steps_per_epoch)
        while True:
            yield from self.epoch(e, start=skip)
            skip = 0
            e += 1
