"""Double-buffered host->device prefetch.

The reference paid a synchronous feed_dict host->runtime copy inside
every ``sess.run`` (mnist_python_m.py:291-294, SURVEY.md N14). Here the
next batch's device transfer overlaps the current step's compute:
``jax.device_put`` is async, so simply staying one batch ahead of the
consumer hides the PCIe/DMA latency behind the MXU work.
"""

from __future__ import annotations

import collections
from typing import Any, Iterator, Optional

from jax.sharding import Mesh

from tensorflow_distributed_tpu.parallel.sharding import shard_batch


def prefetch_with(it: Iterator[Any], place: Any, size: int = 2
                  ) -> Iterator[Any]:
    """Generic double-buffer: yield ``place(batch)`` results ``size``
    transfers ahead of the consumer. ``place`` maps a host batch to
    device arrays (any sharding convention — e.g. the stacked-K layout
    of train.multistep)."""
    buf = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                batch = next(it)
            except StopIteration:
                return
            buf.append(place(batch))

    enqueue(size)
    while buf:
        yield buf.popleft()
        enqueue(1)


def prefetch_to_mesh(it: Iterator[Any], mesh: Mesh, size: int = 2,
                     seq_axis: Optional[int] = None) -> Iterator[Any]:
    """Yield batches already device_put against ``mesh``, ``size`` ahead."""
    return prefetch_with(
        it, lambda b: shard_batch(mesh, b, seq_axis=seq_axis), size)
