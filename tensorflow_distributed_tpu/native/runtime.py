"""ctypes bindings + on-demand build of the native host runtime.

Build model: one `g++ -O3 -shared` invocation of native/tfd_native.cc
into <repo>/build/libtfd_native.so, (re)run automatically when the
source is newer than the library. ctypes instead of pybind11 because
the image ships no pybind11 and the ABI is 6 plain C functions.

Everything here has a pure-Python/numpy fallback (`available()` gates
call sites), so the framework degrades gracefully on hosts without a
toolchain — the reference had the same split: Python drives, TF's C++
does the byte work (SURVEY.md N13/N14).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "tfd_native.cc")
_BUILD_DIR = os.environ.get("TFD_TPU_BUILD_DIR",
                            os.path.join(_REPO_ROOT, "build"))
_LIB = os.path.join(_BUILD_DIR, "libtfd_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False

_IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
               0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Compile to a per-process temp name and rename into place: rename
    # is atomic, so concurrent processes (multi-host launch, xdist)
    # never dlopen a half-written ELF.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp, _SRC, "-lz", "-pthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """Build-if-stale and dlopen the native library; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        have_src = os.path.exists(_SRC)
        stale = (not os.path.exists(_LIB)
                 or (have_src
                     and os.path.getmtime(_LIB) < os.path.getmtime(_SRC)))
        if stale and not (have_src and _build()):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None

        c = ctypes
        lib.tfd_idx_read.restype = c.c_int
        lib.tfd_idx_read.argtypes = [
            c.c_char_p, c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
            c.POINTER(c.c_int), c.POINTER(c.c_int)]
        lib.tfd_free.restype = None
        lib.tfd_free.argtypes = [c.c_void_p]
        lib.tfd_gather_u8_f32.restype = None
        lib.tfd_gather_u8_f32.argtypes = [
            c.c_void_p, c.c_int64, c.c_void_p, c.c_int64, c.c_float,
            c.c_void_p, c.c_int]
        lib.tfd_prefetch_create.restype = c.c_void_p
        lib.tfd_prefetch_create.argtypes = [
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_int64,
            c.c_int, c.c_uint64, c.c_int, c.c_float]
        lib.tfd_prefetch_next.restype = c.c_int
        lib.tfd_prefetch_next.argtypes = [c.c_void_p, c.c_void_p,
                                          c.c_void_p]
        lib.tfd_prefetch_destroy.restype = None
        lib.tfd_prefetch_destroy.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None


def idx_read(path: str) -> np.ndarray:
    """Read an IDX(.gz) file natively (SURVEY.md N13's parse step)."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    data = ctypes.c_void_p()
    dims = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int()
    dtype = ctypes.c_int()
    rc = lib.tfd_idx_read(path.encode(), ctypes.byref(data), dims,
                          ctypes.byref(ndim), ctypes.byref(dtype))
    if rc != 0:
        raise IOError(f"tfd_idx_read({path}) failed: {rc}")
    shape = tuple(dims[i] for i in range(ndim.value))
    np_dtype = _IDX_DTYPES[dtype.value]
    n = int(np.prod(shape))
    buf = ctypes.cast(data, ctypes.POINTER(ctypes.c_uint8 * (
        n * np.dtype(np_dtype).itemsize))).contents
    # One copy out of the C buffer (which is freed below); writable,
    # matching parse_idx's contract.
    arr = np.frombuffer(buf, dtype=np_dtype).reshape(shape).copy()
    lib.tfd_free(data)
    return arr


def gather_u8_f32(src: np.ndarray, idx: np.ndarray, scale: float,
                  nthreads: int = 0) -> np.ndarray:
    """out[i] = src[idx[i]] * scale, threaded in C++."""
    lib = load_library()
    if lib is None:
        return src[idx].astype(np.float32) * scale
    src = np.ascontiguousarray(src)
    assert src.dtype == np.uint8
    item = int(np.prod(src.shape[1:]))
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx), *src.shape[1:]), np.float32)
    nthreads = nthreads or min(8, os.cpu_count() or 1)
    lib.tfd_gather_u8_f32(
        src.ctypes.data_as(ctypes.c_void_p), item,
        idx.ctypes.data_as(ctypes.c_void_p), len(idx), scale,
        out.ctypes.data_as(ctypes.c_void_p), nthreads)
    return out


class NativePrefetcher:
    """Background-thread shuffled batch producer over (u8 images,
    i32 labels), the native replacement for the per-step
    next_batch + feed_dict host work (mnist_python_m.py:291-294).

    Iterates forever (epochs reshuffle, drop-last)."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch: int, *, seed: int = 0, depth: int = 2,
                 nthreads: int = 0, scale: float = 1.0 / 255.0):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        if images.dtype != np.uint8:
            # Refuse to silently truncate float [0,1] images to zeros.
            raise TypeError(
                f"NativePrefetcher wants uint8 image storage, got "
                f"{images.dtype}; keep the raw bytes and let the scale "
                f"argument do the normalization")
        # Keep references: the C side reads these buffers directly.
        self._images = np.ascontiguousarray(images)
        self._labels = np.ascontiguousarray(labels, dtype=np.int32)
        self._item_shape = self._images.shape[1:]
        self._batch = batch
        item = int(np.prod(self._item_shape))
        self._handle = lib.tfd_prefetch_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            self._labels.ctypes.data_as(ctypes.c_void_p),
            len(self._images), item, batch, depth, seed,
            nthreads or min(8, os.cpu_count() or 1), scale)
        if not self._handle:
            raise ValueError("bad prefetcher config (batch > n?)")

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._handle:  # closed: don't hand ctypes a NULL
            raise StopIteration
        x = np.empty((self._batch, *self._item_shape), np.float32)
        y = np.empty((self._batch,), np.int32)
        rc = self._lib.tfd_prefetch_next(
            self._handle, x.ctypes.data_as(ctypes.c_void_p),
            y.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise StopIteration
        return x, y

    def close(self) -> None:
        if self._handle:
            self._lib.tfd_prefetch_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
