"""Native (C++) host runtime bindings."""

from tensorflow_distributed_tpu.native.runtime import (  # noqa: F401
    NativePrefetcher,
    available,
    gather_u8_f32,
    idx_read,
    load_library,
)
