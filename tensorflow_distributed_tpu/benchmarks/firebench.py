"""Serve-under-fire benchmark: the SAME workload, fault-free vs under
the standard fault plan, with availability gates.

The serving claim this pins (ISSUE 6 / ROADMAP item 5): the
continuous-batching engine keeps answering through a decode stall, a
slot-level NaN, a live weight swap, and a SIGKILL-and-supervise — at
>= ``--min-goodput`` of the fault-free tokens/s, with ZERO lost
requests, and with every surviving token IDENTICAL to the fault-free
run (greedy decode + swap-to-the-same-checkpoint + journal-exact
continuations make bitwise identity the correct bar, not a soft
similarity).

Procedure (all runs are CLI subprocesses, so process death is real):

1. train 2 steps of the tiny GPT -> a checkpoint (the swap source AND
   the serving weights, so fault-free and fire legs share params);
2. BASELINE: ``--mode serve`` on a seeded synthetic workload (bursty
   arrivals), journaled;
3. FIRE: the same command under ``resilience.supervisor`` with
   ``decode_stall@A:0.5s,slot_nan@B:0,reload@C,sigkill@D`` and the
   decode watchdog armed — the kill costs a restart whose journal
   resume re-admits in-flight requests as continuations;
4. gates: goodput (useful tokens / SERVING wall, legs summed via the
   journal's per-leg time segments — process startup is excluded on
   both sides identically) >= min-goodput x baseline; 0 lost; 100%
   token-identical; >= 1 slot retry, >= 1 weight swap, >= 1 restart
   actually happened (a drill that never fired proves nothing).

Emits one JSON line per metric plus a checks line; ``--out`` writes
FIREBENCH.json (overwritten per run, like the sibling benchmarks);
exit 1 on any failed gate (``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile


def _leg_walls(journal_path: str):
    """Per-leg serving wall times from the journal's token timestamps:
    ``s`` is scheduler-run-relative and monotone within a leg, so a
    drop marks the restart boundary. Returns a list of leg walls."""
    walls, cur = [], 0.0
    with open(journal_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            s = rec.get("s")
            if s is None:
                continue
            if s < cur - 1e-6:          # restart: the clock reset
                walls.append(cur)
                cur = 0.0
            cur = max(cur, float(s))
    walls.append(cur)
    return walls


def _run(cmd, env, timeout, what):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        print(f"firebench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--num-slots", type=int, default=2)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=12)
    parser.add_argument("--new-tokens", type=int, default=192)
    parser.add_argument("--seq-len", type=int, default=208)
    parser.add_argument("--arrival-rate", type=float, default=32.0)
    parser.add_argument("--trace", default="bursty")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-goodput", type=float, default=0.8)
    parser.add_argument("--stall-s", type=float, default=0.3)
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip the untimed warmup pass (first-use "
                        "XLA compiles then land inside the measured "
                        "serving walls)")
    parser.add_argument("--timeout", type=float, default=420.0,
                        help="per-subprocess timeout (s)")
    parser.add_argument("--workdir", default="",
                        help="scratch dir (default: a fresh tempdir, "
                        "removed on success)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="FIREBENCH.json")
    args = parser.parse_args(argv)
    if args.requests < 2 or args.num_slots < 1:
        parser.error("--requests >= 2 and --num-slots >= 1")

    work = args.workdir or tempfile.mkdtemp(prefix="firebench-")
    os.makedirs(work, exist_ok=True)
    ckpt = os.path.join(work, "ckpt")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"

    total_tokens = args.requests * args.new_tokens
    # Decode-step budget ~ total tokens / slots; key the faults well
    # inside it so every drill actually fires before the work runs dry
    # (gated below — a plan that never fired proves nothing).
    est_steps = max(8, total_tokens // args.num_slots)
    k_stall = max(2, est_steps // 8)
    k_nan = max(3, est_steps // 5)
    k_reload = max(4, est_steps // 3)
    k_kill = max(5, est_steps // 2)
    plan = (f"decode_stall@{k_stall}:{args.stall_s}s,"
            f"slot_nan@{k_nan}:0,reload@{k_reload},sigkill@{k_kill}")

    common = [
        "--model", "gpt_lm", "--model-size", args.size,
        "--seq-len", str(args.seq_len), "--seed", str(args.seed),
        "--compute-dtype", "float32",
    ]
    serve_common = common + [
        "--mode", "serve", "--checkpoint-dir", ckpt,
        "--serve.num-slots", str(args.num_slots),
        "--serve.num-requests", str(args.requests),
        "--serve.prompt-len-min", str(args.prompt_len_min),
        "--serve.prompt-len-max", str(args.prompt_len_max),
        "--serve.max-new-tokens", str(args.new_tokens),
        "--serve.trace", args.trace,
        "--serve.arrival-rate", str(args.arrival_rate),
        # ONE prefill bucket at the cache length: continuation
        # re-prefills (slot retry, journal resume) share the original
        # admissions' program, so no leg ever pays a first-use XLA
        # compile mid-measurement. Bucket-ladder economics are
        # servebench's subject, not this bench's.
        "--serve.buckets", str(args.seq_len),
    ]

    # 1. The checkpoint both runs serve (and the fire run swaps to).
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *common, "--dataset", "synthetic", "--train-steps", "2",
          "--batch-size", "8", "--eval-every", "0", "--log-every", "0",
          "--checkpoint-dir", ckpt, "--checkpoint-every", "2"],
         env, args.timeout, "checkpoint prep")

    # 1b. Untimed warmup: one small serve exercises every program the
    # measured runs dispatch (the single prefill bucket, the decode
    # step, the row insert), so the persistent compile cache is hot
    # and the measured walls compare SERVING, not first-use XLA
    # compiles — which would otherwise land inside whichever leg
    # happened to run first.
    if not args.no_warmup:
        warm = [a for a in serve_common]
        warm[warm.index("--serve.num-requests") + 1] = "4"
        warm[warm.index("--serve.max-new-tokens") + 1] = "8"
        _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
              *warm], env, args.timeout, "warmup")

    # 2. Fault-free baseline.
    base_journal = os.path.join(work, "base.journal")
    base_jsonl = os.path.join(work, "base.jsonl")
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *serve_common, "--serve.journal", base_journal,
          "--observe.metrics-jsonl", base_jsonl],
         env, args.timeout, "baseline serve")

    # 3. Serve under fire, supervised.
    fire_journal = os.path.join(work, "fire.journal")
    fire_jsonl = os.path.join(work, "fire.jsonl")
    fire = _run([sys.executable, "-m",
                 "tensorflow_distributed_tpu.resilience.supervisor",
                 "--max-restarts", "2", "--backoff-base-s", "0.2",
                 "--", *serve_common,
                 "--serve.journal", fire_journal,
                 "--observe.metrics-jsonl", fire_jsonl,
                 "--resilience.sync-timeout-s", "120",
                 "--resilience.fault-plan", plan],
                env, args.timeout, "fire serve")
    restarts = fire.stdout.count('"kind": "restart"')

    # 4. Gates.
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)
    from tensorflow_distributed_tpu.serve import journal as journal_mod

    base_sum = summarize(load_records(base_jsonl))
    fire_sum = summarize(load_records(fire_jsonl))
    base_play = journal_mod.replay(base_journal)
    fire_play = journal_mod.replay(fire_journal)

    lost = [rid for rid in range(args.requests)
            if not fire_play.get(rid, {}).get("done")]
    mismatched = [rid for rid in range(args.requests)
                  if fire_play.get(rid, {}).get("tokens")
                  != base_play.get(rid, {}).get("tokens")]
    base_wall = sum(_leg_walls(base_journal))
    fire_wall = sum(_leg_walls(fire_journal))
    base_tps = total_tokens / max(base_wall, 1e-9)
    fire_tps = total_tokens / max(fire_wall, 1e-9)
    goodput = fire_tps / max(base_tps, 1e-9)
    # Whole-file truth (the LAST serve_summary is the resumed leg's,
    # which saw no faults): count the recovery events themselves.
    rec_counts = fire_sum.get("recovery_counts", {})
    retries = rec_counts.get("slot_quarantine", 0)
    swaps = rec_counts.get("weight_swap", 0)

    common_tags = {
        "model": f"gpt_lm/{args.size}",
        "requests": args.requests, "new_tokens": args.new_tokens,
        "num_slots": args.num_slots, "trace": args.trace,
        "arrival_rate": args.arrival_rate, "seed": args.seed,
        "fault_plan": plan,
    }
    lines = [
        {"metric": "fire_faultfree_tokens_per_sec",
         "value": round(base_tps, 1), "unit": "tokens/sec"},
        {"metric": "fire_tokens_per_sec",
         "value": round(fire_tps, 1), "unit": "tokens/sec"},
        {"metric": "fire_goodput", "value": round(goodput, 4),
         "unit": "fraction of fault-free"},
        {"metric": "fire_serving_wall", "value": round(fire_wall, 3),
         "unit": "s", "faultfree_wall": round(base_wall, 3)},
        {"metric": "fire_retries", "value": retries, "unit": "slot"
         " quarantines"},
        {"metric": "fire_swaps", "value": swaps, "unit": "live weight"
         " swaps",
         "swap_seconds": fire_sum.get("swap_seconds_total",
                                      fire_sum.get("serve_swap_seconds",
                                                   0))},
        {"metric": "fire_restarts", "value": restarts,
         "unit": "supervised restarts"},
        {"metric": "fire_ttft_ms_p99",
         "value": fire_sum.get("serve_ttft_ms_p99"), "unit": "ms",
         "faultfree_p99": base_sum.get("serve_ttft_ms_p99")},
        {"metric": "fire_ttft_ms_p99_recovery",
         "value": fire_sum.get("serve_ttft_ms_p99_recovery"),
         "unit": "ms",
         "recovery_requests": fire_sum.get("serve_recovery_requests",
                                           0)},
        {"metric": "fire_recovery_counts",
         "value": fire_sum.get("recovery_counts", {}), "unit": ""},
    ]
    checks = {
        "metric": "fire_checks",
        "goodput_ok": bool(goodput >= args.min_goodput),
        "min_goodput": args.min_goodput,
        "lost_requests": len(lost),
        "token_identical": args.requests - len(mismatched),
        "of": args.requests,
        "drills_fired_ok": bool(retries >= 1 and swaps >= 1
                                and restarts >= 1),
    }
    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    ok = (checks["goodput_ok"] and not lost and not mismatched
          and checks["drills_fired_ok"])
    if not args.no_check and not ok:
        print(f"firebench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
