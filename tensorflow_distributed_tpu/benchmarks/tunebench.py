"""Autopilot benchmark: the calibrate→plan→act loop closed on live
telemetry, with convergence, quietness, identity, evidence and
overhead gates.

What this pins (ISSUE 20 / ROADMAP item 5's control layer):

1. **Goodput convergence** (in-process, shifting open-loop trace:
   gentle → over-capacity burst → gentle tail): a run booted with a
   WRONG admission knob (``decode_priority`` far above the hand-tuned
   value) but the autopilot armed must converge to >=
   ``--min-goodput-ratio`` (default 0.9) of the hand-tuned config's
   goodput, measured over the second half of each run's token stream
   (the post-convergence regime — "converges to", not "never paid a
   detection transient"). The same wrong knob WITHOUT the autopilot is
   reported alongside to show the gap the controller closed.
2. **Token identity**: per-request token streams from the hand-tuned,
   wrong-knob and autopilot-steered runs are IDENTICAL — every live
   actuation rides the scheduler's control-command path between decode
   steps, so the knobs move scheduling, never sampled tokens.
3. **Decision quietness** (control): the hand-tuned config under a
   gentle trace with the autopilot armed makes ZERO knob changes
   (``tune_summary.quiet``) — hysteresis + deadbands absorb a healthy
   run's noise.
4. **Speculation retune** (in-process): a same-model draft speculator
   (accept rate 1.0 by construction) booted at a shallow k must walk
   the ladder up — live ``set_spec_k`` recompiles mid-run — with the
   streams still identical to a speculation-off reference.
5. **Flag wiring** (CLI subprocess): a fresh-init ``--mode serve`` run
   with ``--observe.autopilot`` + a wrong admission knob under a burst
   trace lands auditable ``tune`` records and a ``tune_summary`` in
   the metrics JSONL, and the serve summary counts the actuations.
6. **Overhead** (fresh-interpreter A/B): tokens/s with the autopilot
   armed >= ``--min-tps-ratio`` (default 0.95) of tokens/s without.

Every ``tune`` record across every leg must carry machine-readable
evidence: the signal, the observed value, the threshold it crossed
and the triggering context (``evidence_ok``).

Emits one JSON line per metric plus a checks line; ``--out`` writes
TUNEBENCH.json (overwritten per run); exit 1 on any failed gate
(``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

#: ``tune`` records must carry these fields to count as auditable
#: evidence (the machine-readable half of every decision).
_TUNE_FIELDS = ("step", "loop", "knob", "action", "signal",
                "threshold", "applied", "evidence")


def _run(cmd, env, timeout, what):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        print(f"tunebench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def _shift_arrivals(phases):
    """Open-loop arrival offsets for ``[(n, rate), ...]`` phases —
    the gentle → burst → gentle shifting trace."""
    t, out = 0.0, []
    for n, rate in phases:
        for _ in range(n):
            t += 1.0 / rate
            out.append(t)
    return out


def _tune_evidence_ok(recs):
    """Every decision auditable: all fields present, the observed
    value numeric, the evidence a non-degenerate dict."""
    tunes = [r for r in recs if r.get("event") == "tune"]
    return all(
        all(k in r for k in _TUNE_FIELDS)
        and isinstance(r.get("observed"), (int, float))
        and isinstance(r.get("evidence"), dict) and r["evidence"]
        for r in tunes)


def _half_tps(times):
    """Tokens/s over the second half of one run's token stream — the
    post-convergence goodput ("converges to", not transient-free)."""
    if len(times) < 4:
        return 0.0
    mid = len(times) // 2
    span = times[-1] - times[mid]
    return (len(times) - mid) / max(span, 1e-9)


class _InProc:
    """Shared in-process context: one tiny model + params, engines
    rebuilt per leg (lookup_program caches compiles across legs)."""

    def __init__(self, args):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tensorflow_distributed_tpu.models.transformer import (
            gpt_lm)
        from tensorflow_distributed_tpu.serve.buckets import (
            default_buckets)

        self.args = args
        self.max_len = (args.prompt_len_max
                        + max(args.new_tokens, args.spec_new_tokens)
                        + 4 + args.spec_k_to)
        self.model = gpt_lm(None, size="tiny", d_model=64, n_layers=2,
                            n_heads=4, d_ff=256, max_len=self.max_len,
                            dropout_rate=0.0)
        self.params = self.model.init(
            jax.random.key(args.seed),
            jnp.zeros((1, 8), jnp.int32))["params"]
        rng = np.random.default_rng(args.seed)
        n = max(args.requests, args.spec_requests)
        self.prompts = [
            rng.integers(0, self.model.cfg.vocab_size,
                         size=int(ln)).astype(np.int32)
            for ln in rng.integers(args.prompt_len_min,
                                   args.prompt_len_max + 1, size=n)]
        self.buckets = default_buckets(args.prompt_len_max,
                                       cap=self.max_len)

    def serve(self, *, dp, arrivals=None, autopilot=None, slo=False,
              speculator=None, spec_tokens=0, requests=None,
              new_tokens=None):
        """One scheduler run; returns (tokens-by-rid, token timestamps,
        emitted records, scheduler)."""
        from tensorflow_distributed_tpu.observe.slo import (
            SLOMonitor, parse_slo, parse_windows)
        from tensorflow_distributed_tpu.serve.engine import (
            SlotDecodeEngine)
        from tensorflow_distributed_tpu.serve.scheduler import (
            Request, Scheduler)

        args = self.args
        n = requests if requests is not None else args.requests
        new = new_tokens if new_tokens is not None else args.new_tokens
        recs, times = [], []

        def emit(event, **fields):
            recs.append({"event": event, **fields})

        eng = SlotDecodeEngine(self.model, self.params, args.num_slots,
                               buckets=self.buckets,
                               spec_tokens=spec_tokens)
        eng.warmup(speculator)
        kw = {}
        if slo:
            fast, slow = parse_windows(args.slo_windows)
            kw["slo_monitor"] = SLOMonitor(
                parse_slo(args.slo), fast_window=fast,
                slow_window=slow, emit=emit)
        sched = Scheduler(
            eng, decode_priority=dp, autopilot=autopilot,
            speculator=speculator,
            on_token=lambda rid, tok, done: times.append(
                sched.clock()), **kw)
        arrivals = arrivals or [0.0] * n
        comps = sched.run([
            Request(rid=i, prompt=p, max_new_tokens=new,
                    arrival_s=arrivals[i])
            for i, p in enumerate(self.prompts[:n])])
        return ({c.rid: list(c.tokens) for c in comps}, times, recs,
                sched)


def _autopilot(args, emitted):
    from tensorflow_distributed_tpu.observe.autopilot import Autopilot
    return Autopilot(
        emit=lambda event, **f: emitted.append(
            {"event": event, **f}),
        every=args.ap_every, confirm=args.ap_confirm,
        cooldown=args.ap_cooldown,
        k_ladder=tuple(int(k) for k in args.k_ladder.split(",")))


def _goodput_phase(ctx, args):
    """Legs 1-2: hand-tuned vs wrong-knob vs wrong-knob+autopilot on
    the same shifting trace; convergence + identity."""
    # Shifting trace: a gentle ramp (the SLO's completion baseline),
    # then a standing burst. The burst backlog is what the wrong
    # admission knob wrecks — a huge decode_priority collapses live
    # occupancy to ~1 while the queue waits — and what the autopilot
    # must win back; the drain IS the post-convergence regime the
    # second-half goodput measures.
    arrivals = _shift_arrivals([
        (args.gentle_requests, args.gentle_rate),
        (args.requests - args.gentle_requests, args.burst_rate)])
    hand_toks, hand_t, _, _ = ctx.serve(dp=args.hand_dp,
                                        arrivals=arrivals, slo=True)
    wrong_toks, wrong_t, _, _ = ctx.serve(dp=args.wrong_dp,
                                          arrivals=arrivals, slo=True)
    ap_recs = []
    ap = _autopilot(args, ap_recs)
    auto_toks, auto_t, _, sched = ctx.serve(
        dp=args.wrong_dp, arrivals=arrivals, slo=True, autopilot=ap)
    tunes = [r for r in ap_recs if r.get("event") == "tune"]
    tightened = [r for r in tunes if r.get("action") == "tighten"
                 and r.get("applied")]
    hand, wrong, auto = (_half_tps(hand_t), _half_tps(wrong_t),
                         _half_tps(auto_t))
    return {
        "hand_tps_half": round(hand, 1),
        "wrong_tps_half": round(wrong, 1),
        "auto_tps_half": round(auto, 1),
        "ratio": round(auto / max(hand, 1e-9), 4),
        "ratio_wrong": round(wrong / max(hand, 1e-9), 4),
        "tune_actions": sched.summary.get("tune_actions", 0),
        "tightened": len(tightened),
        "final_decode_priority": sched.decode_priority,
        "identity": auto_toks == hand_toks == wrong_toks,
        "records": ap_recs,
    }


def _control_phase(ctx, args):
    """Leg 3: hand-tuned knobs + gentle trace + autopilot armed →
    zero knob changes."""
    n = args.control_requests
    arrivals = _shift_arrivals([(n, args.control_rate)])
    ap_recs = []
    ap = _autopilot(args, ap_recs)
    _, _, _, sched = ctx.serve(dp=args.hand_dp, arrivals=arrivals,
                               slo=True, autopilot=ap,
                               requests=n)
    summaries = [r for r in ap_recs
                 if r.get("event") == "tune_summary"]
    return {
        "tune_actions": sched.summary.get("tune_actions", 0),
        "evals": ap.evals,
        "quiet": bool(summaries) and bool(summaries[-1].get("quiet")),
        "records": ap_recs,
    }


def _spec_phase(ctx, args):
    """Legs 4: same-model draft speculator (accept rate 1.0 by
    construction) booted at a shallow k — the autopilot must deepen
    it up the ladder, recompiling verify/draft programs live, with
    the streams identical to a speculation-off reference."""
    from tensorflow_distributed_tpu.serve.speculate import (
        DraftSpeculator)

    ref_toks, _, _, _ = ctx.serve(dp=args.hand_dp,
                                  requests=args.spec_requests,
                                  new_tokens=args.spec_new_tokens)
    ap_recs = []
    ap = _autopilot(args, ap_recs)
    spec = DraftSpeculator(ctx.model, ctx.params, args.num_slots,
                           ctx.buckets, args.spec_k_from)
    toks, _, _, sched = ctx.serve(
        dp=args.hand_dp, autopilot=ap, speculator=spec,
        spec_tokens=args.spec_k_from, requests=args.spec_requests,
        new_tokens=args.spec_new_tokens)
    deepened = [r for r in ap_recs if r.get("event") == "tune"
                and r.get("knob") == "spec_k" and r.get("applied")]
    return {
        "k_from": args.spec_k_from,
        "k_final": int(getattr(sched.engine, "spec_tokens", 0)),
        "spec_tunes": len(deepened),
        "accept_rate": sched.summary.get("accept_rate"),
        "identity": toks == ref_toks,
        "records": ap_recs,
    }


def _cli_phase(args, work, env):
    """Leg 5: the --observe.autopilot* flags end to end — fresh-init
    CLI serve with a wrong admission knob under a burst trace; the
    metrics JSONL must carry applied tune records + the summary."""
    jsonl = os.path.join(work, "cli.jsonl")
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          "--model", "gpt_lm", "--model-size", "tiny",
          "--seq-len", str(args.prompt_len_max + args.new_tokens + 4),
          "--seed", str(args.seed), "--compute-dtype", "float32",
          "--mode", "serve",
          "--serve.num-slots", str(args.num_slots),
          "--serve.num-requests", str(args.requests),
          "--serve.prompt-len-min", str(args.prompt_len_min),
          "--serve.prompt-len-max", str(args.prompt_len_max),
          "--serve.max-new-tokens", str(args.new_tokens),
          "--serve.decode-priority", str(args.wrong_dp),
          "--serve.trace", "bursty",
          "--serve.arrival-rate", str(args.burst_rate),
          "--observe.metrics-jsonl", jsonl,
          "--observe.slo", args.slo,
          "--observe.slo-windows", args.slo_windows,
          "--observe.autopilot", "true",
          "--observe.autopilot-every", str(args.ap_every),
          "--observe.autopilot-confirm", str(args.ap_confirm),
          "--observe.autopilot-cooldown", str(args.ap_cooldown)],
         env, args.timeout, "cli autopilot serve")
    from tensorflow_distributed_tpu.observe.report import load_records
    recs = load_records(jsonl)
    tunes = [r for r in recs if r.get("event") == "tune"
             and r.get("applied")]
    summary = next((r for r in reversed(recs)
                    if r.get("event") == "serve_summary"), {})
    return {
        "tune_records": len(tunes),
        "tune_summary": any(r.get("event") == "tune_summary"
                            for r in recs),
        "summary_tune_actions": summary.get("tune_actions", 0),
        "records": recs,
    }


def _overhead_ab(args):
    """Leg 6 (run in a FRESH interpreter via --ab-only, like every
    other bench's overhead phase): the same seeded workload through
    the scheduler with the autopilot off vs armed-and-quiet,
    INTERLEAVED over ``--overhead-repeats`` rounds, each side's best.
    The A/B model is deliberately bigger than the drill legs' tiny
    config (the controller's cost is fixed host bookkeeping per eval
    tick — gate it against a real step, not XLA dispatch noise)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.observe.autopilot import Autopilot
    from tensorflow_distributed_tpu.serve.buckets import (
        default_buckets)
    from tensorflow_distributed_tpu.serve.engine import (
        SlotDecodeEngine)
    from tensorflow_distributed_tpu.serve.scheduler import (
        Request, Scheduler)

    max_len = args.prompt_len_max + args.overhead_new_tokens + 4
    model = gpt_lm(None, size="tiny", d_model=args.overhead_d_model,
                   n_layers=4, n_heads=8,
                   d_ff=4 * args.overhead_d_model, max_len=max_len,
                   dropout_rate=0.0)
    params = model.init(jax.random.key(args.seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(args.seed)
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=int(n)).astype(np.int32)
               for n in rng.integers(args.prompt_len_min,
                                     args.prompt_len_max + 1,
                                     size=args.overhead_requests)]
    buckets = default_buckets(args.prompt_len_max, cap=max_len)

    def one(piloted: bool) -> float:
        kw = {}
        if piloted:
            kw["autopilot"] = Autopilot(
                every=args.ap_every, confirm=args.ap_confirm,
                cooldown=args.ap_cooldown)
        eng = SlotDecodeEngine(model, params, args.num_slots,
                               buckets=buckets)
        eng.warmup()
        sched = Scheduler(eng, decode_priority=args.hand_dp, **kw)
        sched.run([Request(rid=i, prompt=p,
                           max_new_tokens=args.overhead_new_tokens)
                   for i, p in enumerate(prompts)])
        return float(sched.summary["tokens_per_sec"])

    one(False)                         # warm the A/B shapes untimed
    tps_off = tps_on = 0.0
    for _ in range(args.overhead_repeats):
        tps_off = max(tps_off, one(False))
        tps_on = max(tps_on, one(True))
    return tps_off, tps_on


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--phases",
                        default="goodput,control,spec,cli,overhead")
    parser.add_argument("--requests", type=int, default=36,
                        help="shifting-trace total (gentle ramp + "
                        "standing burst)")
    parser.add_argument("--gentle-requests", type=int, default=8)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=12)
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument("--gentle-rate", type=float, default=8.0)
    parser.add_argument("--burst-rate", type=float, default=200.0,
                        help="far over capacity — the SLO must burn "
                        "and the wrong admission knob must hurt")
    parser.add_argument("--control-requests", type=int, default=14)
    parser.add_argument("--control-rate", type=float, default=3.0,
                        help="control arrivals — gentle, the engine "
                        "keeps up, zero decisions expected")
    parser.add_argument("--hand-dp", type=int, default=4,
                        help="the hand-tuned decode_priority")
    parser.add_argument("--wrong-dp", type=int, default=64,
                        help="the deliberately wrong admission knob "
                        "the autopilot must walk back")
    parser.add_argument("--slo", default="ttft_p95=150ms")
    parser.add_argument("--slo-windows", default="16,64")
    parser.add_argument("--ap-every", type=int, default=10)
    parser.add_argument("--ap-confirm", type=int, default=2)
    parser.add_argument("--ap-cooldown", type=int, default=30)
    parser.add_argument("--k-ladder", default="1,2,4")
    parser.add_argument("--spec-requests", type=int, default=8)
    parser.add_argument("--spec-new-tokens", type=int, default=64,
                        help="per-request budget for the spec leg — "
                        "sized so the accept-rate window crosses "
                        "enough eval ticks to confirm a deepen")
    parser.add_argument("--spec-k-from", type=int, default=2,
                        help="shallow boot k for the deepen leg")
    parser.add_argument("--spec-k-to", type=int, default=4,
                        help="ladder top the deepen leg must reach")
    parser.add_argument("--min-goodput-ratio", type=float, default=0.9)
    parser.add_argument("--min-tps-ratio", type=float, default=0.95)
    parser.add_argument("--overhead-requests", type=int, default=16)
    parser.add_argument("--overhead-new-tokens", type=int, default=64)
    parser.add_argument("--overhead-repeats", type=int, default=5,
                        help="interleaved rounds; each side's best is "
                        "compared (host scheduling noise on this box "
                        "is ~10% run-to-run)")
    parser.add_argument("--overhead-d-model", type=int, default=256)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ab-only", action="store_true",
                        help=argparse.SUPPRESS)  # internal: run just
    # the overhead A/B in a FRESH interpreter (the drill legs leave a
    # warmed-but-fragmented heap that skews a tight in-process A/B)
    # and print one JSON line
    parser.add_argument("--timeout", type=float, default=420.0)
    parser.add_argument("--workdir", default="")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="TUNEBENCH.json")
    args = parser.parse_args(argv)

    if args.ab_only:
        tps_off, tps_on = _overhead_ab(args)
        print(json.dumps({"ab_tps_off": tps_off, "ab_tps_on": tps_on}))
        return 0

    phases = {p.strip() for p in args.phases.split(",") if p.strip()}
    work = args.workdir or tempfile.mkdtemp(prefix="tunebench-")
    os.makedirs(work, exist_ok=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"

    ctx = None
    if phases & {"goodput", "control", "spec"}:
        ctx = _InProc(args)

    lines, checks = [], {"metric": "tune_checks"}
    evidence_recs = []

    if "goodput" in phases:
        g = _goodput_phase(ctx, args)
        evidence_recs += g.pop("records")
        lines.append({"metric": "tune_goodput", "unit": "tokens/sec",
                      "value": g["auto_tps_half"], **g})
        checks["converged"] = bool(
            g["ratio"] >= args.min_goodput_ratio
            and g["tightened"] >= 1
            and g["final_decode_priority"] < args.wrong_dp)
        checks["identity"] = bool(g["identity"])

    if "control" in phases:
        c = _control_phase(ctx, args)
        evidence_recs += c.pop("records")
        lines.append({"metric": "tune_control",
                      "value": c["tune_actions"],
                      "unit": "applied knob changes", **c})
        checks["quiet_control"] = bool(c["quiet"]
                                       and c["tune_actions"] == 0)

    if "spec" in phases:
        s = _spec_phase(ctx, args)
        evidence_recs += s.pop("records")
        lines.append({"metric": "tune_spec", "value": s["k_final"],
                      "unit": "draft depth k", **s})
        checks["spec_retuned"] = bool(
            s["spec_tunes"] >= 1 and s["k_final"] == args.spec_k_to)
        checks["identity"] = bool(checks.get("identity", True)
                                  and s["identity"])

    if "cli" in phases:
        w = _cli_phase(args, work, env)
        evidence_recs += [r for r in w.pop("records")
                          if r.get("event") == "tune"]
        lines.append({"metric": "tune_cli",
                      "value": w["tune_records"],
                      "unit": "applied tune records", **w})
        checks["cli_wired"] = bool(
            w["tune_records"] >= 1 and w["tune_summary"]
            and w["summary_tune_actions"] >= 1)

    ratio = None
    if "overhead" in phases:
        ab = _run([sys.executable, "-m",
                   "tensorflow_distributed_tpu.benchmarks.tunebench",
                   "--ab-only", "--out", "",
                   "--seed", str(args.seed),
                   "--num-slots", str(args.num_slots),
                   "--hand-dp", str(args.hand_dp),
                   "--prompt-len-min", str(args.prompt_len_min),
                   "--prompt-len-max", str(args.prompt_len_max),
                   "--ap-every", str(args.ap_every),
                   "--overhead-requests", str(args.overhead_requests),
                   "--overhead-new-tokens",
                   str(args.overhead_new_tokens),
                   "--overhead-repeats", str(args.overhead_repeats),
                   "--overhead-d-model", str(args.overhead_d_model)],
                  env, args.timeout, "overhead A/B")
        line = [ln for ln in ab.stdout.splitlines()
                if ln.startswith('{"ab_tps_off"')][-1]
        parsed = json.loads(line)
        tps_off, tps_on = parsed["ab_tps_off"], parsed["ab_tps_on"]
        ratio = tps_on / max(tps_off, 1e-9)
        lines.append({"metric": "tune_autopilot_tokens_per_sec",
                      "value": round(tps_on, 1), "unit": "tokens/sec",
                      "autopilot_off": round(tps_off, 1),
                      "ratio": round(ratio, 4)})
        checks["overhead_ok"] = bool(ratio >= args.min_tps_ratio)
        checks["min_tps_ratio"] = args.min_tps_ratio

    # Every decision across every leg auditable (vacuously true when
    # a leg selection produced no decisions at all).
    if any(r.get("event") == "tune" for r in evidence_recs):
        checks["evidence_ok"] = _tune_evidence_ok(evidence_recs)

    common_tags = {
        "model": "gpt_lm/tiny", "requests": args.requests,
        "new_tokens": args.new_tokens, "num_slots": args.num_slots,
        "hand_dp": args.hand_dp, "wrong_dp": args.wrong_dp,
        "slo": args.slo, "slo_windows": args.slo_windows,
        "ap_every": args.ap_every, "ap_confirm": args.ap_confirm,
        "ap_cooldown": args.ap_cooldown, "seed": args.seed,
    }
    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    gates = [v for k, v in checks.items()
             if k not in ("metric", "min_tps_ratio")]
    if not args.no_check and not all(bool(v) for v in gates):
        print(f"tunebench: checks FAILED: {checks}", file=sys.stderr)
        if not args.workdir:
            shutil.rmtree(work, ignore_errors=True)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
