"""Gradient-sync latency A/B: ICI allreduce vs parameter-server emulation.

This is the BASELINE.json metric "allreduce vs ps grad-sync latency",
measured rather than assumed. The reference synchronized gradients by
routing every worker's full gradient tensor through one parameter-server
process over gRPC/TCP and pulling the updated weights back — 2x full
push + 2x full pull per step through a single host NIC
(mnist_python_m.py:216-233; SURVEY.md §5 "communication backend"). The
TPU-native replacement is one XLA psum over ICI: gradients never leave
the chips.

Both sides of the A/B time ONLY the sync protocol on identically-shaped
gradient pytrees (the MNIST CNN's ~3.2M params by default); gradient
computation is excluded from both timed spans:

- ``allreduce``: jitted ``lax.pmean`` over the mesh "data" axis
  (parallel.collectives.allreduce_latency_probe).
- ``ps``: per-shard grads pulled to host numpy, averaged there,
  re-broadcast with device_put (parallel.collectives.ps_style_sync_probe)
  — an honest local-host stand-in for the reference's ps (it still pays
  device<->host transit + host aggregation, but NOT TCP, so the measured
  gap is a *lower bound* on the real one).

Prints one JSON line per metric plus a summary speedup line.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time
from typing import Callable, List


def _time_probe(probe: Callable[[], float], iters: int, warmup: int = 3
                ) -> List[float]:
    for _ in range(warmup):
        probe()
    return [probe() for _ in range(iters)]


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--iters", type=int, default=30)
    parser.add_argument("--model", default="mnist_cnn",
                        choices=["mnist_cnn", "resnet20"])
    args = parser.parse_args(argv)

    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.models import build_model
    from tensorflow_distributed_tpu.parallel.collectives import (
        allreduce_latency_probe, make_per_shard_grads, ps_style_sync_probe)
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=n_dev))
    sample = (np.zeros((2, 28, 28, 1), np.float32) if args.model == "mnist_cnn"
              else np.zeros((2, 32, 32, 3), np.float32))
    model = build_model(args.model, mesh=mesh, compute_dtype=jax.numpy.float32)
    state = create_train_state(model, optax.adam(1e-3), sample, mesh)
    n_params = param_count(state.params)

    # One real gradient computation provides the stacked per-shard grads
    # the ps probe consumes and the param-shaped buffers the allreduce
    # probe consumes.
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, (
        rng.normal(size=(2 * n_dev,) + sample.shape[1:]).astype(np.float32),
        rng.integers(0, 10, size=(2 * n_dev,)).astype(np.int32)))
    grad_fn = make_per_shard_grads(mesh)
    stacked = grad_fn(state, batch[0], batch[1])
    jax.block_until_ready(stacked)

    ps_probe = ps_style_sync_probe(mesh, stacked)
    ar_probe = allreduce_latency_probe(mesh, state.params)

    ps_times = _time_probe(ps_probe, args.iters)
    ar_times = _time_probe(ar_probe, args.iters)
    ps_ms = statistics.median(ps_times) * 1e3
    ar_ms = statistics.median(ar_times) * 1e3

    meta = {"model": args.model, "params": n_params, "devices": n_dev}
    print(json.dumps({
        "metric": "ps_grad_sync_latency_ms", "value": round(ps_ms, 3),
        "unit": "ms/step", **meta}))
    print(json.dumps({
        "metric": "allreduce_grad_sync_latency_ms", "value": round(ar_ms, 3),
        "unit": "ms/step", **meta}))
    print(json.dumps({
        "metric": "allreduce_vs_ps_speedup",
        "value": round(ps_ms / ar_ms, 2) if ar_ms > 0 else float("inf"),
        "unit": "x", **meta}))


if __name__ == "__main__":
    main()
