"""Gradient-sync benchmarks: the ps-era latency A/B and the overlap gate.

Two modes, one CLI:

**Legacy ps A/B** (no ``--family``; the BASELINE.json metric
"allreduce vs ps grad-sync latency"): the reference routed every
worker's full gradient through one parameter-server process over
gRPC/TCP (mnist_python_m.py:216-233; SURVEY.md §5); the TPU-native
replacement is one XLA psum over ICI. Both sides time ONLY the sync
protocol on identically-shaped gradient pytrees.

**Overlap A/B gate** (``--family gpt``): serial psum tail vs the
bucketed overlap path (parallel/overlap.py) on the REAL LM train step
at mesh >= 2 — the ROADMAP item 2 acceptance artifact:

- **identity**: serial and overlap training are BIT-identical over
  several steps (params, Adam slots, EMA — a NaN-poisoned step
  exercises the ``skip_nonfinite`` discard on both sides). The two
  formulations compute the same per-element sums by construction
  (psum_scatter/all_gather vs pmean; blocking-invariant elementwise
  optimizer math); what the gate additionally pins is that XLA:CPU
  COMPILES them to the same roundings — elementwise FMA contraction
  can differ between differently-fused programs (observed at
  --bucket-kb 64 with the skip-norm consumers in the graph), so the
  committed artifact runs the default config where the compiled
  programs agree bit-for-bit;
- **step time**: min-of-interleaved-steps (the planbench discipline —
  all candidates resident, measured round-robin, so host scheduling
  noise degrades every side equally) must satisfy
  ``overlap <= serial * (1 + tol)``; tol defaults to 10% on CPU hosts
  (virtual-device collectives are memcpys — the overlap win there is
  the 1/N sharded update, not hidden comm) and 0 on TPU, where a
  measurable win is required;
- **exposed communication**: an "unsynced" third program (same
  compute, collectives deleted — WRONG math, bench-only) gives the
  compute floor; ``exposed(side) = step_min(side) - unsynced_min``
  estimates each side's serial communication tail. On TPU the gate
  additionally requires the overlap side's exposure to SHRINK.
- the ``allreduce_latency_probe`` comm floor (min-of-N; the probe is
  warm since this PR — its first sample used to carry compile wall)
  is reported beside the exposure estimates for cross-checking.

Prints one JSON line per metric plus a ``gradsync_checks`` line;
``--out`` writes the full artifact (committed as GRADSYNC.json);
exit 1 on a failed gate (``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from typing import Any, Callable, Dict, List

from tensorflow_distributed_tpu.analysis.planner.plan import init_backend


def _time_probe(probe: Callable[[], float], iters: int, warmup: int = 3
                ) -> List[float]:
    for _ in range(warmup):
        probe()
    return [probe() for _ in range(iters)]


def _legacy_ps_ab(args) -> int:
    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.models import build_model
    from tensorflow_distributed_tpu.parallel.collectives import (
        allreduce_latency_probe, make_per_shard_grads, ps_style_sync_probe)
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=n_dev))
    sample = (np.zeros((2, 28, 28, 1), np.float32) if args.model == "mnist_cnn"
              else np.zeros((2, 32, 32, 3), np.float32))
    model = build_model(args.model, mesh=mesh, compute_dtype=jax.numpy.float32)
    state = create_train_state(model, optax.adam(1e-3), sample, mesh)
    n_params = param_count(state.params)

    # One real gradient computation provides the stacked per-shard grads
    # the ps probe consumes and the param-shaped buffers the allreduce
    # probe consumes.
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, (
        rng.normal(size=(2 * n_dev,) + sample.shape[1:]).astype(np.float32),
        rng.integers(0, 10, size=(2 * n_dev,)).astype(np.int32)))
    grad_fn = make_per_shard_grads(mesh)
    stacked = grad_fn(state, batch[0], batch[1])
    jax.block_until_ready(stacked)

    ps_probe = ps_style_sync_probe(mesh, stacked)
    ar_probe = allreduce_latency_probe(mesh, state.params)

    ps_times = _time_probe(ps_probe, args.iters)
    ar_times = _time_probe(ar_probe, args.iters)
    ps_ms = statistics.median(ps_times) * 1e3
    ar_ms = statistics.median(ar_times) * 1e3

    meta = {"model": args.model, "params": n_params, "devices": n_dev}
    print(json.dumps({
        "metric": "ps_grad_sync_latency_ms", "value": round(ps_ms, 3),
        "unit": "ms/step", **meta}))
    print(json.dumps({
        "metric": "allreduce_grad_sync_latency_ms", "value": round(ar_ms, 3),
        "unit": "ms/step", **meta}))
    print(json.dumps({
        "metric": "allreduce_vs_ps_speedup",
        "value": round(ps_ms / ar_ms, 2) if ar_ms > 0 else float("inf"),
        "unit": "x", **meta}))
    return 0


# --- the overlap A/B gate ----------------------------------------------

SIDES = ("serial", "overlap", "unsynced")


def _build_side(sync: str, mesh, model, loss, sh, args, donate: bool,
                skip_nonfinite: bool = False):
    """State + explicit step for one A/B side. Serial/unsynced run
    replicated slots (the serial tail's real layout); overlap runs
    zero1 slots at the same scatter threshold it buckets with."""
    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.parallel.overlap import (
        make_explicit_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    overlap = sync == "overlap"
    state = create_train_state(
        model, optax.adam(1e-3), np.zeros((2, args.seq_len), np.int32),
        mesh, seed=0, opt_fsdp=overlap, fsdp_min_size=args.min_scatter,
        ema=True)
    params_out = (jax.tree_util.tree_map(lambda a: a.sharding,
                                         state.params)
                  if overlap else None)
    step = make_explicit_train_step(
        mesh, state, loss=loss, batch_shardings=sh, grad_sync=sync,
        bucket_bytes=args.bucket_kb * 1024,
        fsdp_min_size=args.min_scatter, donate=donate, ema_decay=0.999,
        params_out_shardings=params_out, skip_nonfinite=skip_nonfinite)
    return state, step


def _bit_equal(a, b) -> bool:
    import jax
    import numpy as np

    fa, fb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(fa) == len(fb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(fa, fb))


def _overlap_ab(args) -> int:
    platform = init_backend(args.devices, tag="gradsync")
    import jax
    import numpy as np

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.parallel.collectives import (
        allreduce_latency_probe, min_latency)
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.overlap import (
        comm_bytes_per_step, plan_buckets)
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, mlm_batch_shardings)

    devices = args.devices or len(jax.devices())
    if devices < 2:
        print("gradsync: the overlap A/B needs >= 2 devices",
              file=sys.stderr)
        return 2
    if len(jax.devices()) < devices:
        print(f"gradsync: asked for {devices} devices but only "
              f"{len(jax.devices())} are visible", file=sys.stderr)
        return 2
    mesh = make_mesh(MeshConfig(data=devices), jax.devices()[:devices])
    # Mesh-less model: the explicit step's forward runs inside its
    # shard_map (parallel/overlap.py docstring).
    model = transformer.gpt_lm(
        mesh=None, size=args.size, tp_partitioning=False,
        dropout_rate=0.0, compute_dtype=jax.numpy.bfloat16,
        max_len=args.seq_len)
    loss = make_mlm_loss()
    sh = mlm_batch_shardings(mesh)
    ds = synthetic_clm(n=max(8 * args.batch, 128), seq_len=args.seq_len,
                       vocab_size=64)

    def put(i: int, poison: bool = False):
        b = ds.batch((np.arange(args.batch) + i * args.batch)
                     % ds.tokens.shape[0])
        if poison:
            b = dict(b)
            b["mask"] = np.asarray(b["mask"]) * np.nan
        return {k: jax.device_put(np.asarray(v), sh[k])
                for k, v in b.items()}

    meta: Dict[str, Any] = {
        "platform": platform, "devices": devices, "family": args.family,
        "size": args.size, "batch": args.batch, "seq_len": args.seq_len,
        "bucket_kb": args.bucket_kb, "min_scatter": args.min_scatter,
    }
    template, _ = _build_side("serial", mesh, model, loss, sh, args,
                              donate=False)
    plan = plan_buckets(template.params, devices,
                        bucket_bytes=args.bucket_kb * 1024,
                        fsdp_min_size=args.min_scatter)
    meta["plan"] = plan.describe()
    meta["comm_bytes_per_step"] = comm_bytes_per_step(plan)
    if not plan.scatter:
        print("gradsync: WARNING no scatterable leaves at "
              f"--min-scatter {args.min_scatter} — overlap degenerates "
              f"to fused psums", file=sys.stderr)

    # --- identity: serial vs overlap bit-equal, skip step included ---
    id_states = {}
    for sync in ("serial", "overlap"):
        st, step = _build_side(sync, mesh, model, loss, sh, args,
                               donate=False, skip_nonfinite=True)
        for i in range(args.identity_steps):
            st, m = step(st, put(i, poison=(i == 1)))
        jax.block_until_ready(m)
        id_states[sync] = st
    identity = {
        "params": _bit_equal(id_states["serial"].params,
                             id_states["overlap"].params),
        "opt_state": _bit_equal(id_states["serial"].opt_state,
                                id_states["overlap"].opt_state),
        "ema": _bit_equal(id_states["serial"].ema,
                          id_states["overlap"].ema),
    }
    print(json.dumps({"metric": "gradsync_identity", **identity,
                      "steps": args.identity_steps, **{
                          k: meta[k] for k in ("platform", "devices")}}))

    # --- step-time A/B: warm, then min-of-interleaved-steps ----------
    ctxs = {}
    for sync in SIDES:
        st, step = _build_side(sync, mesh, model, loss, sh, args,
                               donate=True)
        m = None
        for i in range(args.warmup):
            st, m = step(st, put(i))
        if m is not None:
            jax.block_until_ready(m)
        ctxs[sync] = {"state": st, "step": step, "i": args.warmup,
                      "walls": []}
    for _ in range(args.steps):
        for sync in SIDES:
            ctx = ctxs[sync]
            b = put(ctx["i"])
            ctx["i"] += 1
            t0 = time.perf_counter()
            ctx["state"], m = ctx["step"](ctx["state"], b)
            jax.block_until_ready(m)
            ctx["walls"].append(time.perf_counter() - t0)

    stats: Dict[str, Dict[str, float]] = {}
    for sync in SIDES:
        walls = sorted(ctxs[sync]["walls"])
        stats[sync] = {
            "min_ms": round(1e3 * walls[0], 4),
            "median_ms": round(1e3 * walls[len(walls) // 2], 4)}
        print(json.dumps({"metric": f"gradsync_step_{sync}",
                          **stats[sync], "steps": args.steps,
                          **{k: meta[k] for k in ("platform",
                                                  "devices")}}))

    # Comm floor: one warm mean-allreduce of the full param tree,
    # min-of-N (the satellite-fixed probe).
    floor_s = min_latency(
        allreduce_latency_probe(mesh, template.params), iters=10)
    exposed = {
        sync: round(stats[sync]["min_ms"] - stats["unsynced"]["min_ms"],
                    4)
        for sync in ("serial", "overlap")}
    print(json.dumps({"metric": "gradsync_exposed_comm_ms",
                      **exposed,
                      "allreduce_floor_ms": round(1e3 * floor_s, 4),
                      **{k: meta[k] for k in ("platform", "devices")}}))

    tol = args.tol if args.tol >= 0 else (0.0 if platform == "tpu"
                                          else 0.10)
    checks = {
        "identity": all(identity.values()),
        "overlap_not_slower": (
            stats["overlap"]["min_ms"]
            <= stats["serial"]["min_ms"] * (1.0 + tol)),
    }
    if platform == "tpu":
        # On real ICI the whole point is hiding the tail: require the
        # exposure estimate to shrink, not just the total.
        checks["exposed_shrinks"] = (exposed["overlap"]
                                     < exposed["serial"])
    ok = all(checks.values())
    line = {"metric": "gradsync_checks", "value": ok, **checks,
            "tol": tol, **{k: meta[k] for k in ("platform", "devices")}}
    print(json.dumps(line))

    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            artifact_stamp, default_calibration_path)
        artifact = {"meta": meta, "identity": identity, "steps": stats,
                    "exposed_comm_ms": exposed,
                    "allreduce_floor_ms": round(1e3 * floor_s, 4),
                    "checks": checks, "tol": tol, "ok": ok,
                    # Provenance for the regress ledger: what built
                    # this number, under which calibration profile.
                    **artifact_stamp(default_calibration_path())}
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
        print(f"gradsync: wrote {args.out}")
    if args.no_check:
        return 0
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    # Legacy ps A/B knobs:
    parser.add_argument("--iters", type=int, default=30)
    parser.add_argument("--model", default="mnist_cnn",
                        choices=["mnist_cnn", "resnet20"])
    # Overlap A/B gate knobs:
    parser.add_argument("--family", default="", choices=["", "gpt"],
                        help="LM family for the overlap A/B gate; "
                        "empty = the legacy ps-vs-allreduce A/B")
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--devices", type=int, default=0,
                        help="data-axis width (default: all visible; "
                        "on CPU forces that many virtual devices)")
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--steps", type=int, default=20,
                        help="interleaved timed visits per side")
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--identity-steps", type=int, default=4)
    parser.add_argument("--bucket-kb", type=int, default=8,
                        help="overlap bucket bound (KiB; tiny trees "
                        "want small buckets so several exist — "
                        "production runs use --grad-sync-bucket-mb)")
    parser.add_argument("--min-scatter", type=int, default=256,
                        help="scatterable-leaf threshold (elements); "
                        "the tiny preset's leaves sit under the "
                        "production FSDP_MIN_SIZE")
    parser.add_argument("--tol", type=float, default=-1.0,
                        help="overlap-vs-serial step-time tolerance "
                        "(-1 = auto: 0.10 on CPU, 0 on TPU)")
    parser.add_argument("--out", default="",
                        help="artifact JSON path ('' = don't write)")
    parser.add_argument("--no-check", action="store_true",
                        help="report without gating")
    args = parser.parse_args(argv)
    for flag in ("iters", "steps", "identity_steps"):
        if getattr(args, flag) < 1:
            parser.error(f"--{flag.replace('_', '-')} must be >= 1, "
                         f"got {getattr(args, flag)}")
    if args.warmup < 0:
        parser.error(f"--warmup must be >= 0, got {args.warmup}")
    if args.family:
        return _overlap_ab(args)
    return _legacy_ps_ab(args)


if __name__ == "__main__":
    sys.exit(main())
