"""Planner pick-quality gate: predict, then actually run the sweep.

The acceptance bar for the auto-layout planner (analysis/planner):
on a CPU-feasible sweep (mesh <= 8 devices, tiny gpt + moe), every
feasible candidate is ACTUALLY EXECUTED — same builders, same
shardings, real state — and

1. **pick quality**: the planner's top pick must measure within
   ``--pick-tol`` (default 15%) of the best measured candidate;
2. **HBM ranking**: the planner's predicted peak-HBM ordering must
   match the ordering ``memory_analysis`` reports for the EXECUTED
   steps' compiles (the abstract scoring path and the materialized
   path must describe the same programs).

Infeasible/unscoreable candidates are REPORTED (one line each, with
the reason), never dropped. The artifact is tagged with the effective
platform like bench.py — a CPU number must never be read against a
TPU trajectory unlabeled.

``--strategies`` restricts the sweep to strategy parts this container
can execute: the default (data,fsdp,zero1,expert) excludes tensor
shapes because this image's flax skew breaks TP at real-init time
(pre-existing, documented in CHANGES). The filter applies at
enumeration, so excluded shapes appear in this sweep's plan only as
pruned entries — the STANDALONE planner CLI (no --strategies) is
where TP shapes get AOT-scored on this container, via the abstract
state path that sidesteps the real-init skew.

Emits one JSON line per candidate plus a ``plan_checks`` line;
``--out`` writes PLANBENCH.json; exit 1 on any failed gate
(``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List

# stdlib-importable on purpose (no jax at module load): the shared
# mesh formatter and the one backend-init dance live with the planner.
from tensorflow_distributed_tpu.analysis.planner.candidates import (
    format_mesh)
from tensorflow_distributed_tpu.analysis.planner.plan import init_backend


def _prepare_candidate(cand, facts, batch: int, seq_len: int,
                       size: str, warmup: int,
                       moe_experts: int) -> Dict[str, Any]:
    """Build + warm one candidate for the interleaved measurement:
    real state, a batch feeder, and the EXECUTED step's own
    memory_analysis (via the same shared AOT/extraction path) for the
    ranking cross-check. Tiny-model states stay resident together —
    the sweep's candidates are measured round-robin, not one after
    another, so a transient load spike on the host degrades every
    candidate's samples equally instead of penalizing whichever one
    was running at the time (several tiny candidates compile to
    byte-identical programs; a sequential measurement would gate pure
    scheduling noise against the pick tolerance)."""
    import jax
    import numpy as np

    from tensorflow_distributed_tpu.analysis.planner.score import (
        build_candidate_step)
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.observe.device import (
        aot_lower_compile, extract_costs)
    from tensorflow_distributed_tpu.train.tasks import (
        mlm_batch_shardings)

    step, state, _, mesh = build_candidate_step(
        cand, facts, batch, seq_len=seq_len, size=size,
        moe_experts=moe_experts, abstract=False)
    sh = mlm_batch_shardings(mesh)
    ds = synthetic_clm(n=max(4 * batch, 64), seq_len=seq_len,
                       vocab_size=64)

    def put(i):
        b = ds.batch((np.arange(batch) + i * batch) % ds.tokens.shape[0])
        return {k: jax.device_put(v, sh[k]) for k, v in b.items()}

    executed_costs = extract_costs(
        aot_lower_compile(step, (state, put(0)))[1])
    m = None
    for i in range(warmup):
        state, m = step(state, put(i))
    if m is not None:
        jax.block_until_ready(m)
    return {"step": step, "state": state, "put": put, "i": warmup,
            "walls": [],
            "executed_peak_hbm_bytes":
                executed_costs["peak_hbm_bytes"]}


def _measure_round_robin(ctxs: List[Dict[str, Any]],
                         steps: int) -> None:
    """One timed step per candidate per visit, ``steps`` visits —
    appends walls in place."""
    import jax
    for _ in range(steps):
        for ctx in ctxs:
            b = ctx["put"](ctx["i"])
            ctx["i"] += 1
            t0 = time.perf_counter()
            ctx["state"], m = ctx["step"](ctx["state"], b)
            jax.block_until_ready(m)
            ctx["walls"].append(time.perf_counter() - t0)


def _wall_stats(walls: List[float]) -> Dict[str, Any]:
    walls = sorted(walls)
    return {"measured_step_ms": round(1e3 * walls[len(walls) // 2], 4),
            "measured_step_ms_min": round(1e3 * walls[0], 4)}


def _rank_keys(rows: List[Dict[str, Any]], field: str) -> List[str]:
    """Candidate keys ordered by ``field`` (stable tie-break on the
    key itself, applied identically to both orderings)."""
    return [r["key"] for r in sorted(
        rows, key=lambda r: (float(r[field]), r["key"]))]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--families", default="gpt,moe")
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--steps", type=int, default=10,
                        help="timed steps per candidate (taken "
                        "round-robin across candidates)")
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--moe-experts", type=int, default=0)
    parser.add_argument("--strategies",
                        default="data,fsdp,zero1,expert",
                        help="strategy parts the sweep may execute "
                        "(tensor excluded by default: this "
                        "container's flax skew breaks TP real-init)")
    parser.add_argument("--pick-tol", type=float, default=0.15,
                        help="top pick must measure within this "
                        "fraction of the best measured candidate")
    parser.add_argument("--calibration", default="",
                        help="calibration.json whose effective rates "
                        "replace the static roofline peaks (the "
                        "artifact is stamped with its id)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="PLANBENCH.json")
    args = parser.parse_args(argv)

    platform = init_backend(args.devices, tag="planbench")
    from tensorflow_distributed_tpu.analysis.planner import (
        candidates as cand_lib)
    from tensorflow_distributed_tpu.analysis.planner import plan as plan_lib

    strategies = [s.strip() for s in args.strategies.split(",")
                  if s.strip()]
    from tensorflow_distributed_tpu.observe.registry import (
        artifact_stamp)
    common_tags = {
        "devices": args.devices, "batch": args.batch,
        "seq_len": args.seq_len, "size": args.size,
        "steps": args.steps, "strategies": args.strategies,
        "platform": platform,
        **artifact_stamp(args.calibration),
    }
    lines: List[Dict[str, Any]] = []
    checks: Dict[str, Any] = {"metric": "plan_checks",
                              "pick_tol": args.pick_tol}
    ok = True
    for family in [f.strip() for f in args.families.split(",")
                   if f.strip()]:
        plan = plan_lib.make_plan(
            family, args.devices, args.batch, size=args.size,
            seq_len=args.seq_len, strategies=strategies,
            moe_experts=args.moe_experts,
            calibration=args.calibration)
        facts = cand_lib.model_facts(family, args.size,
                                     moe_experts=args.moe_experts)
        chosen = plan["chosen"]
        measured_rows: List[Dict[str, Any]] = []
        pending: List[Dict[str, Any]] = []  # (line, ctx) pairs
        for row in plan["candidates"]:
            key = f"{format_mesh(row['mesh'])}/{row['strategy']}"
            line: Dict[str, Any] = {
                "metric": "planbench_candidate", "family": family,
                "key": key, "mesh": row["mesh"],
                "strategy": row["strategy"],
                "partition": row["partition"],
                "predicted_step_ms": row.get("step_ms"),
                "predicted_peak_hbm_bytes": row.get("peak_hbm_bytes"),
                # Per-device AOT costs beside the prediction they
                # fed: the (costs, measured) pairs calibrate.py fits
                # effective rates from.
                "flops": row.get("flops"),
                "bytes_accessed": row.get("bytes_accessed"),
                "collective_bytes": row.get("collective_bytes"),
                "feasible": bool(row.get("feasible")),
            }
            lines.append(line)
            if not row.get("feasible"):
                # Reported, never dropped — and never executed: the
                # whole point of marking is not launching these.
                line["reason"] = (row.get("infeasible_reason")
                                  or row.get("error"))
                continue
            cand = cand_lib.Candidate.make(
                row["mesh"], row["partition"],
                microbatches=row.get("microbatches", 0))
            try:
                ctx = _prepare_candidate(
                    cand, facts, args.batch, args.seq_len, args.size,
                    args.warmup, args.moe_experts)
                pending.append({"line": line, "ctx": ctx})
            except Exception as e:
                line["execute_error"] = f"{type(e).__name__}: {e}"[:300]
        _measure_round_robin([p["ctx"] for p in pending], args.steps)
        for p in pending:
            p["line"].update(_wall_stats(p["ctx"]["walls"]))
            p["line"]["executed_peak_hbm_bytes"] = \
                p["ctx"]["executed_peak_hbm_bytes"]
            measured_rows.append(p["line"])
        # Gates.
        fam_checks: Dict[str, Any] = {}
        if chosen is None or not measured_rows:
            fam_checks["pick_ok"] = False
            fam_checks["why"] = ("no feasible pick" if chosen is None
                                 else "nothing executed")
        else:
            chosen_key = (f"{format_mesh(chosen['mesh'])}/"
                          f"{chosen['strategy']}")
            by_key = {r["key"]: r for r in measured_rows}
            # The ratio gates on MIN-of-steps, not the median: the
            # roofline predicts the noise-free step time, and min is
            # its stable estimator — at tiny scale several candidates
            # compile to byte-identical programs, so a median ratio
            # would measure host scheduling noise against the 15% bar.
            best = min(r["measured_step_ms_min"] for r in measured_rows)
            pick = by_key.get(chosen_key)
            fam_checks["top_pick"] = chosen_key
            fam_checks["executed"] = len(measured_rows)
            if pick is None:
                fam_checks["pick_ok"] = False
                fam_checks["why"] = "top pick failed to execute"
            else:
                ratio = pick["measured_step_ms_min"] / best
                fam_checks["pick_measured_ms"] = pick[
                    "measured_step_ms_min"]
                fam_checks["best_measured_ms"] = best
                fam_checks["pick_vs_best"] = round(ratio, 4)
                fam_checks["pick_ok"] = bool(
                    ratio <= 1.0 + args.pick_tol)
            hbm_rows = [r for r in measured_rows
                        if isinstance(r.get("predicted_peak_hbm_bytes"),
                                      (int, float))
                        and isinstance(r.get("executed_peak_hbm_bytes"),
                                       (int, float))]
            if len(hbm_rows) == len(measured_rows) and hbm_rows:
                fam_checks["hbm_rank_ok"] = bool(
                    _rank_keys(hbm_rows, "predicted_peak_hbm_bytes")
                    == _rank_keys(hbm_rows, "executed_peak_hbm_bytes"))
            else:
                # A backend with no memory_analysis can't be ranked —
                # reported as null, not silently passed.
                fam_checks["hbm_rank_ok"] = None
        checks[family] = fam_checks
        ok = ok and bool(fam_checks.get("pick_ok")) and (
            fam_checks.get("hbm_rank_ok") is not False)
    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    if not args.no_check and not ok:
        print(f"planbench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
