"""Transformer-LM training benchmark: tokens/s, TFLOP/s, and MFU.

The reference's entire perf surface is its hand-recorded 6-line
``performance`` table for the MNIST CNN (/root/reference/performance:1-6,
SURVEY.md §6) — a host-bound workload that says nothing about the MXU.
This benchmark is its TPU-native successor for the sequence family this
framework showcases: train the GPT-family causal LM at a real size
(GPT-2-small: 12L x 768d x 12H, models/transformer.py gpt_lm) and report

- tokens/sec through the full jitted train step (fwd + bwd + Adam),
- achieved model TFLOP/s and MFU against the chip's bf16 peak,
- a flash-vs-XLA attention A/B on the SAME training step (the only
  change is TransformerConfig.use_flash), turning the kernel's claimed
  speedup into a measured number.

FLOP accounting (the PaLM/MFU convention, matmuls only):
  per token fwd = 2 * N_matmul  (every matmul param is one MAC/token)
  attention     = 4 * L * d_model per layer fwd (QK^T and PV), halved
                  for causal because the kernel skips masked blocks
  fwd + bwd     = 3x forward
MFU counts the causal-SKIPPED FLOPs — the useful work, not the work a
lazier kernel would have done.

The batch lives on device and is reused every step: this measures the
model/step path (the MXU story); the host->device data path is
bench.py's story. A loss-decrease assertion guards against benchmarking
a degenerate graph.

Timing uses a host readback of the final step's loss as the barrier —
on the tunneled axon runtime block_until_ready alone can return before
remote execution finishes.
"""

from __future__ import annotations

import argparse
import json
import time

# FLOP accounting and chip peaks live in observe.mfu (the unified
# observability subsystem) — re-exported here so the historical
# benchmark import surface keeps working.
from tensorflow_distributed_tpu.observe.mfu import (  # noqa: F401
    PEAK_BF16_FLOPS, attn_flops_per_token_fwd, flops_per_token,
    matmul_params, pipelined_hw_flops_per_token)


def _build(size: str, seq_len: int, use_flash: bool, remat: str,
           batch: int, mesh, seed: int = 0, pipeline_mb: int = 0,
           pipeline_backward: str = "recompute", attn_window: int = 0,
           ce_chunk: int = 0, ce_impl: str = "scan"):
    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, mlm_batch_shardings, mlm_loss)

    kw = dict(max_len=seq_len, dropout_rate=0.0, use_flash=use_flash)
    if attn_window:
        kw["attn_window"] = attn_window
    if remat != "none":
        kw.update(remat=True, remat_policy=remat)
    if pipeline_mb > 0:
        # The flagship through the pipeline: pipelined_lm + the
        # hand-scheduled 1F1B step, flash kernel inside the pipe
        # shard_map (models/pipelined.py).
        from tensorflow_distributed_tpu.models.pipelined import (
            pipelined_lm)
        from tensorflow_distributed_tpu.train.pipeline_step import (
            make_1f1b_train_step)
        model = pipelined_lm(mesh, size=size,
                             num_microbatches=pipeline_mb, **kw)
    else:
        model = gpt_lm(mesh, size=size, **kw)
    state = create_train_state(
        model, optax.adam(3e-4), np.zeros((2, seq_len), np.int32), mesh,
        seed)
    if pipeline_mb > 0:
        step = make_1f1b_train_step(
            model, mesh, seed, batch_shardings=mlm_batch_shardings(mesh),
            backward=pipeline_backward, ce_chunk=ce_chunk)
    else:
        loss = (make_mlm_loss(ce_chunk=ce_chunk, ce_impl=ce_impl,
                              mesh=mesh) if ce_chunk else mlm_loss)
        step = make_train_step(mesh, seed, loss=loss,
                               batch_shardings=mlm_batch_shardings(mesh))
    ds = synthetic_clm(n=batch, seq_len=seq_len,
                       vocab_size=model.cfg.vocab_size, seed=seed)
    hb = ds.batch(np.arange(batch))
    dev_batch = shard_batch(mesh, hb, seq_axis=1)
    return model, state, step, dev_batch


def _timed_steps(step, state, batch, steps: int):
    """Steady-state steps/sec with async dispatch and an honest final
    readback barrier. Returns (dt_seconds, final_state, first, last)."""
    import jax

    state, metrics = step(state, batch)  # compile + step 1
    first_loss = float(jax.device_get(metrics["loss"]))
    for _ in range(2):                   # warm
        state, metrics = step(state, batch)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    last_loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    return dt, state, first_loss, last_loss


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="small",
                        choices=["small", "medium", "large", "xl",
                                 "tiny"])
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--remat", default="none",
                        choices=["none", "full", "dots"])
    parser.add_argument("--attn-window", type=int, default=0,
                        help="sliding-window attention width (0 = "
                        "full causal); the flash kernel skips "
                        "blocks outside the band, so tokens/s "
                        "should GROW as the window shrinks")
    parser.add_argument("--ce-chunk", type=int, default=0,
                        help="> 0: fused vocab-chunked head+loss (ops/"
                        "fused_ce.py) with this chunk width — the full "
                        "[B, L, V] logits are never materialized; "
                        "0 = dense path")
    parser.add_argument("--ce-impl", default="scan",
                        choices=["scan", "kernel"],
                        help="fused-loss formulation (with --ce-chunk): "
                        "lax.scan chunks or the Pallas flash-CE "
                        "kernels (ops/fused_ce_kernel.py)")
    parser.add_argument("--skip-ab", action="store_true",
                        help="skip the flash-vs-XLA attention A/B")
    parser.add_argument("--pipeline-backward", default="recompute",
                        choices=["recompute", "stash"],
                        help="1F1B backward strategy (see parallel."
                        "pipeline.pipeline_value_and_grad)")
    parser.add_argument("--pipeline-microbatches", type=int, default=0,
                        help="> 0: run the pipelined flagship instead "
                        "(1F1B schedule, flash inside the pipe "
                        "shard_map) with this many microbatches; the "
                        "mesh becomes (data=1, pipe=n_devices). The "
                        "flash-vs-XLA A/B is skipped in this mode")
    parser.add_argument("--out", default="",
                        help="also write the JSON lines to this file")
    args = parser.parse_args(argv)
    if args.pipeline_backward != "recompute" and not args.pipeline_microbatches:
        # Same convention as TrainConfig.validate: reject knobs that
        # would be silently ignored (the backward strategy only exists
        # in the pipelined 1F1B step).
        parser.error("--pipeline-backward requires "
                     "--pipeline-microbatches > 0")

    import jax
    import numpy as np

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.state import param_count
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    n_dev = len(jax.devices())
    pmb = args.pipeline_microbatches
    mesh = make_mesh(MeshConfig(data=1, pipe=n_dev) if pmb > 0
                     else MeshConfig(data=n_dev))
    kind = jax.devices()[0].device_kind
    peak = PEAK_BF16_FLOPS.get(kind)

    if args.ce_impl == "kernel" and pmb > 0:
        parser.error("--ce-impl kernel is not available in pipeline "
                     "mode (config.TrainConfig.validate has the why); "
                     "--ce-chunk with the default scan impl composes")
    if args.ce_impl != "scan" and not args.ce_chunk:
        # Same rule as TrainConfig.validate: refuse knobs that would
        # be silently ignored (and mislabel the benchmark record).
        parser.error("--ce-impl requires --ce-chunk > 0 (the fused "
                     "head+loss master switch)")
    model, state, step, batch = _build(
        args.size, args.seq_len, True, args.remat, args.batch, mesh,
        pipeline_mb=pmb, pipeline_backward=args.pipeline_backward,
        attn_window=args.attn_window, ce_chunk=args.ce_chunk,
        ce_impl=args.ce_impl)
    n_params = param_count(state.params)
    fpt = flops_per_token(state.params, model.cfg)

    dt, state, first, last = _timed_steps(step, state, batch, args.steps)
    assert np.isfinite(last), f"non-finite loss {last}"
    assert last < first, f"loss did not decrease: {first} -> {last}"

    tokens = args.steps * args.batch * args.seq_len
    tok_s = tokens / dt
    tflops = tok_s * fpt / 1e12
    mfu = tflops * 1e12 / (peak * n_dev) if peak else None

    family = ("pipelined_lm/1f1b" if pmb > 0 else "gpt_lm")
    meta = {"model": f"{family}/{args.size}", "params": n_params,
            "batch": args.batch, "seq_len": args.seq_len,
            "device": kind, "devices": n_dev, "remat": args.remat}
    if args.attn_window:
        meta["attn_window"] = args.attn_window
    if args.ce_chunk:
        meta["ce_chunk"] = args.ce_chunk
        meta["ce_impl"] = args.ce_impl
    if pmb > 0:
        meta["pipeline_microbatches"] = pmb
        meta["pipeline_backward"] = args.pipeline_backward
    lines = [
        {"metric": "lm_train_tokens_per_sec", "value": round(tok_s, 1),
         "unit": "tokens/sec", **meta},
        {"metric": "lm_train_model_tflops", "value": round(tflops, 2),
         "unit": "TFLOP/s", **meta},
        {"metric": "lm_train_mfu",
         "value": round(100 * mfu, 2) if mfu is not None else None,
         "unit": "%", **meta},
    ]
    if pmb > 0 and args.pipeline_backward == "recompute" and peak:
        # Model MFU charges 3x-forward per token, but 1F1B-recompute
        # EXECUTES 4x-forward for the block stack (each backward tick
        # re-runs the stage forward from the stashed input). Report the
        # hardware utilization too so the schedule's remat trade isn't
        # misread as MXU inefficiency; model MFU stays the headline
        # (useful work per second).
        hw_fpt = pipelined_hw_flops_per_token(state.params, model.cfg)
        hw_mfu = tok_s * hw_fpt / (peak * n_dev)
        lines.append({"metric": "lm_train_hw_mfu",
                      "value": round(100 * hw_mfu, 2), "unit": "%",
                      **meta})

    if not args.skip_ab and pmb > 0:
        import sys
        print("[lm_perf] flash-vs-XLA A/B skipped in pipeline mode "
              "(run without --pipeline-microbatches for it)",
              file=sys.stderr)
    if not args.skip_ab and pmb == 0:
        # STEP-LEVEL A/B, not a kernel microbenchmark: use_flash=False
        # re-jits the whole step (attention falls to the XLA path,
        # parallel.ring_attention.full_attention), so remat/fusion
        # differences elsewhere ride into the ratio too — the metric
        # name says "step_speedup" deliberately. Drop the flash run's
        # state/executable first — two resident GPT-2 train states
        # don't fit 16G HBM at batch 16.
        del state, step, batch
        _, state_x, step_x, batch_x = _build(
            args.size, args.seq_len, False, args.remat, args.batch, mesh,
            attn_window=args.attn_window, ce_chunk=args.ce_chunk,
            ce_impl=args.ce_impl)
        dt_x, _, _, last_x = _timed_steps(step_x, state_x, batch_x,
                                          args.steps)
        assert np.isfinite(last_x)
        lines.append({
            "metric": "flash_vs_xla_attention_step_speedup",
            "value": round(dt_x / dt, 3), "unit": "x",
            "xla_tokens_per_sec": round(tokens / dt_x, 1), **meta})

    print("\n".join(json.dumps(l) for l in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import write_jsonl
        write_jsonl(args.out, lines)


if __name__ == "__main__":
    main()
