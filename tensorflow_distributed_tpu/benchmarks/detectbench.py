"""Incident-detection benchmark: recall / precision / forensics gates.

The resilience fault plans are deterministic ground truth (PR 2/6:
every injection step is known and every injection leaves a
``fault_injected`` record), which makes the anomaly layer's quality a
GATEABLE benchmark, not a judgment call:

1. **train recall**: a tiny train run under a standard train fault
   plan (``nan_grad@A,data_stall@B``, non-finite policy = skip so the
   run survives its own faults) must flag EVERY injected fault kind
   with the expected detector within ``--within`` steps of injection
   (nan_grad -> ``loss_nonfinite``, data_stall -> ``step_time_spike``);
2. **serve recall**: the same for a serve run under
   ``decode_stall@A,slot_nan@B`` (decode_stall ->
   ``decode_time_spike``, slot_nan -> ``slot_nonfinite``), on the
   decode-step clock;
3. **precision**: the SAME seeded runs with no fault plan must emit
   ZERO anomaly records — the detectors' envelopes hold on clean
   traffic;
4. **bundle**: a supervised train leg (``nan_grad@A,sigkill@B``)
   dies without notice; the supervisor's restart event must name the
   dead leg's flight-recorder bundle, the bundle must parse
   (truncated-tail tolerant), its anomaly tail must name the last
   pre-death anomaly (the nan at A), and the postmortem CLI must
   render it;
5. **overhead**: min-of-interleaved A/B — the armed run (anomaly +
   flight recorder) keeps >= ``1 - overhead_tol`` of the control's
   steps/s (instrumentation <= 5% by default).

Emits one JSON line per metric plus a ``detect_checks`` line;
``--out`` writes DETECTBENCH.json (overwritten per run, like the
sibling benchmarks); exit 1 on any failed gate (``--no-check`` to
report without gating). ``--phases`` selects a subset (the t1 smoke
runs ``train,serve,bundle``; subprocess timing at smoke scale is
noise, so the overhead gate lives in the committed artifact run).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

#: fault kind -> anomaly detectors that count as detecting it.
TRAIN_EXPECT = {"nan_grad": ("loss_nonfinite",),
                "data_stall": ("step_time_spike",)}
SERVE_EXPECT = {"decode_stall": ("decode_time_spike",),
                "slot_nan": ("slot_nonfinite",)}


def _run(cmd, env, timeout, what, check=True):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    if check and proc.returncode != 0:
        print(f"detectbench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def _records(path):
    from tensorflow_distributed_tpu.observe.report import load_records
    return load_records(path)


def _recall(records, expect, within):
    """Per injected fault: was an expected-detector anomaly raised
    within ``within`` steps of the injection step the ground-truth
    ``fault_injected`` record names?"""
    injected = {}
    for r in records:
        if (r.get("event") == "recovery"
                and r.get("kind") == "fault_injected"
                and r.get("fault") in expect):
            injected.setdefault(str(r["fault"]), int(r.get("step", 0)))
    anoms = [r for r in records if r.get("event") == "anomaly"]
    detail = {}
    for fault, step in sorted(injected.items()):
        hits = [int(a.get("step", 0)) for a in anoms
                if str(a.get("detector", "")).split("/", 1)[0]
                in expect[fault] and int(a.get("step", 0)) >= step]
        detected = min(hits) if hits else None
        detail[fault] = {
            "detector": expect[fault][0], "injected": step,
            "detected": detected,
            "delay": None if detected is None else detected - step,
            "flagged": bool(detected is not None
                            and detected - step <= within),
        }
    return detail, len(injected)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--phases", default="train,serve,bundle,overhead")
    parser.add_argument("--train-steps", type=int, default=28)
    parser.add_argument("--serve-requests", type=int, default=10)
    parser.add_argument("--new-tokens", type=int, default=40)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--stall-s", type=float, default=0.8)
    parser.add_argument("--within", type=int, default=3,
                        help="max detection delay (steps of the "
                        "phase's clock) the recall gate allows")
    parser.add_argument("--overhead-steps", type=int, default=40)
    parser.add_argument("--overhead-tol", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--timeout", type=float, default=420.0)
    parser.add_argument("--workdir", default="",
                        help="scratch dir (default: a fresh tempdir, "
                        "removed on success)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="DETECTBENCH.json")
    args = parser.parse_args(argv)
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]

    work = args.workdir or tempfile.mkdtemp(prefix="detectbench-")
    os.makedirs(work, exist_ok=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"
    cli = [sys.executable, "-m", "tensorflow_distributed_tpu.cli"]

    # The float-input family (nan_grad poisons the batch's float
    # leaves; token streams have none) at log_every=1 so every step's
    # loss/wall is a detector sample.
    train_common = [
        "--model", "mnist_cnn", "--dataset", "synthetic",
        "--batch-size", "64", "--eval-every", "0", "--log-every", "1",
        "--seed", str(args.seed), "--observe.anomaly", "true",
    ]
    serve_common = [
        "--mode", "serve", "--model", "gpt_lm", "--model-size", "tiny",
        "--compute-dtype", "float32", "--seq-len", str(args.seq_len),
        "--seed", str(args.seed),
        "--serve.num-slots", "2",
        "--serve.num-requests", str(args.serve_requests),
        "--serve.prompt-len-min", "4", "--serve.prompt-len-max", "8",
        "--serve.max-new-tokens", str(args.new_tokens),
        # Gentle spaced arrivals: the CLEAN leg must have no queueing
        # regime shift for the TTFT/queue detectors to misread as an
        # incident — precision is half the gate.
        "--serve.arrival-rate", "2",
        "--serve.buckets", str(args.seq_len),
        "--observe.anomaly", "true",
    ]
    k_nan = max(4, args.train_steps // 3)
    k_stall = max(k_nan + 4, (2 * args.train_steps) // 3)
    train_plan = f"nan_grad@{k_nan},data_stall@{k_stall}:{args.stall_s}s"
    est_steps = args.serve_requests * args.new_tokens // 2
    s_stall = max(10, est_steps // 8)
    s_nan = max(s_stall + 6, est_steps // 4)
    serve_plan = (f"decode_stall@{s_stall}:{args.stall_s}s,"
                  f"slot_nan@{s_nan}:0")

    lines, checks = [], {"metric": "detect_checks"}

    if "train" in phases:
        fire_jsonl = os.path.join(work, "train_fire.jsonl")
        _run(cli + train_common + [
            "--train-steps", str(args.train_steps),
            "--observe.metrics-jsonl", fire_jsonl,
            "--resilience.nonfinite", "skip_batch",
            "--resilience.fault-plan", train_plan,
        ], env, args.timeout, "train fire leg")
        clean_jsonl = os.path.join(work, "train_clean.jsonl")
        _run(cli + train_common + [
            "--train-steps", str(args.train_steps),
            "--observe.metrics-jsonl", clean_jsonl,
            "--resilience.nonfinite", "skip_batch",
        ], env, args.timeout, "train clean leg")
        detail, n_inj = _recall(_records(fire_jsonl), TRAIN_EXPECT,
                                args.within)
        clean_anoms = [r for r in _records(clean_jsonl)
                       if r.get("event") == "anomaly"]
        flagged = sum(1 for d in detail.values() if d["flagged"])
        lines.append({"metric": "detect_train_recall",
                      "flagged": flagged, "of": n_inj,
                      "plan": train_plan, "detail": detail})
        lines.append({"metric": "detect_train_precision",
                      "anomalies": len(clean_anoms),
                      "detectors": sorted({str(r.get("detector"))
                                           for r in clean_anoms})})
        checks["train_recall_ok"] = bool(n_inj == len(TRAIN_EXPECT)
                                         and flagged == n_inj)
        checks["train_precision_ok"] = not clean_anoms

    if "serve" in phases:
        fire_jsonl = os.path.join(work, "serve_fire.jsonl")
        _run(cli + serve_common + [
            "--observe.metrics-jsonl", fire_jsonl,
            "--resilience.fault-plan", serve_plan,
        ], env, args.timeout, "serve fire leg")
        clean_jsonl = os.path.join(work, "serve_clean.jsonl")
        _run(cli + serve_common + [
            "--observe.metrics-jsonl", clean_jsonl,
        ], env, args.timeout, "serve clean leg")
        detail, n_inj = _recall(_records(fire_jsonl), SERVE_EXPECT,
                                args.within)
        clean_anoms = [r for r in _records(clean_jsonl)
                       if r.get("event") == "anomaly"]
        flagged = sum(1 for d in detail.values() if d["flagged"])
        lines.append({"metric": "detect_serve_recall",
                      "flagged": flagged, "of": n_inj,
                      "plan": serve_plan, "detail": detail})
        lines.append({"metric": "detect_serve_precision",
                      "anomalies": len(clean_anoms),
                      "detectors": sorted({str(r.get("detector"))
                                           for r in clean_anoms})})
        checks["serve_recall_ok"] = bool(n_inj == len(SERVE_EXPECT)
                                         and flagged == n_inj)
        checks["serve_precision_ok"] = not clean_anoms

    if "bundle" in phases:
        from tensorflow_distributed_tpu.observe.flightrec import (
            load_bundle)
        from tensorflow_distributed_tpu.observe import postmortem
        flight = os.path.join(work, "flight")
        ckpt = os.path.join(work, "ckpt")
        jsonl = os.path.join(work, "bundle.jsonl")
        steps = max(12, args.train_steps // 2)
        b_nan = max(3, steps // 3)
        b_kill = max(b_nan + 3, (2 * steps) // 3)
        # Die WITHOUT notice mid-run; the supervisor resumes from the
        # cadence checkpoint (bind() consumes the plan, so leg 2
        # completes clean) and must name leg 1's bundle.
        _run([sys.executable, "-m",
              "tensorflow_distributed_tpu.resilience.supervisor",
              "--max-restarts", "2", "--backoff-base-s", "0.2", "--",
              *train_common, "--train-steps", str(steps),
              "--checkpoint-dir", ckpt, "--checkpoint-every", "4",
              "--observe.metrics-jsonl", jsonl,
              "--observe.flightrec", flight,
              "--resilience.nonfinite", "skip_batch",
              "--resilience.fault-plan",
              f"nan_grad@{b_nan},sigkill@{b_kill}",
              ], env, args.timeout, "supervised sigkill leg")
        restart = [r for r in _records(jsonl)
                   if r.get("event") == "recovery"
                   and r.get("kind") == "restart"]
        bundle_path = restart[0].get("bundle") if restart else None
        parsed = last_anom = None
        cli_ok = False
        if bundle_path and os.path.exists(bundle_path):
            parsed = load_bundle(bundle_path)
            anoms = parsed["last"].get("anomaly", [])
            last_anom = anoms[-1] if anoms else None
            buf = __import__("io").StringIO()
            import contextlib as _ctx
            with _ctx.redirect_stdout(buf):
                cli_ok = postmortem.main([bundle_path]) == 0
            cli_ok = cli_ok and "Likely cause" in buf.getvalue()
        lines.append({
            "metric": "detect_bundle",
            "bundle": bundle_path,
            "bundle_kind": (parsed or {}).get("meta", {}).get("bundle"),
            "records": len((parsed or {}).get("records", [])),
            "named_in_restart": bool(bundle_path),
            "last_anomaly_detector": (last_anom or {}).get("detector"),
            "last_anomaly_step": (last_anom or {}).get("step"),
            "postmortem_cli_ok": cli_ok,
        })
        checks["bundle_ok"] = bool(
            bundle_path and parsed and parsed["records"]
            and last_anom
            and last_anom.get("detector") == "loss_nonfinite"
            and last_anom.get("step") == b_nan and cli_ok)

    if "overhead" in phases:
        def leg(tag, armed, i):
            path = os.path.join(work, f"ovh_{tag}{i}.jsonl")
            extra = (["--observe.anomaly", "true",
                      "--observe.flightrec",
                      os.path.join(work, f"ovh_flight{i}")]
                     if armed else [])
            base = [a for a in train_common
                    if a not in ("--observe.anomaly", "true")]
            _run(cli + base + [
                "--train-steps", str(args.overhead_steps),
                "--observe.metrics-jsonl", path, *extra,
            ], env, args.timeout, f"overhead {tag} leg {i}")
            sums = [r for r in _records(path)
                    if r.get("event") == "summary"]
            return float(sums[-1]["steps_per_sec"])

        # INTERLEAVED A/B (ctl, arm, ctl, arm — monotonic machine
        # drift lands on both arms), best-of-2 per arm (min wall =
        # max steps/s): fresh interpreters, warm persistent compile
        # cache.
        control, armed = [], []
        for i in range(2):
            control.append(leg("ctl", False, i))
            armed.append(leg("arm", True, i))
        ratio = max(armed) / max(control)
        lines.append({"metric": "detect_overhead",
                      "ratio": round(ratio, 4),
                      "armed_steps_per_sec": round(max(armed), 3),
                      "control_steps_per_sec": round(max(control), 3),
                      "legs_per_arm": 2})
        checks["overhead_ok"] = bool(ratio >= 1.0 - args.overhead_tol)
        checks["overhead_tol"] = args.overhead_tol

    checks["within_steps"] = args.within
    recall_keys = [k for k in ("train_recall_ok", "serve_recall_ok")
                   if k in checks]
    precision_keys = [k for k in ("train_precision_ok",
                                  "serve_precision_ok") if k in checks]
    checks["recall_ok"] = all(checks[k] for k in recall_keys) \
        if recall_keys else None
    checks["precision_ok"] = all(checks[k] for k in precision_keys) \
        if precision_keys else None
    lines.append(checks)
    common_tags = {"seed": args.seed, "phases": ",".join(phases)}
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    gates = [v for k, v in checks.items()
             if k.endswith("_ok") and v is not None]
    ok = bool(gates) and all(gates)
    if not args.no_check and not ok:
        print(f"detectbench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
