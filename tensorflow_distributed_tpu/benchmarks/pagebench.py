"""Paged-KV + radix-prefix-reuse benchmark -> PAGEBENCH.json.

The serving claim the paging subsystem (serve/paging) exists for:
shared-prompt traffic served WITHOUT recomputing common prefixes and
WITHOUT reserving dense ``[max_len]`` KV rows per slot. One seeded
shared-prefix trace (a few distinct "system prompts" + per-request
tails, then a second MULTI-TURN round whose prompts extend round one's
conversations) is served twice — the dense engine vs the paged engine,
same model, same buckets, same scheduler — and four things are gated:

- **token identity** (100%): every paged stream equals the dense
  stream, and the dense streams equal one-shot greedy ``generate()``
  (the pre-paging engine contract — ``--serve.paged off`` output is
  the same engine class untouched);
- **prefill FLOPs saved >= --min-flops-saved** (0.6): padded prefill
  tokens the device actually computes, paged vs dense (the paged
  engine prefills only uncached tails; FLOPs scale with the same
  2 * params * tokens both sides, so the token ratio IS the FLOPs
  ratio at leading order);
- **slots at HBM budget >= --min-slots-ratio x dense** (1.5): the
  dense run RESERVES num_slots * bytes_per_slot; the paged run's pool
  PEAKS at pages_peak * page_bytes serving the same trace — the ratio
  is how many more slots the same budget holds (composes with int8
  KV's 1.88x: both shrink bytes, independently);
- **warm-prefix p50 TTFT** <= --max-warm-ttft-ratio x dense: the
  second round's turns (session re-attach, tail-only prefill) against
  the dense engine's full re-prefill, spaced arrivals so TTFT
  measures prefill, not queueing.

Run from the repo root (CPU ok):
    python -m tensorflow_distributed_tpu.benchmarks.pagebench
``--out PAGEBENCH.json`` is committed; scripts/t1.sh runs a smoke
subset with relaxed FLOPs floors (fewer requests = fewer warm hits).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _serve(engine, requests, decode_priority: int = 4):
    """One scheduler run -> ({rid: Completion}, summary)."""
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler

    sched = Scheduler(engine, decode_priority=decode_priority)
    done = sched.run(requests)
    return {c.rid: c for c in done}, sched.summary


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--requests", type=int, default=16,
                        help="round-1 requests (round 2 adds one "
                        "follow-up turn per round-1 request)")
    parser.add_argument("--prefixes", type=int, default=3,
                        help="distinct shared system prompts")
    parser.add_argument("--prefix-len", type=int, default=96)
    parser.add_argument("--tail-min", type=int, default=4)
    parser.add_argument("--tail-max", type=int, default=12)
    parser.add_argument("--new-tokens", type=int, default=8)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--turn2-gap", type=float, default=0.25,
                        help="round-2 arrival spacing (s): TTFT "
                        "measures prefill, not queueing")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-flops-saved", type=float, default=0.6)
    parser.add_argument("--min-slots-ratio", type=float, default=1.5)
    parser.add_argument("--max-warm-ttft-ratio", type=float,
                        default=0.9)
    parser.add_argument("--no-check", action="store_true",
                        help="report without gating")
    parser.add_argument("--out", default="PAGEBENCH.json")
    args = parser.parse_args(argv)
    if args.requests < args.prefixes:
        parser.error("--requests must be >= --prefixes")
    if not 1 <= args.tail_min <= args.tail_max:
        parser.error("need 1 <= --tail-min <= --tail-max")

    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.models.generate import generate
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import (
        single_device_mesh)
    from tensorflow_distributed_tpu.serve.buckets import (
        default_buckets, pick_bucket)
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.paging.engine import (
        PagedSlotEngine)
    from tensorflow_distributed_tpu.serve.scheduler import Request
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    import jax.numpy as jnp

    dev = jax.devices()[0]
    rng = np.random.default_rng(args.seed)

    # Cache length: the longest round-2 trajectory, page-aligned.
    worst = (args.prefix_len + 2 * args.tail_max
             + 2 * args.new_tokens)
    max_len = -(-worst // args.page_size) * args.page_size + \
        args.page_size
    # A model big enough that prefill COMPUTE (not dispatch overhead)
    # is what the warm-TTFT gate measures on CPU.
    mesh = single_device_mesh(dev)
    model = gpt_lm(mesh, size="tiny", d_model=128, n_layers=4,
                   n_heads=4, d_ff=512, max_len=max_len,
                   dropout_rate=0.0)
    state = create_train_state(model, optax.identity(),
                               np.zeros((2, 16), np.int32), mesh,
                               seed=0)
    params = state.params
    V = model.cfg.vocab_size

    # Round 1: shared system prompts + per-request tails. Sessions
    # carry the conversation into round 2.
    prefixes = [rng.integers(0, V, size=args.prefix_len).astype(
        np.int32) for _ in range(args.prefixes)]
    round1 = []
    for i in range(args.requests):
        tail = rng.integers(0, V, size=int(rng.integers(
            args.tail_min, args.tail_max + 1))).astype(np.int32)
        round1.append(Request(
            rid=i, prompt=np.concatenate([prefixes[i % args.prefixes],
                                          tail]),
            max_new_tokens=args.new_tokens, session=f"conv{i}"))
    cover = max(len(r.prompt) for r in round1) + args.tail_max + \
        args.new_tokens + 1
    buckets = default_buckets(min(cover, max_len), cap=max_len)

    def round2_from(done):
        """Follow-up turns: each round-1 conversation (prompt + its
        served reply) extended by fresh user tokens — spaced arrivals
        so TTFT isolates prefill."""
        rng2 = np.random.default_rng(args.seed + 1)
        out = []
        for i in range(args.requests):
            conv = np.concatenate(
                [round1[i].prompt,
                 np.asarray(done[i].tokens, np.int32)])
            ext = rng2.integers(0, V, size=int(rng2.integers(
                args.tail_min, args.tail_max + 1))).astype(np.int32)
            out.append(Request(
                rid=1000 + i, prompt=np.concatenate([conv, ext]),
                max_new_tokens=args.new_tokens,
                arrival_s=i * args.turn2_gap, session=f"conv{i}"))
        return out

    # --- dense: the pre-paging engine -------------------------------
    dense = SlotDecodeEngine(model, params, args.num_slots,
                             buckets=buckets)
    dense.warmup()
    t0 = time.perf_counter()
    d1, _ = _serve(dense, round1)
    dense_r2 = round2_from(d1)
    d2, _ = _serve(dense, dense_r2)
    dense_wall = time.perf_counter() - t0
    dense_computed = sum(
        pick_bucket(len(r.prompt), buckets)
        for r in round1 + dense_r2)

    # Pre-paging contract: the dense streams equal one-shot greedy
    # generate() per request (--serve.paged off IS this engine).
    ident_dense = 0
    for r in round1 + dense_r2:
        ref = np.asarray(generate(
            model, params, jnp.asarray(r.prompt[None, :]),
            args.new_tokens))[0]
        got = d1[r.rid].tokens if r.rid < 1000 else d2[r.rid].tokens
        ident_dense += bool(np.array_equal(ref, np.asarray(got)))

    # --- paged: pool + radix + sessions -----------------------------
    paged = PagedSlotEngine(model, params, args.num_slots,
                            page_size=args.page_size,
                            buckets=buckets)
    paged.warmup()
    t0 = time.perf_counter()
    p1, _ = _serve(paged, [
        Request(rid=r.rid, prompt=r.prompt,
                max_new_tokens=r.max_new_tokens, session=r.session)
        for r in round1])
    paged_r2 = round2_from(p1)
    p2, sum2 = _serve(paged, paged_r2)
    paged_wall = time.perf_counter() - t0
    pstats = paged.paging_stats()

    # --- gates ------------------------------------------------------
    n_total = 2 * args.requests
    ident = sum(bool(np.array_equal(np.asarray(d1[i].tokens),
                                    np.asarray(p1[i].tokens)))
                for i in range(args.requests))
    ident += sum(bool(np.array_equal(np.asarray(d2[1000 + i].tokens),
                                     np.asarray(p2[1000 + i].tokens)))
                 for i in range(args.requests))
    saved = 1.0 - pstats["prefill_tokens_computed"] / max(
        1, dense_computed)
    # FLOPs view: prefill forward ~ 2 * params * tokens both sides.
    mflops = 2e-6 * param_count(params)
    dense_reserved = args.num_slots * dense.cache_bytes_per_slot()
    # The serving WORKING SET: distinct pages live slots held at peak
    # (shared prefix pages once). Cached pages sit outside it — they
    # are evictable the moment an admission needs the room, so a
    # budget sized to the working set still serves this trace.
    paged_peak = pstats["slot_pages_peak"] * pstats["page_bytes"]
    slots_ratio = dense_reserved / max(1, paged_peak)
    warm_d = 1e3 * float(np.percentile(
        [d2[1000 + i].ttft_s for i in range(args.requests)], 50))
    warm_p = 1e3 * float(np.percentile(
        [p2[1000 + i].ttft_s for i in range(args.requests)], 50))
    ttft_ratio = warm_p / max(warm_d, 1e-9)

    checks = {
        "metric": "page_checks",
        "token_identical": ident, "of": n_total,
        "dense_identical": ident_dense, "dense_of": n_total,
        "flops_ok": bool(saved >= args.min_flops_saved),
        "min_flops_saved": args.min_flops_saved,
        "slots_ok": bool(slots_ratio >= args.min_slots_ratio),
        "min_slots_ratio": args.min_slots_ratio,
        "ttft_ok": bool(ttft_ratio <= args.max_warm_ttft_ratio),
        "max_warm_ttft_ratio": args.max_warm_ttft_ratio,
        "lost": n_total - len(p1) - len(p2),
        "evictions": pstats["page_evictions"],
        "cow_copies": pstats["cow_copies"],
    }
    lines = [
        {"metric": "page_prefill_flops",
         "dense_tokens": dense_computed,
         "paged_tokens": pstats["prefill_tokens_computed"],
         "dense_mflops": round(mflops * dense_computed, 1),
         "paged_mflops": round(
             mflops * pstats["prefill_tokens_computed"], 1),
         "saved_frac": round(saved, 4),
         "model_params": param_count(params),
         "requests": n_total, "prefixes": args.prefixes,
         "prefix_len": args.prefix_len,
         "buckets": ",".join(str(b) for b in buckets)},
        {"metric": "page_hit",
         "rate": pstats["prefix_hit_rate"],
         "hits": pstats["prefix_hits"],
         "hit_tokens": pstats["prefix_hit_tokens"],
         "prompt_tokens": pstats["prompt_tokens"],
         "sessions": pstats.get("sessions", 0),
         "cached_pages": pstats.get("cached_pages", 0)},
        {"metric": "page_hbm",
         "page_size": args.page_size,
         "page_bytes": pstats["page_bytes"],
         "pages_per_max_len": pstats["pages_per_max_len"],
         "dense_bytes_per_slot": dense.cache_bytes_per_slot(),
         "dense_reserved_bytes": dense_reserved,
         "paged_working_set_bytes": paged_peak,
         "slot_pages_peak": pstats["slot_pages_peak"],
         "pool_pages_peak": pstats["pages_peak"],
         "slots_ratio": round(slots_ratio, 3),
         "slots_at_budget_dense": args.num_slots,
         "slots_at_budget_paged": int(
             dense_reserved // max(1, paged_peak // args.num_slots)),
         "unit": "x"},
        {"metric": "page_warm_ttft",
         "dense_p50_ms": round(warm_d, 2),
         "paged_p50_ms": round(warm_p, 2),
         "ratio": round(ttft_ratio, 3),
         "turn2_gap_s": args.turn2_gap, "unit": "ms"},
        {"metric": "page_walls",
         "dense_wall_s": round(dense_wall, 3),
         "paged_wall_s": round(paged_wall, 3),
         "paged_pool_occupancy": pstats["pool_occupancy"]},
        checks,
    ]
    common = {"device": dev.device_kind, "seed": args.seed}
    lines = [dict(ln, **common) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    ok = (ident == n_total and ident_dense == n_total
          and checks["lost"] == 0
          and checks["flops_ok"] and checks["slots_ok"]
          and checks["ttft_ok"])
    if not args.no_check and not ok:
        print("pagebench: GATE FAILED "
              f"(identity {ident}/{n_total}, dense {ident_dense}/"
              f"{n_total}, saved {saved:.3f}, slots {slots_ratio:.2f}"
              f"x, ttft {ttft_ratio:.3f}x)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
