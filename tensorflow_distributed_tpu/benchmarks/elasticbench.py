"""Elastic-restart benchmark: train, lose chips, continue on a
different mesh — same loss, zero lost steps.

The robustness claim this pins (ISSUE 7 / ROADMAP item 4): a
checkpoint written on mesh A resumes on mesh B — shrinking after a
``device_loss`` under ``supervisor --elastic``, or growing onto
returned capacity with a plain ``--resume`` — with the loss
trajectory matching a never-interrupted run (same global batch, same
data order; per-device batch re-derives from the new data-axis
width), ZERO completed steps lost, and the resharded restore verified
by the sharding-contract checker (``--check`` on every child plus
``restore_resharded``'s own assertion).

Procedure (all runs are CLI subprocesses, so the kill is real):

1. BASELINE: an uninterrupted run on the initial mesh.
2. SHRINK: the same run under ``resilience.supervisor --elastic``
   with ``device_loss@K:L`` — at step K the drill writes the
   device-mask file and SIGKILLs; the supervisor probes the
   survivors, degrades the mesh, and the resharded resume continues
   to the horizon. K defaults to one step past a checkpoint cadence,
   so the resume replays nothing: zero completed steps lost.
3. GROW: a first leg trains to the same kill point on the initial
   mesh and exits cleanly (final save); a second leg resumes with
   MORE devices — the capacity-comeback direction of the same
   resharded restore.
4. Gates: both elastic runs reach the full horizon, resume exactly at
   the pre-kill checkpoint, emit a ``reshard_restore`` recovery event
   (its ``seconds`` is the reported resharded-restore wall), and land
   a final loss within ``--loss-tol`` of the baseline's.

Emits one JSON line per metric plus an ``elastic_checks`` line;
``--out`` writes ELASTICBENCH.json; exit 1 on any failed gate
(``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile


def _env(n_devices: int) -> dict:
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith(
                 "--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"
    return env


def _run(cmd, env, timeout, what):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        print(f"elasticbench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def _facts(jsonl: str) -> dict:
    """The gate-relevant facts of one run's metrics JSONL: final loss,
    steps completed, resume point, and the reshard event."""
    from tensorflow_distributed_tpu.observe.report import load_records
    recs = load_records(jsonl)
    steps = [r for r in recs if r.get("event") == "step"]
    summaries = [r for r in recs if r.get("event") == "summary"]
    resumed = [r for r in recs if r.get("event") == "resumed"]
    reshard = [r for r in recs if r.get("event") == "recovery"
               and r.get("kind") == "reshard_restore"]
    return {
        "last_loss": (float(steps[-1]["loss"])
                      if steps and "loss" in steps[-1] else None),
        "steps": (int(summaries[-1].get("steps", 0))
                  if summaries else None),
        "resumed_step": (int(resumed[-1]["step"]) if resumed else None),
        "reshard": reshard[-1] if reshard else None,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--devices", type=int, default=4,
                        help="initial mesh data width (and visible "
                        "device count for those legs)")
    parser.add_argument("--lose", type=int, default=2,
                        help="chips the device_loss drill takes")
    parser.add_argument("--grow-to", type=int, default=8,
                        help="mesh width of the capacity-comeback "
                        "resume (0 = skip the grow run)")
    parser.add_argument("--steps", type=int, default=24)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--ckpt-every", type=int, default=6)
    parser.add_argument("--kill-step", type=int, default=0,
                        help="device_loss step (default: one past the "
                        "second checkpoint cadence, so the resume "
                        "replays zero completed steps)")
    parser.add_argument("--loss-tol", type=float, default=1e-3)
    parser.add_argument("--timeout", type=float, default=420.0,
                        help="per-subprocess timeout (s)")
    parser.add_argument("--workdir", default="",
                        help="scratch dir (default: a fresh tempdir, "
                        "removed on success)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="ELASTICBENCH.json")
    args = parser.parse_args(argv)
    if not 0 < args.lose < args.devices:
        parser.error("--lose must leave at least one device alive")
    if args.batch % args.devices or (
            args.grow_to and args.batch % args.grow_to):
        parser.error("--batch must divide by --devices and --grow-to")
    kill = args.kill_step or 2 * args.ckpt_every + 1
    if not args.ckpt_every < kill <= args.steps:
        parser.error("--kill-step must land after the first "
                     "checkpoint and within --steps")

    work = args.workdir or tempfile.mkdtemp(prefix="elasticbench-")
    os.makedirs(work, exist_ok=True)
    common = [
        "--dataset", "synthetic", "--batch-size", str(args.batch),
        "--train-steps", str(args.steps), "--eval-every", "0",
        "--log-every", "1", "--eval-batch-size", str(args.batch),
        "--compute-dtype", "float32", "--seed", "0",
    ]

    # 1. Uninterrupted baseline on the initial mesh.
    base_jsonl = os.path.join(work, "base.jsonl")
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *common, "--mesh.data", str(args.devices),
          "--observe.metrics-jsonl", base_jsonl],
         _env(args.devices), args.timeout, "baseline")

    # 2. SHRINK: device_loss under the elastic supervisor. --check on
    # the children runs the sharding-contract assertion and transfer
    # guard through the resize.
    shrink_ckpt = os.path.join(work, "ckpt_shrink")
    shrink_jsonl = os.path.join(work, "shrink.jsonl")
    shrink = _run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--elastic", "--max-restarts", "2", "--backoff-base-s", "0.2",
         "--", *common, "--mesh.data", str(args.devices),
         "--check", "true",
         "--checkpoint-dir", shrink_ckpt,
         "--checkpoint-every", str(args.ckpt_every),
         "--observe.metrics-jsonl", shrink_jsonl,
         "--resilience.fault-plan", f"device_loss@{kill}:{args.lose}"],
        _env(args.devices), args.timeout, "shrink (elastic supervisor)")
    shrink_restarts = shrink.stdout.count('"kind": "restart"')
    shrink_changes = shrink.stdout.count('"kind": "mesh_change"')

    # 3. GROW: train to the kill point, exit cleanly, resume wider.
    grow_facts = None
    if args.grow_to:
        grow_ckpt = os.path.join(work, "ckpt_grow")
        grow_jsonl = os.path.join(work, "grow.jsonl")
        leg1 = [a for a in common]
        leg1[leg1.index("--train-steps") + 1] = str(kill - 1)
        _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
              *leg1, "--mesh.data", str(args.devices),
              "--checkpoint-dir", grow_ckpt,
              "--checkpoint-every", str(args.ckpt_every)],
             _env(args.devices), args.timeout, "grow leg 1")
        _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
              *common, "--mesh.data", str(args.grow_to),
              "--check", "true", "--resume", "true",
              "--checkpoint-dir", grow_ckpt,
              "--checkpoint-every", str(args.ckpt_every),
              "--observe.metrics-jsonl", grow_jsonl],
             _env(args.grow_to), args.timeout, "grow leg 2 (resume)")
        grow_facts = _facts(grow_jsonl)

    # 4. Gates.
    base = _facts(base_jsonl)
    shr = _facts(shrink_jsonl)

    def _delta(facts):
        if facts is None or facts["last_loss"] is None \
                or base["last_loss"] is None:
            return None
        return abs(facts["last_loss"] - base["last_loss"])

    shrink_delta, grow_delta = _delta(shr), _delta(grow_facts)
    common_tags = {
        "model": "mnist_cnn/synthetic", "steps": args.steps,
        "batch": args.batch, "devices": args.devices,
        "lose": args.lose, "grow_to": args.grow_to,
        "kill_step": kill, "ckpt_every": args.ckpt_every,
    }
    lines = [
        {"metric": "elastic_baseline_last_loss",
         "value": base["last_loss"], "unit": "loss"},
        {"metric": "elastic_shrink_last_loss",
         "value": shr["last_loss"], "unit": "loss",
         "delta_vs_baseline": shrink_delta,
         "mesh": f"{args.devices}->{args.devices - args.lose}",
         "resumed_step": shr["resumed_step"],
         "restarts": shrink_restarts, "mesh_changes": shrink_changes},
        {"metric": "elastic_shrink_reshard_seconds",
         "value": (shr["reshard"] or {}).get("seconds"), "unit": "s",
         "from_mesh": (shr["reshard"] or {}).get("from_mesh"),
         "to_mesh": (shr["reshard"] or {}).get("to_mesh")},
    ]
    if grow_facts is not None:
        lines += [
            {"metric": "elastic_grow_last_loss",
             "value": grow_facts["last_loss"], "unit": "loss",
             "delta_vs_baseline": grow_delta,
             "mesh": f"{args.devices}->{args.grow_to}",
             "resumed_step": grow_facts["resumed_step"]},
            {"metric": "elastic_grow_reshard_seconds",
             "value": (grow_facts["reshard"] or {}).get("seconds"),
             "unit": "s"},
        ]
    checks = {
        "metric": "elastic_checks",
        "loss_tol": args.loss_tol,
        "shrink_loss_ok": bool(shrink_delta is not None
                               and shrink_delta <= args.loss_tol),
        "shrink_zero_lost_steps": bool(
            shr["steps"] == args.steps
            and shr["resumed_step"] == kill - 1),
        "shrink_resharded_ok": bool(
            shr["reshard"] is not None and shrink_changes >= 1
            and shrink_restarts >= 1),
        "grow_loss_ok": bool(args.grow_to == 0 or (
            grow_delta is not None and grow_delta <= args.loss_tol)),
        "grow_zero_lost_steps": bool(args.grow_to == 0 or (
            grow_facts is not None
            and grow_facts["steps"] == args.steps
            and grow_facts["resumed_step"] == kill - 1)),
        "grow_resharded_ok": bool(
            args.grow_to == 0 or (grow_facts is not None
                                  and grow_facts["reshard"]
                                  is not None)),
    }
    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    ok = all(v for k, v in checks.items()
             if k.endswith("_ok") or k.endswith("_steps"))
    if not args.no_check and not ok:
        print(f"elasticbench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
