"""CALIBBENCH: the predicted→measured loop's acceptance gate.

Three claims, one artifact:

1. **Calibration tightens the roofline.** Run the planbench-style
   sweep (tiny gpt, every feasible candidate ACTUALLY EXECUTED via the
   same builders), fit effective device rates from the (AOT costs,
   measured step) pairs (analysis/planner/calibrate.py), and require
   the calibrated roofline's median relative error on the sweep to be
   STRICTLY below the uncalibrated one (GENERIC_HW on this CPU host is
   wall-clock-meaningless by design — committed PLANBENCH predicted
   0.26 ms where 18.6 ms was measured) AND inside ``--band`` of
   measured.
2. **The regress ledger bites.** Synthetically degrade a committed
   artifact (FIREBENCH goodput halved, throughput slashed) and require
   ``observe.regress`` to flag it; run the ledger over the committed
   set and require it clean.
3. **The profile is reusable.** The fitted ``calibration.json``
   (atomic, platform/device-kind tagged, git-sha stamped) is written
   beside the artifact — the file ``--plan-calibration`` and the
   planner CLI's ``--calibration`` consume, and whose id stamps every
   bench artifact regenerated after it.

Emits one JSON line per phase plus ``calib_checks``; ``--out`` writes
CALIBBENCH.json; exit 1 on any failed gate (``--no-check`` reports
without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Any, Dict, List

from tensorflow_distributed_tpu.analysis.planner.plan import init_backend


def run_sweep(family: str, devices: int, batch: int, seq_len: int,
              size: str, steps: int, warmup: int
              ) -> List[Dict[str, Any]]:
    """Execute every feasible candidate of the planner sweep and
    return calibration samples: per-device AOT costs + measured
    min-of-interleaved step ms (the planbench measurement discipline —
    round-robin so host noise degrades every candidate equally)."""
    from tensorflow_distributed_tpu.analysis.planner import (
        candidates as cand_lib)
    from tensorflow_distributed_tpu.analysis.planner import (
        plan as plan_lib)
    from tensorflow_distributed_tpu.benchmarks.planbench import (
        _measure_round_robin, _prepare_candidate)

    plan = plan_lib.make_plan(
        family, devices, batch, size=size, seq_len=seq_len,
        strategies=["data", "fsdp", "zero1", "expert"])
    facts = cand_lib.model_facts(family, size)
    pending = []
    for row in plan["candidates"]:
        if not row.get("feasible"):
            continue
        cand = cand_lib.Candidate.make(
            row["mesh"], row["partition"],
            microbatches=row.get("microbatches", 0))
        try:
            ctx = _prepare_candidate(cand, facts, batch, seq_len,
                                     size, warmup, 0)
        except Exception as e:
            print(f"calibbench: candidate {row['strategy']} failed to "
                  f"build: {type(e).__name__}: {e}", file=sys.stderr)
            continue
        pending.append((row, ctx))
    _measure_round_robin([ctx for _, ctx in pending], steps)
    samples = []
    for row, ctx in pending:
        walls = sorted(ctx["walls"])
        samples.append({
            "key": (f"{family}/b{batch}/"
                    f"{cand_lib.format_mesh(row['mesh'])}/"
                    f"{row['strategy']}"),
            "flops": row.get("flops"),
            "bytes_accessed": row.get("bytes_accessed"),
            "collective_bytes": row.get("collective_bytes"),
            "measured_ms": round(1e3 * walls[0], 4),
        })
    return samples


def degraded_copy(name: str, scale: Dict[str, float]) -> str:
    """A committed JSONL artifact with named metrics' values scaled —
    the injected slowdown the regress gate must flag. Returns the
    temp path."""
    from tensorflow_distributed_tpu.observe.regress import (
        REPO_ROOT, baseline_text)

    text = baseline_text(name)
    if text is None:  # working tree fallback (fresh clone, no git)
        with open(os.path.join(REPO_ROOT, name)) as f:
            text = f.read()
    lines = []
    for line in text.splitlines():
        try:
            rec = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            lines.append(line)
            continue
        if isinstance(rec, dict) and rec.get("metric") in scale \
                and isinstance(rec.get("value"), (int, float)):
            rec["value"] = round(rec["value"] * scale[rec["metric"]], 4)
        lines.append(json.dumps(rec))
    fd, path = tempfile.mkstemp(prefix="calibbench_degraded_",
                                suffix=".json")
    with os.fdopen(fd, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--families", default="gpt,moe",
                        help="families swept (each adds cost-shape "
                        "diversity to the fit)")
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--batches", default="16,64",
                        help="global batches swept — two points per "
                        "candidate keeps the fit from interpolating "
                        "a single cost shape")
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--band", type=float, default=0.35,
                        help="calibrated median relative error must "
                        "be within this fraction of measured")
    parser.add_argument("--calibration-out", default="calibration.json",
                        help="where the fitted profile lands ('' = "
                        "don't write)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="CALIBBENCH.json")
    args = parser.parse_args(argv)

    platform = init_backend(args.devices, tag="calibbench")
    from tensorflow_distributed_tpu.analysis.planner import calibrate
    from tensorflow_distributed_tpu.analysis.planner.score import (
        detect_hardware)
    from tensorflow_distributed_tpu.observe import regress
    from tensorflow_distributed_tpu.observe.registry import (
        artifact_stamp, write_jsonl)

    families = [f.strip() for f in args.families.split(",")
                if f.strip()]
    batches = [int(b) for b in args.batches.split(",") if b.strip()]
    samples: List[Dict[str, Any]] = []
    for family in families:
        for batch in batches:
            samples.extend(run_sweep(
                family, args.devices, batch, args.seq_len, args.size,
                args.steps, args.warmup))
    lines: List[Dict[str, Any]] = [{
        "metric": "calib_sweep", "families": args.families,
        "batches": args.batches,
        "candidates": len(samples),
        "samples": samples,
    }]

    # Fit + error A/B against the uncalibrated tables.
    try:
        fit = calibrate.fit_rates(samples)
    except ValueError as e:
        # Every candidate failed to build/measure: the clean one-line
        # failure calibrate's own CLI gives, not a raw traceback.
        print(f"calibbench: {e}", file=sys.stderr)
        return 1
    profile = calibrate.make_profile(
        fit, platform,
        detect_hardware().device_kind,
        source=f"calibbench:{args.families}", devices=args.devices)
    if args.calibration_out:
        calibrate.write_calibration(profile, args.calibration_out)
    uncal = detect_hardware()
    cal = detect_hardware(calibration=profile)

    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else None

    err_uncal = median(calibrate.rel_errors(
        samples, uncal.peak_flops, uncal.hbm_bw, uncal.ici_bw,
        uncal.overhead_ms))
    err_cal = median(calibrate.rel_errors(
        samples, cal.peak_flops, cal.hbm_bw, cal.ici_bw,
        cal.overhead_ms))
    lines.append({
        "metric": "calib_fit",
        "calibration_id": profile["calibration_id"],
        "effective": profile["effective"],
        "samples": fit["samples"],
        "uncalibrated_median_rel_err": round(err_uncal, 4),
        "calibrated_median_rel_err": round(err_cal, 4),
        "calibration_path": args.calibration_out or None,
    })

    # Regress drills: the ledger must flag an injected slowdown and
    # pass the committed set untouched.
    degraded = degraded_copy("FIREBENCH.json",
                             {"fire_goodput": 0.5,
                              "fire_tokens_per_sec": 0.3})
    try:
        flagged = [f for f in regress.compare_artifact(
            "FIREBENCH.json", fresh_path=degraded)
            if f["verdict"] == "regression"]
    finally:
        os.unlink(degraded)
    committed_findings: List[Dict[str, Any]] = []
    for name in regress.manifest_names():
        committed_findings.extend(regress.compare_artifact(name))
    committed_bad = [f for f in committed_findings
                     if f["verdict"] == "regression"]
    lines.append({
        "metric": "calib_regress_drill",
        "degraded_artifact": "FIREBENCH.json",
        "degraded_regressions": len(flagged),
        "degraded_checks": [f["check"] for f in flagged],
        "committed_checks": len(committed_findings),
        "committed_regressions": len(committed_bad),
    })

    checks = {
        "metric": "calib_checks",
        "band": args.band,
        "calibrated_better": bool(err_cal < err_uncal),
        "within_band": bool(err_cal <= args.band),
        "regress_flags_degraded": bool(flagged),
        "regress_clean_on_committed": not committed_bad,
    }
    if committed_bad:
        checks["committed_regressions"] = [
            f"{f.get('artifact')}:{f.get('check')}"
            for f in committed_bad]
    lines.append(checks)
    tags = {"devices": args.devices,
            "seq_len": args.seq_len, "size": args.size,
            "steps": args.steps, "platform": platform,
            **artifact_stamp(args.calibration_out)}
    lines = [dict(ln, **tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        write_jsonl(args.out, lines)
    ok = (checks["calibrated_better"] and checks["within_band"]
          and checks["regress_flags_degraded"]
          and checks["regress_clean_on_committed"])
    if not args.no_check and not ok:
        print(f"calibbench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
