"""Fleetbench: the continuous train→serve loop under diurnal traffic,
gated by availability SLOs (ROADMAP item 5; README "Fleet serving").

The claim this pins: a fleet of engine replicas behind the
health-aware router stays within SLO while individual replicas die,
restart, go stale, fire anomalies, and hot-swap checkpoints a
concurrently-running trainer emits — goodput holds, NO request is
lost, recovery-window p99 TTFT is bounded, model staleness is bounded
with rolling swaps actually observed, and the control run is quiet
(nothing shed, no replica ever quarantined).

Phases (``--phases``; all replicas are real CLI subprocesses):

1. **identity** — the same seeded workload served by (a) ONE plain
   ``--mode serve`` reference process and (b) a 2-replica fleet whose
   second replica is SIGKILLED mid-stream. The router re-dispatches
   the dead replica's in-flight requests as journal continuations;
   greedy determinism + shared checkpoint weights make every
   assembled stream token-IDENTICAL to the reference (gated), with
   zero lost requests and the death/restart/redispatch drills proven
   fired.
2. **loop** — a 3-replica fleet under a diurnal open-loop trace with
   the full train→serve loop: a trainer leg extends the checkpoint
   mid-run (twice), the controller rolls each new step across the
   fleet one replica at a time (capacity never below N-1), and the
   CONTROL run must shed nothing and quarantine nobody. The FAULT run
   replays the same trace with the standard fleet fault plan — one
   replica SIGKILLED mid-burst, one slot-NaN'd (its anomaly
   quarantines it from admissions until it clears and REJOINS), one
   forced stale-snapshot window — and must hold goodput >=
   ``--min-goodput`` of control, lose nothing, shed nothing, keep
   recovery-window p99 TTFT under ``--max-recovery-p99-ms``, and keep
   staleness <= ``--max-staleness`` steps with >= 2 rolling swaps.

Emits one JSON line per metric plus a checks line; ``--out`` writes
FLEETBENCH.json; exit 1 on any failed gate (``--no-check`` to report
without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np


def _run(cmd, env, timeout, what):
    proc = subprocess.run(cmd, env=env, capture_output=True,
                          text=True, timeout=timeout)
    if proc.returncode != 0:
        print(f"fleetbench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def _write_workload(path: str, n: int, seed: int, new_tokens: int,
                    plen_lo: int, plen_hi: int, vocab: int,
                    rate: float, diurnal: bool) -> None:
    """Seeded mixed-length prompts with an open-loop arrival trace
    (diurnal: serve/run.py's sinusoidal day; else uniform) and a
    high/standard/batch class mix — one file both the single-replica
    reference and the fleet consume (rid = line order)."""
    rng = np.random.default_rng(seed)
    classes = ("high", "standard", "batch")
    t = 0.0
    with open(path, "w") as f:
        for i in range(n):
            plen = int(rng.integers(plen_lo, plen_hi + 1))
            prompt = rng.integers(0, vocab, size=plen)
            if diurnal:
                lam = rate * (1.0 + 0.75 * np.sin(
                    2 * np.pi * i / max(n, 1)))
                arrival, t = t, t + 1.0 / lam
            else:
                arrival = i / rate
            f.write(json.dumps({
                "prompt": [int(x) for x in prompt],
                "max_new_tokens": new_tokens,
                "arrival_s": round(float(arrival), 4),
                "slo": classes[i % 3]}) + "\n")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=16)
    parser.add_argument("--num-slots", type=int, default=2)
    parser.add_argument("--identity-requests", type=int, default=24)
    parser.add_argument("--loop-requests", type=int, default=36)
    parser.add_argument("--loop-replicas", type=int, default=3)
    parser.add_argument("--arrival-rate", type=float, default=3.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-goodput", type=float, default=0.85)
    parser.add_argument("--max-recovery-p99-ms", type=float,
                        default=20000.0)
    parser.add_argument("--max-staleness", type=int, default=4,
                        help="model-staleness bound in train steps "
                        "(= 2 checkpoint intervals here)")
    parser.add_argument("--phases", default="identity,loop",
                        help="comma list from {identity, loop}")
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="per-phase timeout (s)")
    parser.add_argument("--workdir", default="",
                        help="scratch dir (default: fresh tempdir, "
                        "removed on success)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="FLEETBENCH.json")
    args = parser.parse_args(argv)
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    bad = set(phases) - {"identity", "loop"}
    if bad:
        parser.error(f"unknown phases {sorted(bad)}")

    from tensorflow_distributed_tpu.fleet.controller import (
        ControllerConfig)
    from tensorflow_distributed_tpu.fleet.router import RouterConfig
    from tensorflow_distributed_tpu.fleet.run import (
        load_workload, run_fleet)
    from tensorflow_distributed_tpu.serve import journal as journal_mod

    work = args.workdir or tempfile.mkdtemp(prefix="fleetbench-")
    os.makedirs(work, exist_ok=True)
    ckpt = os.path.join(work, "ckpt")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"

    common = [
        "--model", "gpt_lm", "--model-size", args.size,
        "--seq-len", str(args.seq_len), "--seed", str(args.seed),
        "--compute-dtype", "float32",
    ]

    def train_args(ckpt_dir: str) -> list:
        return [*common, "--dataset", "synthetic",
                "--batch-size", "8", "--eval-every", "0",
                "--log-every", "0", "--checkpoint-dir", ckpt_dir,
                "--checkpoint-every", "2"]

    def serve_args(ckpt_dir: str) -> list:
        return [
            "--mode", "serve", *common,
            "--checkpoint-dir", ckpt_dir,
            "--serve.num-slots", str(args.num_slots),
            # ONE prefill bucket at the cache length: continuation
            # re-prefills (failover, cancel-retry) share the original
            # admissions' compiled program (firebench's rationale).
            "--serve.buckets", str(args.seq_len),
            "--observe.anomaly", "true",
        ]

    def trainer_leg(ckpt_dir: str, total_steps: int) -> list:
        return [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
                *train_args(ckpt_dir), "--train-steps",
                str(total_steps), "--resume", "true"]

    # 0. Seed checkpoint (2 steps) + warmup so the persistent compile
    # cache is hot before anything is timed.
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *train_args(ckpt), "--train-steps", "2"],
         env, args.timeout, "checkpoint prep")
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *serve_args(ckpt), "--serve.num-requests", "4",
          "--serve.max-new-tokens", "8",
          "--serve.prompt-len-min", str(args.prompt_len_min),
          "--serve.prompt-len-max", str(args.prompt_len_max)],
         env, args.timeout, "warmup serve")

    def arm_kill(name: str, deadline_s: float = 60.0):
        """An action that SIGKILLs ``name`` the moment its JOURNAL
        shows a request mid-decode with real budget left (falling
        back to an unconditional kill at the deadline) — a fixed-time
        kill can land in an idle gap, and a snapshot-armed one can
        race a request's completion (the snapshot is up to an export
        interval stale); the journal is fresh to within one decode
        step, so the killed replica reliably leaves in-flight work
        for the router to re-dispatch."""
        def act(ctl, router):
            import threading
            import time as time_mod

            def mid_decode() -> bool:
                # Stateless full replay (named epoch): the hunt runs
                # on its own thread and must not touch the handle's
                # incremental tail cache the router is advancing.
                h = ctl.members[name].handle
                jr = h.read_journal(epoch=h.epoch)
                return any(
                    not e.get("done") and not e.get("reject")
                    and 1 <= len(e.get("tokens", ()))
                    <= args.new_tokens // 2
                    for e in jr.values())

            def hunt():
                t_end = time_mod.monotonic() + deadline_s
                while time_mod.monotonic() < t_end:
                    if mid_decode():
                        break
                    time_mod.sleep(0.01)
                ctl.kill(name)
            threading.Thread(target=hunt, daemon=True).start()
        return act

    lines = []
    checks = {"metric": "fleet_checks"}
    common_tags = {
        "model": f"gpt_lm/{args.size}", "num_slots": args.num_slots,
        "new_tokens": args.new_tokens, "seed": args.seed,
    }

    # ---- phase 1: identity (failover re-dispatch == reference) -----
    if "identity" in phases:
        wl = os.path.join(work, "identity.jsonl")
        _write_workload(wl, args.identity_requests, args.seed,
                        args.new_tokens, args.prompt_len_min,
                        args.prompt_len_max, 64, 8.0, diurnal=False)
        ref_journal = os.path.join(work, "ref.journal")
        _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
              *serve_args(ckpt), "--serve.requests", wl,
              "--serve.journal", ref_journal],
             env, args.timeout, "identity reference serve")
        ref = journal_mod.replay(ref_journal)

        kill_t = (args.identity_requests / 8.0) * 0.3
        summary = run_fleet(
            fleet_dir=os.path.join(work, "identity-fleet"),
            replicas=2, base_args=serve_args(ckpt),
            workload=load_workload(wl), ckpt_dir=ckpt, env=env,
            actions=[(kill_t, arm_kill("r1"))],
            router_cfg=RouterConfig(stale_s=2.0,
                                    dispatch_timeout_s=60.0),
            controller_cfg=ControllerConfig(backoff_base_s=0.25),
            timeout_s=args.timeout,
            jsonl=os.path.join(work, "identity-fleet.jsonl"))
        toks = summary.pop("tokens")
        mismatched = [
            rid for rid in range(args.identity_requests)
            if toks.get(str(rid)) != ref.get(rid, {}).get("tokens")]
        lines.append({
            "metric": "fleet_identity",
            "requests": args.identity_requests,
            "done": summary["requests_done"],
            "lost": summary["requests_lost"],
            "shed": summary["requests_shed"],
            "token_identical":
                args.identity_requests - len(mismatched),
            "redispatches": summary["redispatches"],
            "deaths": summary["deaths"],
            "restarts": summary["restarts"],
            "dispatch_retry_hist": summary["dispatch_retry_hist"],
            "unit": "requests"})
        checks.update(
            identity_lost=summary["requests_lost"],
            identity_token_identical=(
                args.identity_requests - len(mismatched)),
            identity_of=args.identity_requests,
            identity_drills_ok=bool(
                summary["deaths"] >= 1 and summary["restarts"] >= 1
                and summary["redispatches"] >= 1))

    # ---- phase 2: the train->serve loop, control vs fault ----------
    if "loop" in phases:
        wl = os.path.join(work, "loop.jsonl")
        _write_workload(wl, args.loop_requests, args.seed + 1,
                        args.new_tokens, args.prompt_len_min,
                        args.prompt_len_max, 64, args.arrival_rate,
                        diurnal=True)
        span = args.loop_requests / args.arrival_rate

        def loop_run(tag: str, actions_extra, extra_args=None):
            import threading
            import time as time_mod

            # Per-run checkpoint dir seeded from the prep checkpoint:
            # the trainer legs in each run start from step 2 (the
            # control run must not pre-train the fault run's weights).
            run_ckpt = os.path.join(work, f"ckpt-{tag}")
            shutil.copytree(ckpt, run_ckpt)
            state = {"done": False, "fail": ""}

            def train_thread():
                # Two SEQUENTIAL trainer legs (-> steps 4 and 6):
                # each lands a new checkpoint mid-serving, each
                # triggers one rolling swap. A thread (not a router
                # action) so the sequencing wait never stalls the
                # front-end loop.
                try:
                    time_mod.sleep(span * 0.15)
                    for total in (4, 6):
                        p = subprocess.run(
                            trainer_leg(run_ckpt, total), env=env,
                            capture_output=True, text=True,
                            timeout=args.timeout)
                        if p.returncode != 0:
                            state["fail"] = (
                                f"trainer leg {total}: rc="
                                f"{p.returncode} "
                                f"{p.stderr[-500:]}")
                            return
                finally:
                    state["done"] = True

            def linger(ctl, router):
                # Outlive the trainer and its rollouts: the fleet
                # stays up until step 6 has rolled everywhere (or the
                # trainer failed — then stop and let the gates red).
                if not state["done"]:
                    return True
                return (not state["fail"]
                        and (ctl.rolled_step or 0) < 6)

            th = threading.Thread(target=train_thread, daemon=True)
            th.start()
            try:
                summary = run_fleet(
                    fleet_dir=os.path.join(work, f"{tag}-fleet"),
                    replicas=args.loop_replicas,
                    base_args=serve_args(run_ckpt),
                    workload=load_workload(wl), ckpt_dir=run_ckpt,
                    env=env, actions=list(actions_extra),
                    linger=linger, extra_args=extra_args,
                    router_cfg=RouterConfig(
                        stale_s=1.5, dispatch_timeout_s=60.0,
                        shed_wait_s=30.0, anomaly_cooldown_s=4.0),
                    controller_cfg=ControllerConfig(
                        backoff_base_s=0.25, swap_timeout_s=60.0),
                    timeout_s=args.timeout,
                    jsonl=os.path.join(work, f"{tag}.jsonl"))
            finally:
                th.join(timeout=args.timeout)
            if state["fail"]:
                print(f"fleetbench: {tag}: {state['fail']}",
                      file=sys.stderr)
            summary.pop("tokens", None)
            return summary

        # CONTROL: faults off. Must be boring: nothing shed, nobody
        # quarantined or dead, swaps still rolling.
        ctl_sum = loop_run("control", [])
        # FAULT: the standard fleet plan — r1 SIGKILL mid-burst, r2
        # slot-NaN early (anomaly -> quarantine -> rejoin), r0 a
        # forced stale-snapshot window.
        fault_sum = loop_run(
            "fault",
            [(span * 0.35, arm_kill("r1")),
             (span * 0.25, lambda ctl, router:
              ctl.members["r0"].handle.send(
                  {"cmd": "hold_export", "secs": 4.0}))],
            extra_args={"r2": ["--resilience.fault-plan",
                               "slot_nan@12:0"]})

        goodput = (fault_sum.get("tokens_per_sec", 0.0)
                   / max(ctl_sum.get("tokens_per_sec", 0.0), 1e-9))
        lines += [
            {"metric": "fleet_control_tokens_per_sec",
             "value": ctl_sum.get("tokens_per_sec"),
             "unit": "tokens/sec",
             "wall_s": ctl_sum.get("wall_s")},
            {"metric": "fleet_fault_tokens_per_sec",
             "value": fault_sum.get("tokens_per_sec"),
             "unit": "tokens/sec",
             "wall_s": fault_sum.get("wall_s")},
            {"metric": "fleet_goodput", "value": round(goodput, 4),
             "unit": "fraction of control"},
            {"metric": "fleet_control_quiet",
             "shed": ctl_sum["requests_shed"],
             "quarantines": ctl_sum["quarantines"],
             "deaths": ctl_sum["deaths"],
             "lost": ctl_sum["requests_lost"],
             "rolling_swaps": ctl_sum["rolling_swaps"],
             "staleness_max_steps": ctl_sum["staleness_max_steps"],
             "unit": ""},
            {"metric": "fleet_fault_recovery",
             "ttft_ms_p99_recovery":
                 fault_sum.get("ttft_ms_p99_recovery"),
             "recovery_requests": fault_sum.get("recovery_requests"),
             "quarantines": fault_sum["quarantines"],
             "rejoins": fault_sum["rejoins"],
             "deaths": fault_sum["deaths"],
             "restarts": fault_sum["restarts"],
             "redispatches": fault_sum["redispatches"],
             "dispatch_retry_hist": fault_sum["dispatch_retry_hist"],
             "unit": "ms"},
            {"metric": "fleet_fault_staleness",
             "value": fault_sum["staleness_max_steps"],
             "rolling_swaps": fault_sum["rolling_swaps"],
             "replica_swaps": fault_sum["replica_swaps"],
             "unit": "train steps"},
        ]
        rec_p99 = fault_sum.get("ttft_ms_p99_recovery", 0.0) or 0.0
        checks.update(
            goodput=round(goodput, 4),
            goodput_ok=bool(goodput >= args.min_goodput),
            min_goodput=args.min_goodput,
            loop_lost=(ctl_sum["requests_lost"]
                       + fault_sum["requests_lost"]),
            loop_shed=(ctl_sum["requests_shed"]
                       + fault_sum["requests_shed"]),
            control_quiet_ok=bool(
                ctl_sum["requests_shed"] == 0
                and ctl_sum["quarantines"] == 0
                and ctl_sum["deaths"] == 0),
            recovery_p99_ok=bool(
                fault_sum.get("recovery_requests", 0) >= 1
                and rec_p99 <= args.max_recovery_p99_ms),
            max_recovery_p99_ms=args.max_recovery_p99_ms,
            staleness_ok=bool(
                max(ctl_sum["staleness_max_steps"],
                    fault_sum["staleness_max_steps"])
                <= args.max_staleness),
            max_staleness=args.max_staleness,
            swaps_ok=bool(ctl_sum["rolling_swaps"] >= 2
                          and fault_sum["rolling_swaps"] >= 2),
            fault_drills_ok=bool(
                fault_sum["deaths"] >= 1
                and fault_sum["restarts"] >= 1
                and fault_sum["quarantines"] >= 2
                and fault_sum["rejoins"] >= 1))

    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)

    ok = True
    if "identity" in phases:
        ok &= (checks["identity_lost"] == 0
               and checks["identity_token_identical"]
               == checks["identity_of"]
               and checks["identity_drills_ok"])
    if "loop" in phases:
        ok &= (checks["goodput_ok"] and checks["loop_lost"] == 0
               and checks["loop_shed"] == 0
               and checks["control_quiet_ok"]
               and checks["recovery_p99_ok"]
               and checks["staleness_ok"] and checks["swaps_ok"]
               and checks["fault_drills_ok"])
    if not args.no_check and not ok:
        print(f"fleetbench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
