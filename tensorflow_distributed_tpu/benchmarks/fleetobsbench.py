"""Fleetobsbench: the fleet observatory under a real SIGKILL failover
(README "Fleet observatory"; observe/fleet_trace.py + fleet/run.py
``--fleet.*`` flags).

The claim this pins: with the observatory armed, the fleet is ONE
observable system — not N disjoint per-process views. Concretely:

1. **Stitched trace** — a 2-replica fleet serves a seeded workload
   while one replica is SIGKILLED mid-decode. The merged
   ``fleet_trace.json`` must be span-balanced AND render the moved
   request's full story on one timeline: the router's ``request`` span
   (leg 0), the dead replica's serve spans closed at ``process_death``
   (leg A), and the surviving replica's continuation under a fresh
   wire id (leg B).
2. **End-to-end SLO accounting** — the router-level SLOMonitor scores
   CLIENT-perceived latency (admission -> first token / inter-token,
   retries and failovers included). The fault run (a decode stall on
   the survivor + the SIGKILL) must fire ``fleet_slo_alert``; the
   control run must stay quiet.
3. **Latency decomposition** — per-request router-queue / inbox-lag /
   replica-queue / prefill / decode components from the stitched
   timeline must sum to the measured end-to-end latency within
   ``--residual-tol`` (control run: no dead time to hide in).
4. **Control-plane feed** — the final ``--fleet.export-path`` snapshot
   parses and its per-class end-to-end p50/p95 equal observe.report's
   fold of the same run EXACTLY (the PR-11 snapshot==report contract,
   fleet level); the fleetview CLI renders the run.
5. **Overhead** — min-of-interleaved tokens/sec with the full
   observatory on vs off must stay >= ``--min-tps-ratio``.

Phases (``--phases``): ``failover`` (control + fault runs, claims
1-4) and ``overhead`` (claim 5). Emits one JSON line per metric plus
a ``fleetobs_checks`` line; ``--out`` writes FLEETOBSBENCH.json;
exit 1 on any failed gate (``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np


def _run(cmd, env, timeout, what):
    proc = subprocess.run(cmd, env=env, capture_output=True,
                          text=True, timeout=timeout)
    if proc.returncode != 0:
        print(f"fleetobsbench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def _write_workload(path: str, n: int, seed: int, new_tokens: int,
                    plen_lo: int, plen_hi: int, vocab: int,
                    rate: float) -> None:
    """Seeded mixed-length prompts on a uniform open-loop arrival
    trace with the high/standard/batch class cycle (rid = line
    order — fleet/run.py's comparability contract)."""
    rng = np.random.default_rng(seed)
    classes = ("high", "standard", "batch")
    with open(path, "w") as f:
        for i in range(n):
            plen = int(rng.integers(plen_lo, plen_hi + 1))
            prompt = rng.integers(0, vocab, size=plen)
            f.write(json.dumps({
                "prompt": [int(x) for x in prompt],
                "max_new_tokens": new_tokens,
                "arrival_s": round(i / rate, 4),
                "slo": classes[i % 3]}) + "\n")


def _load_jsonl(path: str):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _failover_legs(trace_path: str):
    """The rids whose merged-trace story shows all three legs: the
    router ``request`` span, serve spans for >= 2 generations on >= 2
    distinct source processes, and a ``process_death`` closure on the
    dead generation."""
    from tensorflow_distributed_tpu.observe.fleet_trace import (
        gen_to_rid)
    from tensorflow_distributed_tpu.observe.trace import load_trace
    events = load_trace(trace_path)
    serve_legs = {}
    router_rids = set()
    death_gens = set()
    for ev in events:
        args = ev.get("args") or {}
        if ev.get("ph") == "b" and ev.get("name") == "request":
            try:
                sid = int(ev.get("id"))
            except (TypeError, ValueError):
                continue
            if ev.get("cat") == "serve":
                serve_legs.setdefault(gen_to_rid(sid), set()).add(
                    (int(ev.get("pid", -1)), sid))
            elif ev.get("cat") == "fleet":
                router_rids.add(sid)
        elif ev.get("ph") == "e" and args.get("process_death"):
            try:
                death_gens.add(int(ev.get("id")))
            except (TypeError, ValueError):
                pass
    moved = []
    for rid, legs in sorted(serve_legs.items()):
        pids = {p for p, _ in legs}
        gens = {g for _, g in legs}
        if (len(pids) >= 2 and len(gens) >= 2 and rid in router_rids
                and any(gen_to_rid(g) == rid for g in death_gens)):
            moved.append(rid)
    return moved, len(events)


def _snapshot_eq_report(fleet_dir: str, snap_path: str) -> bool:
    """The final control-plane snapshot's per-class end-to-end
    p50/p95 must equal observe.report's fleet_request fold exactly —
    same population, same nearest-rank percentile."""
    from tensorflow_distributed_tpu.observe.report import summarize
    rep = summarize(_load_jsonl(
        os.path.join(fleet_dir, "fleet.jsonl"))).get("fleet", {})
    with open(snap_path) as f:
        snap = json.load(f)
    keys = [k for k in snap if k.startswith("ttft_ms_p95_")
            or k.startswith("ttft_ms_p50_")]
    return bool(keys) and all(snap[k] == rep.get(k) for k in keys)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--new-tokens", type=int, default=48)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=16)
    parser.add_argument("--num-slots", type=int, default=2)
    parser.add_argument("--requests", type=int, default=18)
    parser.add_argument("--overhead-requests", type=int, default=12)
    parser.add_argument("--arrival-rate", type=float, default=3.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slo", default="ttft_p95=30s,tok_p99=80ms",
                        help="fleet SLO targets; the tok_p99 leg is "
                        "the one the fault run's stall must trip")
    parser.add_argument("--stall-s", type=float, default=6.0,
                        help="decode stall injected on the SURVIVOR "
                        "(its cost lands in client-perceived "
                        "inter-token latency)")
    parser.add_argument("--stall-step", type=int, default=30)
    parser.add_argument("--kill-frac", type=float, default=0.35,
                        help="SIGKILL arm time as a fraction of the "
                        "arrival span")
    parser.add_argument("--stale-s", type=float, default=10.0,
                        help="router staleness bound — must exceed "
                        "--stall-s so the stalled survivor is never "
                        "quarantined mid-drill")
    parser.add_argument("--export-every", type=float, default=0.5)
    parser.add_argument("--residual-tol", type=float, default=0.10,
                        help="max mean |residual|/e2e on the control "
                        "run's latency decomposition")
    parser.add_argument("--min-tps-ratio", type=float, default=0.95)
    parser.add_argument("--overhead-runs", type=int, default=2,
                        help="interleaved off/on run PAIRS")
    parser.add_argument("--phases", default="failover,overhead",
                        help="comma list from {failover, overhead}")
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("--workdir", default="",
                        help="scratch dir (default: fresh tempdir, "
                        "removed on success)")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="FLEETOBSBENCH.json")
    args = parser.parse_args(argv)
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    bad = set(phases) - {"failover", "overhead"}
    if bad:
        parser.error(f"unknown phases {sorted(bad)}")
    if args.stall_s >= args.stale_s:
        parser.error("--stall-s must stay under --stale-s (a stalled "
                     "survivor must not be quarantined)")

    from tensorflow_distributed_tpu.fleet.controller import (
        ControllerConfig)
    from tensorflow_distributed_tpu.fleet.router import RouterConfig
    from tensorflow_distributed_tpu.fleet.run import (
        FleetObsConfig, load_workload, run_fleet)
    from tensorflow_distributed_tpu.observe import fleetview

    work = args.workdir or tempfile.mkdtemp(prefix="fleetobsbench-")
    os.makedirs(work, exist_ok=True)
    ckpt = os.path.join(work, "ckpt")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"

    common = [
        "--model", "gpt_lm", "--model-size", args.size,
        "--seq-len", str(args.seq_len), "--seed", str(args.seed),
        "--compute-dtype", "float32",
    ]

    def serve_args(ckpt_dir: str) -> list:
        return [
            "--mode", "serve", *common,
            "--checkpoint-dir", ckpt_dir,
            "--serve.num-slots", str(args.num_slots),
            # ONE prefill bucket at the cache length: continuation
            # re-prefills (the failover leg) share the original
            # admissions' compiled program (fleetbench's rationale).
            "--serve.buckets", str(args.seq_len),
            "--observe.anomaly", "true",
        ]

    # 0. Seed checkpoint (2 steps) + warmup so the persistent compile
    # cache is hot before anything is timed.
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *common, "--dataset", "synthetic", "--batch-size", "8",
          "--eval-every", "0", "--log-every", "0",
          "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
          "--train-steps", "2"],
         env, args.timeout, "checkpoint prep")
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *serve_args(ckpt), "--serve.num-requests", "4",
          "--serve.max-new-tokens", "8",
          "--serve.prompt-len-min", str(args.prompt_len_min),
          "--serve.prompt-len-max", str(args.prompt_len_max)],
         env, args.timeout, "warmup serve")

    def arm_kill(name: str, deadline_s: float = 60.0):
        """SIGKILL ``name`` the moment its journal shows a request
        mid-decode with real budget left (fleetbench's arm: the
        journal is fresh to within one decode step, so the killed
        replica reliably leaves in-flight work — and durable trace
        spans — behind)."""
        def act(ctl, router):
            import threading
            import time as time_mod

            def mid_decode() -> bool:
                h = ctl.members[name].handle
                jr = h.read_journal(epoch=h.epoch)
                return any(
                    not e.get("done") and not e.get("reject")
                    and 1 <= len(e.get("tokens", ()))
                    <= args.new_tokens // 2
                    for e in jr.values())

            def hunt():
                t_end = time_mod.monotonic() + deadline_s
                while time_mod.monotonic() < t_end:
                    if mid_decode():
                        break
                    time_mod.sleep(0.01)
                ctl.kill(name)
            threading.Thread(target=hunt, daemon=True).start()
        return act

    router_cfg = RouterConfig(stale_s=args.stale_s,
                              dispatch_timeout_s=90.0)
    controller_cfg = ControllerConfig(backoff_base_s=0.25)

    def fleet_obs(fleet_dir: str) -> FleetObsConfig:
        return FleetObsConfig(
            trace=True, slo=args.slo,
            export_path=os.path.join(fleet_dir, "fleet_snapshot.json"),
            export_every=args.export_every)

    def observed_run(tag: str, wl_path: str, actions=(),
                     extra_args=None, obs_on: bool = True):
        fleet_dir = os.path.join(work, f"{tag}-fleet")
        summary = run_fleet(
            fleet_dir=fleet_dir, replicas=2,
            base_args=serve_args(ckpt),
            workload=load_workload(wl_path), ckpt_dir=ckpt, env=env,
            actions=list(actions), extra_args=extra_args,
            router_cfg=router_cfg, controller_cfg=controller_cfg,
            poll_s=0.02, timeout_s=args.timeout,
            jsonl=os.path.join(fleet_dir, "fleet.jsonl"),
            obs=fleet_obs(fleet_dir) if obs_on else None)
        summary.pop("tokens", None)
        return fleet_dir, summary

    lines = []
    checks = {"metric": "fleetobs_checks"}
    common_tags = {
        "model": f"gpt_lm/{args.size}", "num_slots": args.num_slots,
        "new_tokens": args.new_tokens, "seed": args.seed,
        "slo": args.slo,
    }

    # ---- phase 1: failover (control vs fault, observatory on) ------
    if "failover" in phases:
        wl = os.path.join(work, "failover.jsonl")
        _write_workload(wl, args.requests, args.seed, args.new_tokens,
                        args.prompt_len_min, args.prompt_len_max, 64,
                        args.arrival_rate)
        span = args.requests / args.arrival_rate

        ctl_dir, ctl_sum = observed_run("control", wl)
        # FAULT: r1 SIGKILLED mid-decode (the stitching drill) and the
        # SURVIVOR r0 decode-stalled (the client-visible latency hit
        # the fleet SLO must page on — the router clock keeps ticking
        # while no per-replica monitor would blink).
        fault_dir, fault_sum = observed_run(
            "fault", wl,
            actions=[(span * args.kill_frac, arm_kill("r1"))],
            extra_args={"r0": [
                "--resilience.fault-plan",
                f"decode_stall@{args.stall_step}:{args.stall_s}s"]})

        moved, trace_events = _failover_legs(
            os.path.join(fault_dir, "fleet_trace.json"))
        ctl_snap_eq = _snapshot_eq_report(
            ctl_dir, os.path.join(ctl_dir, "fleet_snapshot.json"))
        fault_snap_eq = _snapshot_eq_report(
            fault_dir, os.path.join(fault_dir, "fleet_snapshot.json"))
        view = fleetview.render(
            fault_dir,
            snapshot=os.path.join(fault_dir, "fleet_snapshot.json"))
        view_ok = ("fleet observatory" in view
                   and "stitched trace" in view
                   and "balanced" in view)

        decomp = [r for r in _load_jsonl(
            os.path.join(ctl_dir, "fleet.jsonl"))
            if r.get("event") == "fleet_decomp"]
        comps = ("e2e_ms", "router_queue_ms", "inbox_lag_ms",
                 "replica_queue_ms", "prefill_ms", "decode_ms",
                 "absorb_ms", "residual_ms")
        mean = {k: round(sum(float(d.get(k, 0)) for d in decomp)
                         / max(len(decomp), 1), 3) for k in comps}

        lines += [
            {"metric": "fleetobs_failover_control",
             "alerts": ctl_sum.get("fleet_slo_alerts"),
             "done": ctl_sum.get("requests_done"),
             "lost": ctl_sum.get("requests_lost"),
             "shed": ctl_sum.get("requests_shed"),
             "deaths": ctl_sum.get("deaths"),
             "balanced": ctl_sum.get("stitch_balanced"),
             "skipped": ctl_sum.get("stitch_skipped"),
             "decomp_requests": ctl_sum.get("decomp_requests"),
             "residual_frac_mean":
                 ctl_sum.get("decomp_residual_frac_mean"),
             "snapshot_eq_report": ctl_snap_eq,
             "tokens_per_sec": ctl_sum.get("tokens_per_sec"),
             "unit": ""},
            {"metric": "fleetobs_failover_fault",
             "alerts": fault_sum.get("fleet_slo_alerts"),
             "done": fault_sum.get("requests_done"),
             "lost": fault_sum.get("requests_lost"),
             "deaths": fault_sum.get("deaths"),
             "redispatches": fault_sum.get("redispatches"),
             "balanced": fault_sum.get("stitch_balanced"),
             "skipped": fault_sum.get("stitch_skipped"),
             "closed_at_death":
                 fault_sum.get("stitch_closed_at_death"),
             "stitch_sources": fault_sum.get("stitch_sources"),
             "trace_events": trace_events,
             "moved_rids": moved,
             "snapshot_eq_report": fault_snap_eq,
             "budget_remaining_min":
                 fault_sum.get("fleet_slo_budget_remaining_min"),
             "unit": ""},
            {"metric": "fleetobs_decomp", **mean,
             "requests": len(decomp), "unit": "ms (control means)"},
        ]
        residual = ctl_sum.get("decomp_residual_frac_mean")
        checks.update(
            control_quiet=bool(
                ctl_sum.get("fleet_slo_alerts") == 0
                and ctl_sum.get("deaths") == 0
                and ctl_sum.get("requests_shed") == 0),
            fault_alerted=bool(
                (fault_sum.get("fleet_slo_alerts") or 0) >= 1),
            lost=(ctl_sum.get("requests_lost", 1)
                  + fault_sum.get("requests_lost", 1)),
            traces_balanced=bool(ctl_sum.get("stitch_balanced")
                                 and fault_sum.get("stitch_balanced")),
            failover_legs_ok=bool(
                len(moved) >= 1
                and (fault_sum.get("stitch_closed_at_death") or 0) >= 1
                and (fault_sum.get("deaths") or 0) >= 1
                and (fault_sum.get("redispatches") or 0) >= 1),
            decomp_ok=bool(
                ctl_sum.get("decomp_requests") == args.requests
                and residual is not None
                and residual <= args.residual_tol),
            residual_frac_mean=residual,
            residual_tol=args.residual_tol,
            snapshot_agrees_with_report=bool(ctl_snap_eq
                                             and fault_snap_eq),
            fleetview_ok=view_ok)

    # ---- phase 2: observatory overhead (min-of-interleaved) --------
    if "overhead" in phases:
        wl = os.path.join(work, "overhead.jsonl")
        _write_workload(wl, args.overhead_requests, args.seed + 1,
                        args.new_tokens, args.prompt_len_min,
                        args.prompt_len_max, 64, args.arrival_rate)
        tps = {"off": [], "on": []}
        for i in range(args.overhead_runs):
            for mode in ("off", "on"):
                _, s = observed_run(f"ov-{mode}{i}", wl,
                                    obs_on=(mode == "on"))
                tps[mode].append(float(s.get("tokens_per_sec", 0.0)))
        ratio = (min(tps["on"]) / max(min(tps["off"]), 1e-9))
        lines.append({
            "metric": "fleetobs_overhead",
            "value": round(min(tps["on"]), 2),
            "tracing_off": round(min(tps["off"]), 2),
            "ratio": round(ratio, 4),
            "runs_on": [round(v, 2) for v in tps["on"]],
            "runs_off": [round(v, 2) for v in tps["off"]],
            "unit": "tokens/sec"})
        checks.update(
            overhead_ok=bool(ratio >= args.min_tps_ratio),
            overhead_ratio=round(ratio, 4),
            min_tps_ratio=args.min_tps_ratio)

    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)

    ok = True
    if "failover" in phases:
        ok &= (checks["control_quiet"] and checks["fault_alerted"]
               and checks["lost"] == 0
               and checks["traces_balanced"]
               and checks["failover_legs_ok"]
               and checks["decomp_ok"]
               and checks["snapshot_agrees_with_report"]
               and checks["fleetview_ok"])
    if "overhead" in phases:
        ok &= checks["overhead_ok"]
    if not args.no_check and not ok:
        print(f"fleetobsbench: checks FAILED: {checks}",
              file=sys.stderr)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
