"""Continuous-batching vs sequential one-shot serving benchmark.

The serving engine's claim (serve/ package): aggregate throughput on a
mixed-length request stream comes from keeping ONE hot compiled decode
step saturated with whatever requests are in flight, not from running
each request through its own prefill+decode program. This bench pits
the two against each other on the same workload and model:

- **continuous**: serve.SlotDecodeEngine + Scheduler — requests share
  the slot batch, prompts prefill through the bounded bucket ladder;
- **sequential**: one ``generate()`` call per request, in arrival
  order — every distinct prompt length traces a fresh XLA program
  (the repo's only serving story before serve/ existed).

Emits one JSON line per metric plus a summary line carrying the two
acceptance checks (also pinned in tests/test_serve.py):
``speedup_ok`` (continuous >= --min-speedup x sequential aggregate
tokens/s) and ``prefill_programs_ok`` (distinct compiled prefill
programs <= bucket count). Exits 1 if either fails (--no-check to
report without gating). --out writes the lines to SERVEBENCH.json
(overwritten per run, like the sibling benchmarks).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="tiny",
                        help="gpt_lm size preset (tiny | small)")
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=48)
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument("--decode-priority", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-speedup", type=float, default=2.0)
    parser.add_argument("--no-check", action="store_true",
                        help="report without gating on the checks")
    parser.add_argument("--out", default="SERVEBENCH.json")
    args = parser.parse_args(argv)
    if args.requests < 1 or args.num_slots < 1:
        parser.error("--requests and --num-slots must be >= 1")

    import jax
    import numpy as np

    from tensorflow_distributed_tpu.models.generate import generate
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import (
        single_device_mesh)
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.scheduler import (
        Request, Scheduler)
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    import optax

    rng = np.random.default_rng(args.seed)
    lens = rng.integers(args.prompt_len_min, args.prompt_len_max + 1,
                        size=args.requests)
    buckets = default_buckets(int(lens.max()))
    max_len = max(buckets) + args.new_tokens

    dev = jax.devices()[0]
    mesh = single_device_mesh(dev)
    model = gpt_lm(mesh, size=args.size, max_len=max_len,
                   dropout_rate=0.0)
    state = create_train_state(model, optax.identity(),
                               np.zeros((2, 16), np.int32), mesh, seed=0)
    params = state.params
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=int(n)).astype(np.int32) for n in lens]
    total_tokens = args.requests * args.new_tokens

    # --- continuous batching -------------------------------------------
    engine = SlotDecodeEngine(model, params, args.num_slots,
                              buckets=buckets)
    sched = Scheduler(engine, decode_priority=args.decode_priority)
    reqs = [Request(rid=i, prompt=p, max_new_tokens=args.new_tokens)
            for i, p in enumerate(prompts)]
    t0 = time.perf_counter()
    done = {c.rid: c for c in sched.run(reqs)}
    continuous_s = time.perf_counter() - t0

    # --- sequential one-shot baseline ----------------------------------
    # One generate() per request in arrival order — the pre-serve/
    # path: a fresh prefill+decode program per distinct prompt length,
    # batch 1 on the decode step.
    t0 = time.perf_counter()
    seq_out = [np.asarray(generate(model, params,
                                   jax.numpy.asarray(p[None, :]),
                                   args.new_tokens)) [0]
               for p in prompts]
    sequential_s = time.perf_counter() - t0

    matches = sum(
        bool(np.array_equal(seq_out[i], np.asarray(done[i].tokens)))
        for i in range(args.requests))
    cont_tps = total_tokens / continuous_s
    seq_tps = total_tokens / sequential_s
    speedup = cont_tps / seq_tps

    common = {
        "model": f"gpt_lm/{args.size}",
        "params": param_count(params),
        "requests": args.requests, "new_tokens": args.new_tokens,
        "num_slots": args.num_slots,
        "prompt_lens": f"{args.prompt_len_min}-{args.prompt_len_max}",
        "buckets": ",".join(str(b) for b in buckets),
        "device": dev.device_kind,
    }
    lines = [
        {"metric": "serve_continuous_tokens_per_sec",
         "value": round(cont_tps, 1), "unit": "tokens/sec"},
        {"metric": "serve_sequential_tokens_per_sec",
         "value": round(seq_tps, 1), "unit": "tokens/sec"},
        {"metric": "serve_speedup", "value": round(speedup, 2),
         "unit": "x"},
        {"metric": "serve_ttft_ms_p50", "unit": "ms",
         "value": round(1e3 * float(np.percentile(
             [done[i].ttft_s for i in range(args.requests)], 50)), 2)},
        {"metric": "serve_mean_slot_occupancy",
         "value": sched.summary["mean_slot_occupancy"], "unit": ""},
        {"metric": "serve_prefill_programs",
         "value": engine.prefill_compiles, "unit": "programs"},
    ]
    checks = {
        "metric": "serve_checks",
        "speedup_ok": bool(speedup >= args.min_speedup),
        "min_speedup": args.min_speedup,
        "prefill_programs_ok": bool(
            engine.prefill_compiles <= len(buckets)),
        "token_identical": int(matches), "of": args.requests,
    }
    lines.append(checks)
    lines = [dict(ln, **common) for ln in lines]

    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        # Overwrite like the sibling benchmarks: reruns replace, never
        # silently accumulate stale lines.
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    if not args.no_check and not (
            checks["speedup_ok"] and checks["prefill_programs_ok"]
            and matches == args.requests):
        print(f"servebench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
