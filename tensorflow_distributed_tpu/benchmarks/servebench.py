"""Serving benchmark: continuous batching + the fast-path serving
stack (speculative decoding, int8 KV cache, SLO scheduling).

Four phases, each gated, one committed ``SERVEBENCH.json``:

- **base** — the original claim (serve/ package): aggregate throughput
  on a mixed-length request stream comes from keeping ONE hot compiled
  decode step saturated, not from per-request prefill+decode programs.
  Continuous (SlotDecodeEngine + Scheduler) vs sequential (one
  ``generate()`` per request); gates ``speedup_ok`` (>= --min-speedup)
  and ``prefill_programs_ok`` (distinct prefill compiles <= buckets),
  token-identical.
- **spec** — speculative decoding (serve/speculate.py). Speculation
  pays off exactly when greedy tails are predictable, so this phase
  first TRAINS a small model to convergence on a deterministic
  bigram-cycle language (token t is always followed by its cycle
  successor — memorized in a few hundred CPU steps) and serves
  cycle-walk prompts: the k-gram self-draft proposes from request
  history and the verify program retires ``accepted + 1`` tokens per
  dispatch. Gates: spec tokens/s >= --min-spec-speedup x the
  non-speculative run on the SAME workload, 100% token identity, and
  a real accept rate (the artifact carries ``accept_rate``).
- **int8** — KV-cache quantization (``--serve.kv-dtype int8``). The
  trained model is rebuilt with ``kv_cache_quant="int8"`` (same
  params; per-(token, head) scales beside the cache) and the phase
  measures HBM per slot via the engine's own cache accounting: gate
  ``slots_at_budget`` — how many int8 slots fit the bf16 engine's
  cache budget — >= --min-int8-slots x, plus a pinned greedy-
  divergence tolerance (mean matching-prefix fraction vs the bf16
  engine >= 1 - --int8-divergence).
- **slo** — the SLO scheduler under an over-capacity bursty trace:
  the same workload (25% high / 25% batch classes) served FIFO then
  policy="slo"; gate: the high class's p95 TTFT under SLO <=
  --max-slo-ratio x FIFO's. The artifact's ``p95_ttft_under_load``
  is the SLO run's high-class p95.
- **tp** — tensor-parallel serving (``--serve.mesh-model``): the SAME
  engine built over a [data=1, model=2] mesh — params and the slot
  cache's head axis sharded over "model". Gates: token identity vs
  the model=1 engine on the same seeded workload for a dense, an
  int8, and a SPECULATIVE config (greedy determinism must survive
  GSPMD's psums), and the per-device cache-bytes ratio
  (model=1 / model=2, the engine's own ``cache_bytes_per_slot``)
  >= --min-tp-ratio. The per-step collective schedule itself is
  pinned by the ``serve_decode_tp``/``serve_verify_tp`` census
  goldens (analysis/jaxprcheck.py).

``--phases`` subsets for the t1 smoke; ``--no-check`` reports without
gating. --out writes SERVEBENCH.json (overwritten per run, like the
sibling benchmarks).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _cycle_walk(cycle, start: int, length: int):
    """``length`` tokens following the bigram cycle from phase
    ``start`` — the deterministic language the spec/int8 phases
    serve."""
    import numpy as np

    n = len(cycle)
    return np.asarray([cycle[(start + j) % n] for j in range(length)],
                      np.int32)


def _train_bigram(model, params, cycle, seq_len: int, steps: int,
                  batch: int, seed: int):
    """Adam next-token CE on cycle walks until the model memorizes the
    bigram successor function (early-stops on exact argmax accuracy).
    Returns (params, steps_run, accuracy)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    rng = np.random.default_rng(seed)
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
            tgt = tokens[:, 1:]
            nll = -jnp.take_along_axis(lp, tgt[..., None],
                                       axis=-1)[..., 0]
            return nll.mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    @jax.jit
    def accuracy(params, tokens):
        logits = model.apply({"params": params}, tokens)
        pred = jnp.argmax(logits[:, :-1], axis=-1)
        return (pred == tokens[:, 1:]).mean()

    def batch_walks():
        starts = rng.integers(0, len(cycle), size=batch)
        return jnp.asarray(np.stack(
            [_cycle_walk(cycle, int(s), seq_len) for s in starts]))

    acc, i = 0.0, 0
    for i in range(1, steps + 1):
        params, opt, _ = step(params, opt, batch_walks())
        if i % 25 == 0:
            acc = float(accuracy(params, batch_walks()))
            if acc == 1.0:
                break
    return params, i, acc


def _serve(model, params, prompts, new_tokens: int, num_slots: int,
           buckets, decode_priority: int, spec_tokens: int = 0,
           requests=None, **sched_kw):
    """One scheduler run; returns (done{rid: Completion}, summary,
    wall_s, engine). ``requests`` overrides the plain prompt workload
    (the slo phase passes classed/timed ones)."""
    import time as _time

    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.scheduler import (
        Request, Scheduler)
    from tensorflow_distributed_tpu.serve.speculate import SelfDraft

    engine = SlotDecodeEngine(model, params, num_slots, buckets=buckets,
                              spec_tokens=spec_tokens)
    engine.warmup()
    spec = (SelfDraft(num_slots, spec_tokens) if spec_tokens else None)
    sched = Scheduler(engine, decode_priority=decode_priority,
                      speculator=spec, **sched_kw)
    if requests is None:
        requests = [Request(rid=i, prompt=p, max_new_tokens=new_tokens)
                    for i, p in enumerate(prompts)]
    t0 = _time.perf_counter()
    done = {c.rid: c for c in sched.run(requests)}
    return done, sched.summary, _time.perf_counter() - t0, engine


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="tiny",
                        help="gpt_lm size preset for the base phase")
    parser.add_argument("--phases", default="base,spec,int8,slo,tp",
                        help="comma-separated subset of "
                             "base,spec,int8,slo,tp")
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=48)
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument("--decode-priority", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-speedup", type=float, default=2.0)
    parser.add_argument("--spec-tokens", type=int, default=4)
    parser.add_argument("--spec-new-tokens", type=int, default=64,
                        help="decode length for the spec/int8 phases: "
                             "long enough that decode work (what "
                             "speculation accelerates) dominates the "
                             "admission interleave both runs share")
    parser.add_argument("--min-spec-speedup", type=float, default=1.3)
    parser.add_argument("--train-steps", type=int, default=400,
                        help="bigram memorization budget (early-stops "
                             "at 100%% next-token accuracy)")
    parser.add_argument("--min-int8-slots", type=float, default=1.8)
    parser.add_argument("--int8-divergence", type=float, default=0.05,
                        help="tolerated 1 - mean matching-prefix "
                             "fraction, int8 vs bf16 greedy")
    parser.add_argument("--max-slo-ratio", type=float, default=0.5)
    parser.add_argument("--slo-requests", type=int, default=24)
    parser.add_argument("--min-tp-ratio", type=float, default=1.9,
                        help="required model=1 / model=2 per-device "
                             "cache-bytes ratio (exact head-sharding "
                             "gives 2.0; headroom for rounding)")
    parser.add_argument("--no-check", action="store_true",
                        help="report without gating on the checks")
    parser.add_argument("--out", default="SERVEBENCH.json")
    args = parser.parse_args(argv)
    if args.requests < 1 or args.num_slots < 1:
        parser.error("--requests and --num-slots must be >= 1")
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    unknown = set(phases) - {"base", "spec", "int8", "slo", "tp"}
    if unknown:
        parser.error(f"unknown phases {sorted(unknown)}")
    if "tp" in phases:
        # The TP A/B needs >= 2 devices: same virtual-CPU topology
        # discipline as analysis/jaxprcheck (the flags must land
        # before the backend is first USED; a no-op when the caller
        # already forced them, e.g. under tests/conftest.py).
        from tensorflow_distributed_tpu.analysis.jaxprcheck import (
            _force_cpu_topology)
        _force_cpu_topology()

    import jax
    import numpy as np

    from tensorflow_distributed_tpu.models.generate import generate
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.scheduler import Request
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    import jax.numpy as jnp
    import optax

    dev = jax.devices()[0]
    lines = []
    checks = {"metric": "serve_checks"}
    rng = np.random.default_rng(args.seed)

    # --- base: continuous batching vs sequential one-shot ---------------
    if "base" in phases:
        from tensorflow_distributed_tpu.parallel.mesh import (
            single_device_mesh)

        lens = rng.integers(args.prompt_len_min, args.prompt_len_max + 1,
                            size=args.requests)
        buckets = default_buckets(int(lens.max()))
        max_len = max(buckets) + args.new_tokens
        mesh = single_device_mesh(dev)
        model = gpt_lm(mesh, size=args.size, max_len=max_len,
                       dropout_rate=0.0)
        state = create_train_state(model, optax.identity(),
                                   np.zeros((2, 16), np.int32), mesh,
                                   seed=0)
        params = state.params
        prompts = [rng.integers(0, model.cfg.vocab_size,
                                size=int(n)).astype(np.int32)
                   for n in lens]
        total_tokens = args.requests * args.new_tokens

        done, summary, continuous_s, engine = _serve(
            model, params, prompts, args.new_tokens, args.num_slots,
            buckets, args.decode_priority)
        # Sequential one-shot baseline: one generate() per request in
        # arrival order — the pre-serve/ path (a fresh prefill+decode
        # program per distinct prompt length, batch 1 decode).
        t0 = time.perf_counter()
        seq_out = [np.asarray(generate(model, params,
                                       jnp.asarray(p[None, :]),
                                       args.new_tokens))[0]
                   for p in prompts]
        sequential_s = time.perf_counter() - t0

        matches = sum(
            bool(np.array_equal(seq_out[i], np.asarray(done[i].tokens)))
            for i in range(args.requests))
        cont_tps = total_tokens / continuous_s
        seq_tps = total_tokens / sequential_s
        speedup = cont_tps / seq_tps
        lines += [
            {"metric": "serve_continuous_tokens_per_sec",
             "value": round(cont_tps, 1), "unit": "tokens/sec",
             "model": f"gpt_lm/{args.size}",
             "params": param_count(params),
             "requests": args.requests, "new_tokens": args.new_tokens,
             "num_slots": args.num_slots,
             "prompt_lens":
                 f"{args.prompt_len_min}-{args.prompt_len_max}",
             "buckets": ",".join(str(b) for b in buckets)},
            {"metric": "serve_sequential_tokens_per_sec",
             "value": round(seq_tps, 1), "unit": "tokens/sec"},
            {"metric": "serve_speedup", "value": round(speedup, 2),
             "unit": "x"},
            {"metric": "serve_ttft_ms_p50", "unit": "ms",
             "value": round(1e3 * float(np.percentile(
                 [done[i].ttft_s for i in range(args.requests)],
                 50)), 2)},
            {"metric": "serve_mean_slot_occupancy",
             "value": summary["mean_slot_occupancy"], "unit": ""},
            {"metric": "serve_prefill_programs",
             "value": engine.prefill_compiles, "unit": "programs"},
        ]
        checks.update(
            speedup_ok=bool(speedup >= args.min_speedup),
            min_speedup=args.min_speedup,
            prefill_programs_ok=bool(
                engine.prefill_compiles <= len(buckets)),
            token_identical=int(matches), of=args.requests)

    # --- the trained bigram-cycle model (spec + int8 phases) ------------
    tuned = None
    if "spec" in phases or "int8" in phases:
        # Head dim 64 (d_model 64, 1 head): the realistic grain where
        # int8 + per-(token, head) f32 scales genuinely ~halve a bf16
        # cache row (2*dh / (dh + 4) = 1.88x at dh=64; at tiny's dh=16
        # the scale overhead eats the win — that is a head-dim fact,
        # not an implementation artifact).
        cycle_len, vocab = 8, 64
        cycle = [int(t) for t in rng.permutation(vocab)[:cycle_len]]
        spec_prompt_lens = rng.integers(10, 21, size=args.requests)
        spec_max_len = int(spec_prompt_lens.max()) \
            + args.spec_new_tokens + args.spec_tokens
        kw = dict(size="tiny", d_model=64, n_heads=1, d_ff=128,
                  vocab_size=vocab, max_len=spec_max_len,
                  dropout_rate=0.0, compute_dtype=jnp.bfloat16)
        model_t = gpt_lm(None, **kw)
        params_t = model_t.init(jax.random.key(args.seed),
                                jnp.zeros((1, 8), jnp.int32))["params"]
        t0 = time.perf_counter()
        # Train at the FULL serving length: learned positional
        # embeddings don't generalize past the trained positions, and
        # the serve chains run all the way to prompt + new + spec.
        params_t, tsteps, acc = _train_bigram(
            model_t, params_t, cycle, seq_len=spec_max_len,
            steps=args.train_steps, batch=16, seed=args.seed + 1)
        train_s = time.perf_counter() - t0
        prompts_t = [_cycle_walk(cycle, int(rng.integers(cycle_len)),
                                 int(n)) for n in spec_prompt_lens]
        buckets_t = default_buckets(int(spec_prompt_lens.max()),
                                    cap=spec_max_len)
        tuned = dict(model=model_t, params=params_t, kw=kw,
                     prompts=prompts_t, buckets=buckets_t)
        lines.append({"metric": "serve_bigram_model",
                      "train_steps": int(tsteps),
                      "next_token_accuracy": round(acc, 4),
                      "train_s": round(train_s, 2),
                      "cycle_len": cycle_len, "head_dim": 64})
        checks["bigram_memorized"] = bool(acc >= 0.999)

    # --- spec: self-draft speculative decoding A/B ----------------------
    if "spec" in phases:
        done_p, sum_p, wall_p, _ = _serve(
            tuned["model"], tuned["params"], tuned["prompts"],
            args.spec_new_tokens, args.num_slots, tuned["buckets"],
            args.decode_priority)
        done_s, sum_s, wall_s, eng_s = _serve(
            tuned["model"], tuned["params"], tuned["prompts"],
            args.spec_new_tokens, args.num_slots, tuned["buckets"],
            args.decode_priority, spec_tokens=args.spec_tokens)
        spec_ident = sum(
            done_p[i].tokens == done_s[i].tokens
            for i in range(args.requests))
        spec_speedup = (sum_s["tokens_per_sec"]
                        / max(sum_p["tokens_per_sec"], 1e-9))
        accept_rate = float(sum_s.get("accept_rate", 0.0))
        lines += [
            {"metric": "serve_plain_tokens_per_sec",
             "value": sum_p["tokens_per_sec"], "unit": "tokens/sec",
             "workload": "bigram-cycle walks"},
            {"metric": "serve_spec_tokens_per_sec",
             "value": sum_s["tokens_per_sec"], "unit": "tokens/sec",
             "spec_tokens": args.spec_tokens,
             "accept_rate": accept_rate,
             "verify_steps": sum_s.get("verify_steps"),
             "plain_decode_steps": sum_p.get("decode_steps"),
             "spec_decode_steps": sum_s.get("decode_steps")},
            {"metric": "serve_spec_speedup",
             "value": round(spec_speedup, 2), "unit": "x"},
        ]
        checks.update(
            spec_ok=bool(spec_speedup >= args.min_spec_speedup),
            min_spec_speedup=args.min_spec_speedup,
            spec_token_identical=int(spec_ident),
            spec_of=args.requests,
            accept_rate=accept_rate)

    # --- int8: KV-cache quantization ------------------------------------
    if "int8" in phases:
        from tensorflow_distributed_tpu.serve.engine import (
            SlotDecodeEngine)

        model_q = gpt_lm(None, kv_cache_quant="int8", **tuned["kw"])
        # bf16 baseline run (non-speculative — isolate the dtype A/B).
        done_b, _, _, eng_b = _serve(
            tuned["model"], tuned["params"], tuned["prompts"],
            args.spec_new_tokens, args.num_slots, tuned["buckets"],
            args.decode_priority)
        done_q, _, _, eng_q = _serve(
            model_q, tuned["params"], tuned["prompts"],
            args.spec_new_tokens, args.num_slots, tuned["buckets"],
            args.decode_priority)
        bps_b = eng_b.cache_bytes_per_slot()
        bps_q = eng_q.cache_bytes_per_slot()
        # The gate is the per-slot BYTES ratio (scale-inclusive): how
        # many int8 slots fit per bf16 slot's HBM. slots_at_budget
        # illustrates it at this run's slot count — integer floor, so
        # small-workload runs under-show the continuous ratio.
        slots_ratio = bps_b / bps_q
        budget = args.num_slots * bps_b
        slots_at_budget = budget // bps_q
        # Greedy-divergence tolerance: the matching-prefix fraction of
        # the int8 stream vs the bf16 stream, per request.
        fracs = []
        for i in range(args.requests):
            a, b = done_b[i].tokens, done_q[i].tokens
            m = 0
            for x, y in zip(a, b):
                if x != y:
                    break
                m += 1
            fracs.append(m / max(len(a), 1))
        divergence = 1.0 - float(np.mean(fracs))
        lines += [
            {"metric": "serve_int8_cache_bytes_per_slot",
             "bf16": int(bps_b), "int8": int(bps_q),
             "unit": "bytes"},
            {"metric": "serve_int8_slots_at_budget",
             "value": int(slots_at_budget),
             "budget_bytes": int(budget),
             "baseline_slots": args.num_slots,
             "ratio": round(slots_ratio, 3), "unit": "slots"},
            {"metric": "serve_int8_greedy_divergence",
             "value": round(divergence, 4),
             "exact_requests": int(sum(f == 1.0 for f in fracs)),
             "of": args.requests, "unit": "1 - prefix match"},
        ]
        checks.update(
            int8_slots_ok=bool(slots_ratio >= args.min_int8_slots),
            min_int8_slots=args.min_int8_slots,
            int8_divergence=round(divergence, 4),
            int8_divergence_ok=bool(
                divergence <= args.int8_divergence))

    # --- slo: priority classes under an over-capacity burst -------------
    if "slo" in phases:
        # Fresh model is fine (policy reads classes, not content) but
        # reuse the tuned one when present to skip a build.
        n = args.slo_requests
        slo_lens = rng.integers(8, 17, size=n)
        # The bucket ladder must cover PREEMPTION continuations —
        # prompt + tokens-decoded-so-far, up to prompt + new - 1
        # (exactly serve/run.py's cover=need rule for policy=slo); a
        # ladder sized to prompts alone crashes the run the moment a
        # victim has decoded past the largest bucket.
        slo_cover = int(slo_lens.max()) + args.new_tokens
        if tuned is None:
            s_max_len = slo_cover
            model_s = gpt_lm(None, size="tiny", max_len=s_max_len,
                             dropout_rate=0.0)
            params_s = model_s.init(
                jax.random.key(args.seed),
                jnp.zeros((1, 8), jnp.int32))["params"]
            vocab_s = model_s.cfg.vocab_size
        else:
            model_s, params_s = tuned["model"], tuned["params"]
            vocab_s = tuned["kw"]["vocab_size"]
            s_max_len = tuned["model"].cfg.max_len
        buckets_s = default_buckets(slo_cover, cap=s_max_len)
        slo_prompts = [rng.integers(0, vocab_s, size=int(m)).astype(
            np.int32) for m in slo_lens]
        classes = (["high", "batch", "standard", "standard"] * n)[:n]
        # Over-capacity burst: everything arrives in the first ~0.2 s
        # of a multi-second serve — FIFO makes late high-class
        # arrivals wait out the whole backlog.
        arrivals = [0.01 * (i // 4) for i in range(n)]

        def slo_requests():
            return [Request(rid=i, prompt=slo_prompts[i],
                            max_new_tokens=args.new_tokens,
                            arrival_s=arrivals[i], slo=classes[i])
                    for i in range(n)]

        def p95_high(done):
            highs = sorted(1e3 * c.ttft_s for c in done.values()
                           if c.slo == "high")
            return float(np.percentile(np.asarray(highs), 95))

        done_f, _, _, _ = _serve(
            model_s, params_s, None, args.new_tokens, 2, buckets_s,
            args.decode_priority, requests=slo_requests(),
            policy="fifo")
        done_o, sum_o, _, _ = _serve(
            model_s, params_s, None, args.new_tokens, 2, buckets_s,
            args.decode_priority, requests=slo_requests(),
            policy="slo")
        fifo_p95, slo_p95 = p95_high(done_f), p95_high(done_o)
        ratio = slo_p95 / max(fifo_p95, 1e-9)
        # Token identity across policies: preemption re-derives by
        # greedy determinism, so the streams must match FIFO's.
        slo_ident = sum(done_f[i].tokens == done_o[i].tokens
                        for i in range(n))
        lines += [
            {"metric": "serve_slo_p95_ttft_high",
             "fifo_ms": round(fifo_p95, 2),
             "slo_ms": round(slo_p95, 2),
             "ratio": round(ratio, 3),
             "preemptions": sum_o.get("preemptions"),
             "requests": n, "classes": "high:0.25,batch:0.25",
             "trace": "burst", "unit": "ms"},
        ]
        checks.update(
            slo_ok=bool(ratio <= args.max_slo_ratio),
            max_slo_ratio=args.max_slo_ratio,
            p95_ttft_under_load=round(slo_p95, 2),
            slo_token_identical=int(slo_ident), slo_of=n)

    # --- tp: tensor-parallel replica A/B vs the model=1 engine ----------
    if "tp" in phases:
        import flax.linen as nn

        from tensorflow_distributed_tpu.config import MeshConfig
        from tensorflow_distributed_tpu.parallel.mesh import make_mesh
        from tensorflow_distributed_tpu.parallel.sharding import (
            param_sharding)

        tp_width = 2
        if len(jax.devices()) < tp_width:
            raise RuntimeError(
                f"tp phase needs {tp_width} devices, have "
                f"{len(jax.devices())}")
        mesh_tp = make_mesh(MeshConfig(data=1, model=tp_width),
                            jax.devices()[:tp_width])
        tp_lens = rng.integers(args.prompt_len_min,
                               args.prompt_len_max + 1,
                               size=args.requests)
        tp_buckets = default_buckets(int(tp_lens.max()))
        # Verify headroom for the speculative config rides max_len.
        tp_max_len = max(tp_buckets) + args.new_tokens \
            + args.spec_tokens
        # tiny (4 heads) — the tuned bigram model is 1-head by design
        # (its int8 grain) and cannot shard; heads must divide tp.
        tp_kw = dict(size="tiny", max_len=tp_max_len, dropout_rate=0.0,
                     compute_dtype=jnp.bfloat16)

        def tp_place(model_tp, params):
            """The model=1 weights, placed into the TP layout derived
            from the TP model's own partition metadata — both engines
            serve IDENTICAL values, so every output mismatch is the
            sharded program's fault."""
            abstract = jax.eval_shape(
                lambda k: model_tp.init(k, jnp.zeros((1, 8), jnp.int32)),
                jax.random.key(0))
            return jax.device_put(
                params, param_sharding(mesh_tp, abstract)["params"])

        ident = {}
        for quant in ("none", "int8"):
            m1 = gpt_lm(None, kv_cache_quant=quant, **tp_kw)
            m2 = gpt_lm(mesh_tp, kv_cache_quant=quant, **tp_kw)
            if quant == "none":
                params_1 = nn.meta.unbox(m1.init(
                    jax.random.key(args.seed),
                    jnp.zeros((1, 8), jnp.int32)))["params"]
                prompts_tp = [
                    rng.integers(0, m1.cfg.vocab_size,
                                 size=int(n)).astype(np.int32)
                    for n in tp_lens]
            params_2 = tp_place(m2, params_1)
            done_1, _, _, eng_1 = _serve(
                m1, params_1, prompts_tp, args.new_tokens,
                args.num_slots, tp_buckets, args.decode_priority)
            done_2, _, _, eng_2 = _serve(
                m2, params_2, prompts_tp, args.new_tokens,
                args.num_slots, tp_buckets, args.decode_priority)
            ident[quant] = sum(done_1[i].tokens == done_2[i].tokens
                               for i in range(args.requests))
            if quant == "none":
                bps_1 = eng_1.cache_bytes_per_slot()
                bps_2 = eng_2.cache_bytes_per_slot()
                done_base = done_1
        # Speculative config on the TP mesh vs the model=1 PLAIN run:
        # greedy determinism must hold across BOTH the verify program
        # and the sharding at once.
        m2s = gpt_lm(mesh_tp, **tp_kw)
        done_2s, sum_2s, _, _ = _serve(
            m2s, tp_place(m2s, params_1), prompts_tp, args.new_tokens,
            args.num_slots, tp_buckets, args.decode_priority,
            spec_tokens=args.spec_tokens)
        ident["spec"] = sum(done_base[i].tokens == done_2s[i].tokens
                            for i in range(args.requests))
        tp_ratio = bps_1 / max(bps_2, 1)
        lines += [
            {"metric": "serve_tp_cache_bytes_per_slot",
             "model1": int(bps_1), "model2": int(bps_2),
             "ratio": round(tp_ratio, 3), "tp": tp_width,
             "unit": "bytes/device"},
            {"metric": "serve_tp_identity",
             "dense": int(ident["none"]), "int8": int(ident["int8"]),
             "spec": int(ident["spec"]), "of": args.requests,
             "tp": tp_width,
             "spec_verify_steps": sum_2s.get("verify_steps")},
        ]
        checks.update(
            tp_cache_ratio=round(tp_ratio, 3),
            tp_cache_ratio_ok=bool(tp_ratio >= args.min_tp_ratio),
            min_tp_ratio=args.min_tp_ratio,
            tp_token_identical=int(sum(ident.values())),
            tp_of=3 * args.requests)

    lines.append(checks)
    common = {"device": dev.device_kind, "phases": ",".join(phases),
              "seed": args.seed}
    lines = [dict(ln, **common) for ln in lines]

    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        # Overwrite like the sibling benchmarks: reruns replace, never
        # silently accumulate stale lines.
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    gate_keys = [k for k in ("speedup_ok", "prefill_programs_ok",
                             "bigram_memorized", "spec_ok",
                             "int8_slots_ok", "int8_divergence_ok",
                             "slo_ok", "tp_cache_ratio_ok")
                 if k in checks]
    identity_ok = all((
        checks.get("token_identical", 0) == checks.get("of", 0),
        checks.get("spec_token_identical", 0) == checks.get("spec_of",
                                                            0),
        checks.get("slo_token_identical", 0) == checks.get("slo_of",
                                                           0),
        checks.get("tp_token_identical", 0) == checks.get("tp_of", 0)))
    if not args.no_check and not (
            all(checks[k] for k in gate_keys) and identity_ok):
        print(f"servebench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
