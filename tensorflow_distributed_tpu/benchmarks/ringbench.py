"""Ring-attention local-compute A/B: Pallas partial kernel vs einsum.

The zigzag causal ring's per-step work is half-block partial attends
(parallel.ring_attention._partial_attend). This measures that building
block on the chip at the shapes an L=8192, S=8 ring actually runs
(local block 1024 -> half-blocks nh=512), einsum oracle vs the Pallas
partial-softmax kernel (ops.flash_attention.flash_attention_partial),
forward and forward+backward-through-merge. A single chip cannot run
an S>1 ring (no second device for the ppermutes), so this is the
honest single-chip form of the ring speedup: the collective schedule
is pinned by the CPU-mesh parity tests; the arithmetic is measured
here. Prints one JSON line per metric.
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--head-dim", type=int, default=64)
    parser.add_argument("--half-block", type=int, default=512,
                        help="nh = L / (2S); 512 = L 8192 over S 8")
    parser.add_argument("--ring-size", type=int, default=8,
                        help="S: ring steps simulated per timed call")
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--out", default="")
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_distributed_tpu.ops.flash_attention import (
        flash_attention_partial)
    from tensorflow_distributed_tpu.parallel.ring_attention import (
        _block_attend, _merge, causal_bias)
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    B, H, D, nh = args.batch, args.heads, args.head_dim, args.half_block
    S = args.ring_size
    rng = np.random.default_rng(0)
    mk = lambda *shape: jnp.asarray(  # noqa: E731
        rng.normal(size=shape), jnp.bfloat16) * 0.5
    q, q2 = mk(B, nh, H, D), mk(B, nh, H, D)
    # DISTINCT K,V per simulated ring step — the rotated blocks a real
    # ring receives; identical operands would let XLA CSE the repeated
    # attends down to one.
    ks, vs = mk(S, B, nh, H, D), mk(S, B, nh, H, D)
    ks2, vs2 = mk(S, B, nh, H, D), mk(S, B, nh, H, D)
    tri = causal_bias(nh, nh)

    def einsum_partial(q, k, v, causal):
        return _block_attend(q, k, v, tri if causal else None)

    def flash_partial(q, k, v, causal):
        return flash_attention_partial(q, k, v, causal=causal)

    def ring_step(attend):
        # The FULL per-device zigzag arithmetic for an S-way ring:
        # step 0 does the two triangular diagonals + one full attend,
        # every later step two full attends — 2S + 1 half-attends and
        # the accumulator merges (parallel.ring_attention
        # _zigzag_causal_shard), minus only the ppermutes a single
        # chip cannot run. The S-1 later steps ride a lax.scan with
        # DISTINCT K,V per step (the ring's rotated blocks): no CSE,
        # one compiled kernel instance.
        def f(q, q2, ks, vs, ks2, vs2):
            acc1 = attend(q, ks[0], vs[0], True)
            acc2 = _merge(*attend(q2, ks2[0], vs2[0], True),
                          *attend(q2, ks[0], vs[0], False))

            def tick(carry, xs):
                a1, a2 = carry
                k1, v1, k2, v2 = xs
                a2 = _merge(*a2, *attend(q2, k1, v1, False))
                a1 = _merge(*a1, *attend(q, k2, v2, False))
                return (a1, a2), None

            (acc1, acc2), _ = jax.lax.scan(
                tick, (acc1, acc2),
                (ks[1:], vs[1:], ks2[1:], vs2[1:]))
            outs = []
            for m, l, o in (acc1, acc2):
                outs.append(o / l.transpose(0, 2, 1)[..., None])
            out = jnp.concatenate(outs, axis=1)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return f

    import statistics

    def timed(fn, grad: bool):
        # Differentiate wrt ALL inputs — grads wrt only q/q2 would let
        # XLA dead-code-eliminate the whole dk/dv backward (verified:
        # 5 vs 9 dots in optimized HLO) and under-measure fwd_bwd.
        f = jax.jit(jax.grad(fn, argnums=tuple(range(6))) if grad
                    else fn)
        args6 = (q, q2, ks, vs, ks2, vs2)
        r = f(*args6)  # compile
        jax.block_until_ready(r)
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            r = f(*args6)
            # Honest axon barrier: host readback of a dependent scalar.
            leaf = r[0] if isinstance(r, tuple) else r
            float(jax.device_get(jnp.sum(leaf.astype(jnp.float32))))
            times.append(time.perf_counter() - t0)
        return statistics.median(times) * 1e3

    meta = {"batch": B, "heads": H, "head_dim": D, "half_block": nh,
            "ring_size": S, "seq_len": 2 * S * nh,
            "device": jax.devices()[0].device_kind}
    lines = []
    for grad, tag in ((False, "fwd"), (True, "fwd_bwd")):
        t_e = timed(ring_step(einsum_partial), grad)
        t_f = timed(ring_step(flash_partial), grad)
        lines.append({
            "metric": f"ring_block_flash_vs_einsum_{tag}_speedup",
            "value": round(t_e / t_f, 3), "unit": "x",
            "einsum_ms": round(t_e, 3), "flash_ms": round(t_f, 3),
            **meta})

    out = "\n".join(json.dumps(ln) for ln in lines)
    print(out)
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)  # git-sha/calibration stamped


if __name__ == "__main__":
    main()
