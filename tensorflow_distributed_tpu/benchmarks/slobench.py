"""Serve-observatory benchmark: tracing + SLO burn-rate monitoring
over a faulted, over-capacity serve run, with validity and overhead
gates.

What this pins (ISSUE 11 / ROADMAP item 5's measurement layer):

1. **Control** (clean run, gentle open-loop arrivals, full observatory
   armed): the Perfetto trace is VALID — every request's async spans
   balance — the exported metrics snapshots parse, the FINAL snapshot's
   per-class TTFT p95 agrees EXACTLY with the post-run report's number
   (same nearest-rank formula over the same completions), and the
   burn-rate monitor stays silent: zero ``slo_alert`` records.
2. **Fire** (the PR-6 standard fault plan — decode stall, on-device
   slot NaN, live weight reload, SIGKILL-and-supervise — plus an
   over-capacity BURST arrival pattern, same observatory): the burn
   -rate alert FIRES, the one trace file spans the restart (the
   resumed leg closes the dead leg's in-flight spans and continues
   the timeline) and still balances, the quarantine/swap recovery
   instants are present in it, and the journal shows zero lost
   requests.
3. **Overhead** (in-process A/B, same seeded workload): aggregate
   tokens/s with the full observatory armed is >= ``--min-tps-ratio``
   (default 0.95) of tokens/s with it off — instrumentation must cost
   <= 5%.

Emits one JSON line per metric plus a checks line; ``--out`` writes
SLOBENCH.json (overwritten per run); exit 1 on any failed gate
(``--no-check`` to report without gating).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile


def _run(cmd, env, timeout, what):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        print(f"slobench: {what} failed rc={proc.returncode}\n"
              f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        raise SystemExit(1)
    return proc


def _trace_checks(trace_path: str):
    """(balanced, instant-name set, event count) for one trace file."""
    from tensorflow_distributed_tpu.observe.trace import (
        load_trace, unbalanced_async)
    events = load_trace(trace_path)
    stray = unbalanced_async(events)
    instants = {e.get("name") for e in events if e.get("ph") == "i"}
    return len(stray) == 0, instants, len(events)


def _overhead_ab(args):
    """In-process A/B: the same seeded fresh-init workload through the
    scheduler with the observatory off vs fully armed (tracer, SLO
    monitor, JSONL registry, snapshot export), INTERLEAVED over
    ``--overhead-repeats`` rounds (host scheduling noise on this box
    is ~10% run-to-run — alternating the configs and taking each
    side's best compares steady states, the repo's min-of-interleaved
    bench convention).

    The A/B model is deliberately BIGGER than the drill legs' tiny
    config (``--overhead-d-model``, default 256, 4 layers): the
    instrumentation cost is a fixed ~tens of µs of host bookkeeping
    per decode step, so measuring it against a sub-ms toy step would
    gate Python dict overhead against XLA dispatch noise rather than
    against the step work any real deployment has (where the same µs
    are well under 1%)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.observe.registry import (
        JsonlSink, MetricsRegistry)
    from tensorflow_distributed_tpu.observe.serve_trace import (
        ServeTracer)
    from tensorflow_distributed_tpu.observe.slo import (
        SLOMonitor, parse_slo, parse_windows)
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.scheduler import (
        Request, Scheduler)

    work = tempfile.mkdtemp(prefix="slobench-ab-")
    max_len = args.prompt_len_max + args.overhead_new_tokens + 4
    model = gpt_lm(None, size="tiny", d_model=args.overhead_d_model,
                   n_layers=4, n_heads=8,
                   d_ff=4 * args.overhead_d_model, max_len=max_len,
                   dropout_rate=0.0)
    params = model.init(jax.random.key(args.seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(args.seed)
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=int(n)).astype(np.int32)
               for n in rng.integers(args.prompt_len_min,
                                     args.prompt_len_max + 1,
                                     size=args.overhead_requests)]
    buckets = default_buckets(args.prompt_len_max, cap=max_len)

    def one(observed: bool, rep: int) -> float:
        eng_kw, sched_kw, closers = {}, {}, []
        if observed:
            tag = f"ab-on{rep}"
            tracer = ServeTracer(os.path.join(work, f"{tag}.trace"))
            registry = MetricsRegistry(
                [JsonlSink(os.path.join(work, f"{tag}.jsonl"))])
            fast, slow = parse_windows(args.slo_windows)
            eng_kw["tracer"] = tracer
            sched_kw.update(
                tracer=tracer, registry=registry,
                slo_monitor=SLOMonitor(
                    parse_slo(args.slo), fast_window=fast,
                    slow_window=slow, emit=registry.emit,
                    tracer=tracer),
                export_every=0.25,
                export_path=os.path.join(work, f"{tag}.snap"))
            closers = [tracer.close, registry.close]
        eng = SlotDecodeEngine(model, params, 4, buckets=buckets,
                               **eng_kw)
        eng.warmup()
        sched = Scheduler(eng, decode_priority=4, **sched_kw)
        sched.run([Request(rid=i, prompt=p,
                           max_new_tokens=args.overhead_new_tokens)
                   for i, p in enumerate(prompts)])
        for close in closers:
            close()
        return float(sched.summary["tokens_per_sec"])

    one(False, -1)                     # warm the A/B shapes untimed
    tps_off = tps_on = 0.0
    for r in range(args.overhead_repeats):
        tps_off = max(tps_off, one(False, r))
        tps_on = max(tps_on, one(True, r))
    shutil.rmtree(work, ignore_errors=True)
    return tps_off, tps_on


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="tiny")
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--num-slots", type=int, default=2)
    parser.add_argument("--prompt-len-min", type=int, default=4)
    parser.add_argument("--prompt-len-max", type=int, default=12)
    parser.add_argument("--new-tokens", type=int, default=96)
    parser.add_argument("--seq-len", type=int, default=112)
    parser.add_argument("--control-rate", type=float, default=3.0,
                        help="control arrivals (req/s) — gentle, the "
                        "engine keeps up, no alert expected")
    parser.add_argument("--burst-rate", type=float, default=64.0,
                        help="fire arrivals (req/s, bursty) — far "
                        "over capacity, the alert must fire")
    parser.add_argument("--slo", default="ttft_p95=400ms",
                        help="targets armed on both legs")
    parser.add_argument("--slo-windows", default="30,120")
    parser.add_argument("--stall-s", type=float, default=0.3)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-tps-ratio", type=float, default=0.95)
    parser.add_argument("--overhead-requests", type=int, default=16)
    parser.add_argument("--overhead-new-tokens", type=int, default=64)
    parser.add_argument("--overhead-repeats", type=int, default=4)
    parser.add_argument("--overhead-d-model", type=int, default=256)
    parser.add_argument("--skip-overhead", action="store_true")
    parser.add_argument("--ab-only", action="store_true",
                        help=argparse.SUPPRESS)  # internal: run just
    # the overhead A/B in a FRESH interpreter (the drill legs leave
    # the bench process with a warmed-but-fragmented heap that skews
    # a tight in-process A/B) and print one JSON line
    parser.add_argument("--timeout", type=float, default=420.0)
    parser.add_argument("--workdir", default="")
    parser.add_argument("--no-check", action="store_true")
    parser.add_argument("--out", default="SLOBENCH.json")
    args = parser.parse_args(argv)

    work = args.workdir or tempfile.mkdtemp(prefix="slobench-")
    os.makedirs(work, exist_ok=True)
    ckpt = os.path.join(work, "ckpt")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"

    if args.ab_only:
        tps_off, tps_on = _overhead_ab(args)
        print(json.dumps({"ab_tps_off": tps_off, "ab_tps_on": tps_on}))
        return 0

    # The PR-6 standard fault plan, keyed well inside the decode-step
    # budget (~requests * new_tokens / slots) so every drill fires.
    est_steps = max(8, args.requests * args.new_tokens
                    // args.num_slots)
    plan = (f"decode_stall@{max(2, est_steps // 8)}:{args.stall_s}s,"
            f"slot_nan@{max(3, est_steps // 5)}:0,"
            f"reload@{max(4, est_steps // 3)},"
            f"sigkill@{max(5, est_steps // 2)}")

    common = [
        "--model", "gpt_lm", "--model-size", args.size,
        "--seq-len", str(args.seq_len), "--seed", str(args.seed),
        "--compute-dtype", "float32",
    ]
    observe = lambda leg: [  # noqa: E731 - tiny per-leg path helper
        "--observe.metrics-jsonl", os.path.join(work, f"{leg}.jsonl"),
        "--observe.trace", os.path.join(work, f"{leg}.trace.json"),
        "--observe.slo", args.slo,
        "--observe.slo-windows", args.slo_windows,
        "--observe.export-every", "0.25",
        "--observe.export-path", os.path.join(work, f"{leg}.snap.json"),
    ]
    serve_common = common + [
        "--mode", "serve", "--checkpoint-dir", ckpt,
        "--serve.num-slots", str(args.num_slots),
        "--serve.num-requests", str(args.requests),
        "--serve.prompt-len-min", str(args.prompt_len_min),
        "--serve.prompt-len-max", str(args.prompt_len_max),
        "--serve.max-new-tokens", str(args.new_tokens),
        "--serve.buckets", str(args.seq_len),
    ]

    # 1. Checkpoint prep (serving weights + the reload swap source).
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *common, "--dataset", "synthetic", "--train-steps", "2",
          "--batch-size", "8", "--eval-every", "0", "--log-every", "0",
          "--checkpoint-dir", ckpt, "--checkpoint-every", "2"],
         env, args.timeout, "checkpoint prep")

    # 2. CONTROL: clean, gentle arrivals, observatory armed.
    _run([sys.executable, "-m", "tensorflow_distributed_tpu.cli",
          *serve_common, *observe("control"),
          "--serve.arrival-rate", str(args.control_rate)],
         env, args.timeout, "control serve")

    # 3. FIRE: over-capacity burst + the standard fault plan, under
    # the supervisor (SIGKILL -> journal resume; the trace file spans
    # the restart).
    fire_journal = os.path.join(work, "fire.journal")
    fire = _run([sys.executable, "-m",
                 "tensorflow_distributed_tpu.resilience.supervisor",
                 "--max-restarts", "2", "--backoff-base-s", "0.2",
                 "--", *serve_common, *observe("fire"),
                 "--serve.trace", "bursty",
                 "--serve.arrival-rate", str(args.burst_rate),
                 "--serve.journal", fire_journal,
                 "--resilience.sync-timeout-s", "120",
                 "--resilience.fault-plan", plan],
                env, args.timeout, "fire serve")
    restarts = fire.stdout.count('"kind": "restart"')

    # 4. Gates.
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)
    from tensorflow_distributed_tpu.serve import journal as journal_mod

    def leg_records(leg):
        return load_records(os.path.join(work, f"{leg}.jsonl"))

    control_recs = leg_records("control")
    fire_recs = leg_records("fire")
    control_sum = summarize(control_recs)
    fire_sum = summarize(fire_recs)
    control_alerts = sum(1 for r in control_recs
                         if r.get("event") == "slo_alert")
    fire_alerts = sum(1 for r in fire_recs
                      if r.get("event") == "slo_alert")

    control_ok, control_instants, control_events = _trace_checks(
        os.path.join(work, "control.trace.json"))
    fire_ok, fire_instants, fire_events = _trace_checks(
        os.path.join(work, "fire.trace.json"))
    recovery_marks = fire_instants & {"slot_quarantine", "weight_swap",
                                      "journal_resume"}

    # Snapshot validity + agreement (control leg: one clean process,
    # one population). Every snapshot record parses with the core
    # fields; the final one's standard-class p95 must EQUAL the
    # report's serve-request-derived p95 (all-standard workload, same
    # nearest-rank formula).
    snaps = [r for r in control_recs
             if r.get("event") == "metrics_snapshot"]
    snap_fields_ok = bool(snaps) and all(
        all(k in s for k in ("t_s", "decode_steps", "requests_done",
                             "queue_depth", "slot_occupancy",
                             "tokens_per_sec", "slo"))
        for s in snaps)
    snap_file = json.load(open(os.path.join(work, "control.snap.json")))
    final_snap_p95 = snaps[-1].get("ttft_ms_p95_standard") if snaps \
        else None
    report_p95 = control_sum.get("serve_ttft_ms_p95")
    snap_agree = (final_snap_p95 is not None
                  and final_snap_p95 == report_p95
                  and snap_file.get("requests_done") == args.requests)

    fire_play = journal_mod.replay(fire_journal)
    lost = [rid for rid in range(args.requests)
            if not fire_play.get(rid, {}).get("done")]
    rec_counts = fire_sum.get("recovery_counts", {})

    # 5. Overhead A/B in a FRESH interpreter (isolated from this
    # process's post-drill heap state, like every other phase).
    tps_off = tps_on = ratio = None
    if not args.skip_overhead:
        ab = _run([sys.executable, "-m",
                   "tensorflow_distributed_tpu.benchmarks.slobench",
                   "--ab-only", "--out", "",
                   "--seed", str(args.seed),
                   "--overhead-requests", str(args.overhead_requests),
                   "--overhead-new-tokens",
                   str(args.overhead_new_tokens),
                   "--overhead-repeats", str(args.overhead_repeats),
                   "--overhead-d-model", str(args.overhead_d_model),
                   "--prompt-len-min", str(args.prompt_len_min),
                   "--prompt-len-max", str(args.prompt_len_max),
                   "--slo", args.slo, "--slo-windows",
                   args.slo_windows],
                  env, args.timeout, "overhead A/B")
        line = [ln for ln in ab.stdout.splitlines()
                if ln.startswith('{"ab_tps_off"')][-1]
        parsed = json.loads(line)
        tps_off, tps_on = parsed["ab_tps_off"], parsed["ab_tps_on"]
        ratio = tps_on / max(tps_off, 1e-9)

    common_tags = {
        "model": f"gpt_lm/{args.size}", "requests": args.requests,
        "new_tokens": args.new_tokens, "num_slots": args.num_slots,
        "slo": args.slo, "slo_windows": args.slo_windows,
        "fault_plan": plan, "seed": args.seed,
        "burst_rate": args.burst_rate,
        "control_rate": args.control_rate,
    }
    lines = [
        {"metric": "slo_control_alerts", "value": control_alerts,
         "unit": "slo_alert records",
         "p95_ttft_ms": control_sum.get("serve_ttft_ms_p95")},
        {"metric": "slo_fire_alerts", "value": fire_alerts,
         "unit": "slo_alert records",
         "p95_ttft_ms": fire_sum.get("serve_ttft_ms_p95"),
         "budget_remaining_min": fire_sum.get(
             "serve_slo_budget_remaining_min")},
        {"metric": "slo_trace_events",
         "value": {"control": control_events, "fire": fire_events},
         "unit": "trace events",
         "recovery_instants": sorted(recovery_marks)},
        {"metric": "slo_fire_recovery_counts", "value": rec_counts,
         "unit": "", "restarts": restarts,
         "p99_ttft_ms_recovery": fire_sum.get(
             "serve_ttft_ms_p99_recovery")},
        {"metric": "slo_snapshots",
         "value": len(snaps), "unit": "metrics_snapshot records",
         "final_p95_standard": final_snap_p95,
         "report_p95": report_p95},
    ]
    if ratio is not None:
        lines.append(
            {"metric": "slo_instrumentation_tokens_per_sec",
             "value": round(tps_on, 1), "unit": "tokens/sec",
             "tracing_off": round(tps_off, 1),
             "ratio": round(ratio, 4)})
    checks = {
        "metric": "slo_checks",
        "control_quiet": control_alerts == 0,
        "fire_alerted": fire_alerts >= 1,
        "traces_balanced": bool(control_ok and fire_ok),
        "recovery_instants_ok": bool(
            {"slot_quarantine", "weight_swap"} <= recovery_marks),
        "trace_spans_restart": "journal_resume" in recovery_marks,
        "snapshots_ok": bool(snap_fields_ok),
        "snapshot_agrees_with_report": bool(snap_agree),
        "lost_requests": len(lost),
        "drills_fired_ok": bool(
            rec_counts.get("slot_quarantine", 0) >= 1
            and rec_counts.get("weight_swap", 0) >= 1
            and restarts >= 1),
    }
    if ratio is not None:
        checks["overhead_ok"] = bool(ratio >= args.min_tps_ratio)
        checks["min_tps_ratio"] = args.min_tps_ratio
    lines.append(checks)
    lines = [dict(ln, **common_tags) for ln in lines]
    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)
    ok = (checks["control_quiet"] and checks["fire_alerted"]
          and checks["traces_balanced"]
          and checks["recovery_instants_ok"]
          and checks["trace_spans_restart"]
          and checks["snapshots_ok"]
          and checks["snapshot_agrees_with_report"]
          and not lost and checks["drills_fired_ok"]
          and checks.get("overhead_ok", True))
    if not args.no_check and not ok:
        print(f"slobench: checks FAILED: {checks}", file=sys.stderr)
        return 1
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
