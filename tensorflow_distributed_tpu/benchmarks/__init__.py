"""Benchmark entrypoints.

Each module is runnable (``python -m tensorflow_distributed_tpu.benchmarks.<name>``)
and prints one JSON line per metric, in the same shape as the repo-root
``bench.py`` headline benchmark.
"""
