"""MoE training benchmark: tokens/s, active-param MFU, dispatch cost.

EP is claimed first-class (PARITY.md parallelism checklist) — this
records what the GShard dense-dispatch formulation (models/moe.py)
actually delivers on chip, and documents its scale envelope. Reports

- tokens/sec through the full jitted moe_lm train step,
- MFU charged on ACTIVE FLOPs only (dense params + K/E of the expert
  params per token — the standard MoE accounting; the dropped-token
  fraction means real work can be slightly lower),
- the dispatch/combine einsum overhead as extra TFLOPs (2*S*E*C*M per
  group per tensor — work the dense formulation does that a ragged one
  would not),
- compiled memory: temp + argument bytes from XLA's memory analysis,
  alongside the closed-form dispatch-tensor bytes,
- the envelope: dispatch+combine bytes grow O(S^2 * E * c / E) = O(S^2)
  at fixed capacity factor (C = ceil(c*K*S/E)), printed for a seq
  sweep so the cliff is visible without running it.

The envelope conclusion lives in models/moe.py's docstring; this
benchmark is its measured backing (MOEBENCH.json).

Timing uses a host readback as the barrier — same tunnel caveat as
lm_perf.py.
"""

from __future__ import annotations

import argparse
import json
import math

from tensorflow_distributed_tpu.benchmarks.lm_perf import _timed_steps
from tensorflow_distributed_tpu.observe.mfu import (
    PEAK_BF16_FLOPS, flops_per_token)


def moe_active_flops_per_token(params, cfg) -> float:
    """fwd+bwd FLOPs per token with expert matmuls charged at K/E
    (each token visits top_k of num_experts experts). Thin alias over
    observe.mfu.flops_per_token, which owns the MoE active-FLOPs
    accounting (cfg carries moe_experts/moe_top_k)."""
    return flops_per_token(params, cfg)


def dispatch_bytes(seq: int, experts: int, top_k: int,
                   capacity_factor: float) -> int:
    """Closed-form f32 bytes for ONE group's dispatch + combine
    [S, E, C] tensors (models/moe.py builds both)."""
    cap = max(1, math.ceil(capacity_factor * top_k * seq / experts))
    return 2 * 4 * seq * experts * cap


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--experts", type=int, default=8)
    parser.add_argument("--top-k", type=int, default=2)
    parser.add_argument("--d-model", type=int, default=768)
    parser.add_argument("--n-layers", type=int, default=12)
    parser.add_argument("--moe-group-len", type=int, default=0,
                        dest="group_len",
                        help="MoE routing-group length (0 = whole "
                        "sequence); the dispatch-envelope knob — same "
                        "name as the train CLI's flag")
    parser.add_argument("--moe-dispatch", default="dense",
                        choices=["dense", "scatter"], dest="dispatch",
                        help="token-movement formulation (models/"
                        "moe.py); scatter skips the one-hot einsums")
    parser.add_argument("--remat", default="none",
                        choices=["none", "full", "dots"])
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--out", default="")
    args = parser.parse_args(argv)

    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.transformer import moe_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        make_moe_loss, mlm_batch_shardings)
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    n_dev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=n_dev))
    kind = jax.devices()[0].device_kind
    peak = PEAK_BF16_FLOPS.get(kind)

    model = moe_lm(mesh, size="small", moe_experts=args.experts,
                   moe_top_k=args.top_k, d_model=args.d_model,
                   n_layers=args.n_layers, max_len=args.seq_len,
                   moe_group_len=args.group_len,
                   moe_dispatch=args.dispatch, dropout_rate=0.0,
                   **({"remat": True, "remat_policy": args.remat}
                      if args.remat != "none" else {}))
    state = create_train_state(
        model, optax.adam(3e-4), np.zeros((2, args.seq_len), np.int32),
        mesh)
    step = make_train_step(mesh, loss=make_moe_loss(0.01, 0.0),
                           batch_shardings=mlm_batch_shardings(mesh))
    ds = synthetic_clm(n=args.batch, seq_len=args.seq_len,
                       vocab_size=model.cfg.vocab_size)
    batch = shard_batch(mesh, ds.batch(np.arange(args.batch)), seq_axis=1)

    mem = {}
    try:
        ana = step.lower(state, batch).compile().memory_analysis()
        mem = {"temp_bytes": int(ana.temp_size_in_bytes),
               "argument_bytes": int(ana.argument_size_in_bytes)}
    except Exception as e:  # tunnel backends may not expose it
        mem = {"memory_analysis_unavailable": str(e)}

    dt, state, first, last = _timed_steps(step, state, batch, args.steps)
    assert np.isfinite(last), f"non-finite loss {last}"
    assert last < first, f"loss did not decrease: {first} -> {last}"

    tokens = args.steps * args.batch * args.seq_len
    tok_s = tokens / dt
    fpt = moe_active_flops_per_token(state.params, model.cfg)
    tflops = tok_s * fpt / 1e12
    mfu = tflops * 1e12 / (peak * n_dev) if peak else None

    # Dispatch/combine einsum work per token, fwd (+2x for bwd), PER
    # LAYER x n_layers (every block's MLP is a MoE): each einsum costs
    # 2*E*C*M MACs per token-position. Capacity follows the ROUTING
    # GROUP length (= --group-len when set) — which is why group_len
    # is also a FLOPs knob, not just a memory knob: C (hence dispatch
    # work) scales with the group.
    # min(): MoeMlp routes the whole sequence as ONE group when
    # group_len >= seq, so capacity follows the smaller of the two.
    grp = min(args.group_len or args.seq_len, args.seq_len)
    cf = model.cfg.moe_capacity_factor
    cap = max(1, math.ceil(cf * args.top_k * grp / args.experts))
    disp_fpt = (3.0 * 2.0 * (2.0 * args.experts * cap * args.d_model)
                * args.n_layers)
    disp_tflops = tok_s * disp_fpt / 1e12

    cfg = model.cfg
    meta = {"model": "moe_lm", "params": param_count(state.params),
            "experts": args.experts, "top_k": args.top_k,
            "capacity": cap, "group_len": args.group_len,
            "dispatch": args.dispatch,
            "remat": args.remat, "batch": args.batch,
            "seq_len": args.seq_len, "d_model": args.d_model,
            "n_layers": args.n_layers, "device": kind, "devices": n_dev}
    lines = [
        {"metric": "moe_train_tokens_per_sec", "value": round(tok_s, 1),
         "unit": "tokens/sec", **meta},
        {"metric": "moe_train_active_tflops",
         "value": round(tflops, 2), "unit": "TFLOP/s", **meta},
        {"metric": "moe_train_active_mfu",
         "value": round(100 * mfu, 2) if mfu is not None else None,
         "unit": "%", **meta},
        {"metric": "moe_dispatch_overhead_tflops",
         "value": round(disp_tflops, 2), "unit": "TFLOP/s", **meta},
        {"metric": "moe_step_memory", "value": mem, "unit": "bytes",
         **meta},
        {"metric": "moe_dispatch_bytes_per_group_envelope",
         "value": {str(s): dispatch_bytes(s, args.experts, args.top_k,
                                          cfg.moe_capacity_factor)
                   for s in (1024, 4096, 8192, 16384, 32768)},
         "unit": "f32 bytes (dispatch+combine, one group)", **meta},
    ]
    out = "\n".join(json.dumps(l) for l in lines)
    print(out)
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import (
            write_jsonl)
        write_jsonl(args.out, lines)  # git-sha/calibration stamped


if __name__ == "__main__":
    main()
