"""Autoregressive generation benchmark: prefill and decode throughput.

The training side's perf story lives in lm_perf.py (MFU) and bench.py
(data path); this covers the INFERENCE path the reference never had:
KV-cache generation (models/generate.py) as one jitted prefill+decode
program. Reports

- prefill tokens/sec (the batched, MXU-bound phase),
- decode tokens/sec and ms/token (the bandwidth-bound phase — each
  step reads every param and the KV cache once per token), and
- the same decode with grouped KV heads (--n-kv-heads), measuring
  what the narrower cache buys.

Prints one JSON line per metric; --out also writes them to a file
(overwritten per run, like the sibling benchmarks).

Timing uses a host readback of the final tokens as the barrier — on
the tunneled axon runtime block_until_ready alone can return before
remote execution finishes (same caveat as lm_perf.py).
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size", default="small",
                        help="gpt_lm size preset (small | tiny)")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--prompt-len", type=int, default=512)
    parser.add_argument("--new-tokens", type=int, default=256)
    parser.add_argument("--n-kv-heads", type=int, default=0,
                        help="also A/B decode with this many KV heads "
                        "(0 = skip the A/B)")
    parser.add_argument("--kv-cache-quant", default="none",
                        choices=["none", "int8"],
                        help="also A/B decode with this cache "
                        "storage (int8 halves the dominant decode "
                        "HBM read vs bf16)")
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--out", default="")
    args = parser.parse_args(argv)
    if args.new_tokens < 2:
        parser.error("--new-tokens must be >= 2 (decode is timed as "
                     "total minus the 1-token prefill run)")
    if args.iters < 1:
        parser.error("--iters must be >= 1")

    import jax
    import numpy as np

    from tensorflow_distributed_tpu.models.generate import generate
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import single_device_mesh
    from tensorflow_distributed_tpu.train.state import (
        create_train_state, param_count)
    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    enable_persistent_cache()
    import optax

    dev = jax.devices()[0]
    mesh = single_device_mesh(dev)
    max_len = args.prompt_len + args.new_tokens
    rng = np.random.default_rng(0)

    def bench(label, **model_kw):
        model = gpt_lm(mesh, size=args.size, max_len=max_len,
                       dropout_rate=0.0, **model_kw)
        # Inference-only: optax.identity keeps the sharded-init path
        # without allocating Adam's 2x-param slot memory.
        state = create_train_state(
            model, optax.identity(),
            np.zeros((2, 16), np.int32), mesh, seed=0)
        params = state.params
        prompt = np.asarray(
            rng.integers(0, model.cfg.vocab_size,
                         size=(args.batch, args.prompt_len)), np.int32)

        def timed(n_tokens):
            """Warm-up compile, then the averaged timed loop with a
            host readback barrier — one methodology for both phases."""
            toks = generate(model, params, prompt, n_tokens)
            _ = np.asarray(toks)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                toks = generate(model, params, prompt, n_tokens)
            _ = np.asarray(toks)
            return (time.perf_counter() - t0) / args.iters

        wall = timed(args.new_tokens)
        # Split phases: a 1-token run is (prefill + one pick).
        prefill = timed(1)

        decode = max(wall - prefill, 1e-9)
        n_decode = args.batch * (args.new_tokens - 1)
        lines = [
            {"metric": f"gen_prefill_tokens_per_sec{label}",
             "value": round(args.batch * args.prompt_len / prefill, 1),
             "unit": "tokens/sec"},
            {"metric": f"gen_decode_tokens_per_sec{label}",
             "value": round(n_decode / decode, 1), "unit": "tokens/sec"},
            {"metric": f"gen_decode_ms_per_token{label}",
             "value": round(1e3 * decode / (args.new_tokens - 1), 3),
             "unit": "ms/token"},
        ]
        common = {
            "model": f"gpt_lm/{args.size}",
            "params": param_count(params),
            "batch": args.batch, "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens,
            "device": dev.device_kind, "n_kv_heads": model_kw.get(
                "n_kv_heads", model.cfg.n_heads),
            "kv_cache_quant": model_kw.get("kv_cache_quant", "none"),
        }
        return [dict(ln, **common) for ln in lines]

    lines = bench("")
    if args.n_kv_heads:
        lines += bench("_gqa", n_kv_heads=args.n_kv_heads)
    if args.kv_cache_quant != "none":
        lines += bench("_kvq", kv_cache_quant=args.kv_cache_quant)
        if args.n_kv_heads:
            # The composed story: narrow (GQA) AND thin (int8) cache.
            lines += bench("_gqa_kvq", n_kv_heads=args.n_kv_heads,
                           kv_cache_quant=args.kv_cache_quant)

    print("\n".join(json.dumps(ln) for ln in lines))
    if args.out:
        # Overwrite like the sibling benchmarks: reruns replace, never
        # silently accumulate stale lines (observe.registry owns the
        # JSONL format).
        from tensorflow_distributed_tpu.observe.registry import write_jsonl
        write_jsonl(args.out, lines)


if __name__ == "__main__":
    main()
