"""Device-mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's cluster plumbing:

- ``tf.train.ClusterSpec`` parsed from ``ps_hosts``/``worker_hosts`` flags
  (mnist_python_m.py:146-154) -> ``bootstrap()`` driving
  ``jax.distributed.initialize`` from env vars; the device set then comes
  from ``jax.devices()``. There is no ps role: every device is a worker
  and parameters live on-chip.
- ``tf.train.Server`` / ``server.join()`` (mnist_python_m.py:156-161) ->
  nothing user-visible; the TPU runtime and ICI fabric replace gRPC.
- ``is_chief`` (task_index == 0, mnist_python_m.py:163) -> ``is_chief()``
  == ``jax.process_index() == 0``, used only to elect one process for
  logging/checkpoint writes, never for an init dance.

Mesh axes:
    data   — data parallelism (the reference's 2 worker replicas)
    model  — tensor parallelism (not in the reference; first-class here)
    seq    — sequence/context parallelism (ring attention)
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from tensorflow_distributed_tpu.config import MeshConfig

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
MESH_AXES = (AXIS_DATA, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL, AXIS_EXPERT)

_bootstrapped = False


def bootstrap(coordinator: Optional[str] = None,
              num_processes: Optional[int] = None,
              process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX if a coordinator is configured.

    Replaces the reference's per-role server boot
    (mnist_python_m.py:156-161) and the chief's
    ``prepare_or_wait_for_session`` barrier (:272-275): after this
    returns, every process sees the same global device list and
    compiles the same SPMD program — there is nothing to "wait" for.

    No-op on a single host (the common test/bench path). Arguments
    default to the ``TPU_COORDINATOR_ADDRESS`` / ``TPU_NUM_PROCESSES`` /
    ``TPU_PROCESS_ID`` environment variables, so launching N identical
    processes with different env is the whole cluster story — the
    reference needed three differently-edited script copies.
    """
    global _bootstrapped
    if _bootstrapped:
        return
    coordinator = coordinator or os.environ.get("TPU_COORDINATOR_ADDRESS")
    if coordinator is None:
        _bootstrapped = True
        return
    num_processes = num_processes or int(os.environ.get("TPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("TPU_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _bootstrapped = True


def is_chief() -> bool:
    """True on the process elected for logging/checkpoint writes.

    The reference's chief (task_index==0, mnist_python_m.py:163) also ran
    variable init, sync-token init and a queue-runner thread
    (:224-233,:279-282); none of that exists under SPMD — this is purely
    "who prints".
    """
    return jax.process_index() == 0


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(data, pipe, seq, model)`` mesh over the given devices.

    ``cfg.data == -1`` means "all devices not consumed by
    pipe/seq/model". A 1-device mesh is valid and is exactly the
    reference's single-device path (mnist_single.py): same train step,
    mesh of one.
    """
    cfg = cfg or MeshConfig()
    cfg.validate()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    denom = cfg.model * cfg.seq * cfg.pipe * cfg.expert
    if n % denom != 0:
        raise ValueError(
            f"{n} devices not divisible by pipe*seq*model*expert = "
            f"{cfg.pipe}*{cfg.seq}*{cfg.model}*{cfg.expert}")
    data = cfg.data if cfg.data != -1 else n // denom
    if data * denom != n:
        raise ValueError(
            f"mesh {data}x{cfg.pipe}x{cfg.seq}x{cfg.model}x{cfg.expert}"
            f" != {n} devices")
    arr = np.array(devices).reshape(data, cfg.pipe, cfg.seq, cfg.model,
                                    cfg.expert)
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    """1-device mesh — the mnist_single.py path, same code, mesh of one."""
    device = device or jax.devices()[0]
    return make_mesh(MeshConfig(data=1), [device])
