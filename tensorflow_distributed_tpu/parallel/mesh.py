"""Device-mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's cluster plumbing:

- ``tf.train.ClusterSpec`` parsed from ``ps_hosts``/``worker_hosts`` flags
  (mnist_python_m.py:146-154) -> ``bootstrap()`` driving
  ``jax.distributed.initialize`` from env vars; the device set then comes
  from ``jax.devices()``. There is no ps role: every device is a worker
  and parameters live on-chip.
- ``tf.train.Server`` / ``server.join()`` (mnist_python_m.py:156-161) ->
  nothing user-visible; the TPU runtime and ICI fabric replace gRPC.
- ``is_chief`` (task_index == 0, mnist_python_m.py:163) -> ``is_chief()``
  == ``jax.process_index() == 0``, used only to elect one process for
  logging/checkpoint writes, never for an init dance.

Mesh axes:
    data   — data parallelism (the reference's 2 worker replicas)
    model  — tensor parallelism (not in the reference; first-class here)
    seq    — sequence/context parallelism (ring attention)
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from tensorflow_distributed_tpu.config import MeshConfig

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
MESH_AXES = (AXIS_DATA, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL, AXIS_EXPERT)

_bootstrapped = False


# --- mesh-feasibility rules (pure helpers; no jax, no backend) ---------
#
# The ONE home for the constraints every mesh-shape chooser applies:
# the elastic supervisor (resilience/supervisor.pick_elastic_mesh)
# re-sizing the data axis onto surviving devices, and the auto-layout
# planner (analysis/planner/candidates.py) enumerating factorizations.
# Both used to re-derive the same two rules; a third copy was the line
# this factoring exists to prevent.


def nondata_product(axes) -> int:
    """Product of the non-data axis sizes in ``axes`` (a {name: size}
    mapping; missing axes count 1) — the devices one data coordinate
    consumes. Non-data axes are SEMANTIC parallelism choices (tensor/
    seq/pipe/expert degrees the params' layouts assume), which is why
    resizes preserve them exactly and only "data" absorbs change."""
    denom = 1
    for name in (AXIS_MODEL, AXIS_SEQ, AXIS_PIPE, AXIS_EXPERT):
        denom *= max(1, int(axes.get(name, 1)))
    return denom


def pick_data_width(axes, alive: int, batch: Optional[int] = None
                    ) -> Optional[int]:
    """The largest data-axis width for ``alive`` devices: non-data
    axes of ``axes`` preserved, data = the biggest d whose product
    fits ``alive`` AND divides the global ``batch`` (per-device batch
    stays an integer share of the SAME global batch — the loss
    trajectory's comparability condition). None when even data=1
    doesn't fit — there is no compatible shape. Pure and jax-free."""
    denom = nondata_product(axes)
    if denom > alive or alive < 1:
        return None
    return next((d for d in range(alive // denom, 0, -1)
                 if batch is None or batch % d == 0), None)


def mesh_infeasible(axes, devices: int,
                    batch: Optional[int] = None) -> Optional[str]:
    """Why an EXPLICIT factorization can't run on ``devices`` with
    global ``batch`` — None when it can. The hard constraints shared
    by every chooser: every axis >= 1, the axis product must equal
    the device count, and the data width must divide the batch.
    Family-level divisibility (heads over "model", layers over
    "pipe", experts over "expert") lives with the model facts in
    analysis/planner/candidates.py — this module doesn't know models.
    Pure and jax-free."""
    sizes = {a: int(axes.get(a, 1)) for a in MESH_AXES}
    bad = [f"{a}={v}" for a, v in sizes.items() if v < 1]
    if bad:
        return f"axis sizes must be >= 1 ({', '.join(bad)})"
    product = sizes[AXIS_DATA] * nondata_product(sizes)
    if product != devices:
        return (f"mesh product {product} != {devices} device(s)")
    if batch is not None and batch % sizes[AXIS_DATA]:
        return (f"global batch {batch} not divisible by data width "
                f"{sizes[AXIS_DATA]}")
    return None


def bootstrap(coordinator: Optional[str] = None,
              num_processes: Optional[int] = None,
              process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX if a coordinator is configured.

    Replaces the reference's per-role server boot
    (mnist_python_m.py:156-161) and the chief's
    ``prepare_or_wait_for_session`` barrier (:272-275): after this
    returns, every process sees the same global device list and
    compiles the same SPMD program — there is nothing to "wait" for.

    No-op on a single host (the common test/bench path). Arguments
    default to the ``TPU_COORDINATOR_ADDRESS`` / ``TPU_NUM_PROCESSES`` /
    ``TPU_PROCESS_ID`` environment variables, so launching N identical
    processes with different env is the whole cluster story — the
    reference needed three differently-edited script copies.
    """
    global _bootstrapped
    if _bootstrapped:
        return
    coordinator = coordinator or os.environ.get("TPU_COORDINATOR_ADDRESS")
    if coordinator is None:
        _bootstrapped = True
        return
    num_processes = num_processes or int(os.environ.get("TPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("TPU_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _bootstrapped = True


def is_chief() -> bool:
    """True on the process elected for logging/checkpoint writes.

    The reference's chief (task_index==0, mnist_python_m.py:163) also ran
    variable init, sync-token init and a queue-runner thread
    (:224-233,:279-282); none of that exists under SPMD — this is purely
    "who prints".
    """
    return jax.process_index() == 0


def process_batch_role(mesh: Mesh):
    """(effective_count, effective_index) for BATCH-ROW distribution.

    The data layer splits each global batch into per-process disjoint
    row slices — correct ONLY when the mesh's "data" axis spans the
    processes. When a NON-data axis spans them (e.g. a cross-process
    ring: data=1, seq=8 over 2 hosts), several processes sit inside
    the same data coordinate and must supply IDENTICAL rows, or the
    assembled global batch is garbage. With the row-major
    (data, pipe, seq, model, expert) construction above, process p's
    local devices are the contiguous block [p*L, (p+1)*L) of
    jax.devices(), so its data coordinate(s) follow from L vs the
    devices-per-data-coordinate count; this returns the values the
    batchers should use in place of raw process_count/process_index.
    """
    pc = jax.process_count()
    if pc == 1:
        return 1, 0
    total = mesh.devices.size
    local = total // pc
    inner = total // mesh.shape[AXIS_DATA]  # devices per data coord
    if max(local, inner) % min(local, inner):
        raise ValueError(
            f"unsupported process layout: {local} local devices per "
            f"process vs {inner} devices per data coordinate — a "
            f"process would straddle a data-shard boundary")
    eff_count = total // max(local, inner)
    eff_index = jax.process_index() // max(1, inner // local)
    return eff_count, eff_index


def process_axis_range(mesh: Mesh, axis: str, dim: int):
    """[lo, hi) slice of a ``dim``-sized global array axis sharded over
    mesh ``axis`` that THIS process's local devices address.

    Needed by the multi-host batch assembly: when a non-batch mesh axis
    (e.g. "seq") spans processes, each process must hand
    ``make_array_from_process_local_data`` exactly its local block of
    that axis — passing the full axis makes JAX infer a doubled global
    shape (each process's copy taken as a distinct shard). Relies on
    the row-major MESH_AXES device layout of make_mesh below.
    """
    pc = jax.process_count()
    size = mesh.shape[axis]
    if pc == 1 or size == 1:
        return 0, dim
    # Devices per increment of this axis = product of the axis sizes
    # AFTER it in MESH_AXES order.
    stride = 1
    for a in MESH_AXES[MESH_AXES.index(axis) + 1:]:
        stride *= mesh.shape[a]
    span = stride * size  # one full cycle of the axis
    local = mesh.devices.size // pc
    if local >= span:
        return 0, dim  # process covers every coordinate
    if stride % local and local % stride:
        raise ValueError(
            f"unsupported process layout for axis {axis!r}: {local} "
            f"local devices vs stride {stride}")
    first = jax.process_index() * local
    coord0 = (first // stride) % size
    count = max(1, local // stride)
    if coord0 + count > size:
        # The process's device block wraps across a cycle of this axis
        # (e.g. data=1, pipe=2, seq=3 over 3 processes of 2): its
        # coordinates are non-contiguous and cannot be one host slice.
        raise ValueError(
            f"unsupported process layout: process covers wrapped "
            f"{axis!r} coordinates [{coord0}, {coord0 + count}) of "
            f"{size}")
    rows = dim // size
    return coord0 * rows, (coord0 + count) * rows


def alive_devices() -> list:
    """``jax.devices()`` minus the drill mask.

    ``TFD_DEVICE_MASK=N`` hides the LAST N devices from mesh
    construction — the mechanism by which an elastic-restart drill
    (resilience/faults.py ``device_loss``, resilience/supervisor.py
    ``--elastic``) models dead chips on a host whose runtime still
    enumerates them. A real preemption needs no mask: the lost chips
    are simply absent from ``jax.devices()`` on the restarted leg.
    Unset (the default) this is exactly ``jax.devices()``.
    """
    devs = list(jax.devices())
    mask = int(os.environ.get("TFD_DEVICE_MASK", "0") or 0)
    if mask < 0:
        raise ValueError(f"TFD_DEVICE_MASK must be >= 0, got {mask}")
    if mask >= len(devs):
        raise ValueError(
            f"TFD_DEVICE_MASK={mask} masks every device "
            f"({len(devs)} visible) — nothing left to run on")
    return devs[:len(devs) - mask] if mask else devs


def mesh_shape_dict(mesh: Mesh) -> dict:
    """``{axis: size}`` in MESH_AXES order — the serializable mesh
    identity the checkpoint layer's mesh manifest records."""
    return {a: int(mesh.shape[a]) for a in mesh.axis_names}


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(data, pipe, seq, model)`` mesh over the given devices.

    ``cfg.data == -1`` means "all devices not consumed by
    pipe/seq/model". A 1-device mesh is valid and is exactly the
    reference's single-device path (mnist_single.py): same train step,
    mesh of one. Defaults to :func:`alive_devices` — the full device
    set unless an elastic drill masked some.
    """
    cfg = cfg or MeshConfig()
    cfg.validate()
    devices = list(devices if devices is not None else alive_devices())
    n = len(devices)
    denom = cfg.model * cfg.seq * cfg.pipe * cfg.expert
    if n % denom != 0:
        raise ValueError(
            f"{n} devices not divisible by pipe*seq*model*expert = "
            f"{cfg.pipe}*{cfg.seq}*{cfg.model}*{cfg.expert}")
    data = cfg.data if cfg.data != -1 else n // denom
    if data * denom != n:
        raise ValueError(
            f"mesh {data}x{cfg.pipe}x{cfg.seq}x{cfg.model}x{cfg.expert}"
            f" != {n} devices")
    arr = np.array(devices).reshape(data, cfg.pipe, cfg.seq, cfg.model,
                                    cfg.expert)
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    """1-device mesh — the mnist_single.py path, same code, mesh of one."""
    device = device or jax.devices()[0]
    return make_mesh(MeshConfig(data=1), [device])
