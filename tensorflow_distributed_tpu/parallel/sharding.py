"""Sharding rules: how params, optimizer state, and batches map to a mesh.

TPU-native replacement for ``tf.train.replica_device_setter``
(mnist_python_m.py:177), which round-robined Variables onto the ps and
compute onto workers. Here there is no variable/op placement split:
parameters carry (optional) partition metadata, batches are sharded over
the data axis, and XLA's SPMD partitioner emits collectives (psum over
ICI) wherever math crosses shards — the per-step ps pull/push
(SURVEY.md N4) simply has no analog.

Conventions:
- Model params without partition metadata are fully replicated (the
  reference's model, ~3.3M params, is small enough that ZeRO-style
  sharding would be pure overhead).
- Params built with ``flax.linen.with_partitioning`` carry logical axis
  names that are already mesh axis names ("model", "seq") — used by the
  tensor-parallel transformer.
- Batches shard their leading axis over "data" (and, for long-sequence
  inputs, their sequence axis over "seq").
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import (
    AXIS_DATA, AXIS_SEQ, process_axis_range, process_batch_role)


def path_key(path) -> tuple:
    """Normalize a jax tree_flatten_with_path path to a tuple of
    strings, so param paths can be compared across pytrees whose key
    entry types differ (DictKey vs SequenceKey vs future kinds)."""
    return tuple(str(getattr(k, "key", getattr(k, "idx", k)))
                 for k in path)


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params live on every chip, unlike the
    reference where they lived only on the ps CPU and streamed over TCP
    each step, mnist_python_m.py:177, SURVEY.md N4)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 1,
                   seq_axis: Optional[int] = None) -> NamedSharding:
    """Shard dim 0 over the data axis; optionally a sequence dim over seq.

    This is the framework's data-parallel contract: each data-slice of
    the mesh sees a disjoint shard of the global batch — unlike the
    reference, whose workers sampled MNIST independently with no
    sharding at all (SURVEY.md N13; a documented behavioral upgrade).
    """
    spec = [None] * ndim
    spec[0] = AXIS_DATA
    if seq_axis is not None:
        spec[seq_axis] = AXIS_SEQ
    return NamedSharding(mesh, P(*spec))


# Leaves smaller than this stay replicated under FSDP: gathering a
# handful of bias/layernorm vectors costs more in collective latency
# than their replicated residency costs in HBM.
FSDP_MIN_SIZE = 2 ** 14


def fsdp_scatter_dim(shape: tuple, axis_size: int,
                     taken: tuple = ()) -> int:
    """The dim a leaf shards over "data" under the FSDP/ZeRO rule: the
    LARGEST still-unsharded dim divisible by the axis size (ties keep
    the earliest). -1 when no dim qualifies. THE one copy of the
    dim-choice rule — ``param_sharding`` places slots with it and the
    overlap grad-sync (parallel.overlap) reduce-scatters along it, so
    the two can never disagree about where a shard lives."""
    best = -1
    for d, n in enumerate(shape):
        if d not in taken and n % axis_size == 0:
            if best < 0 or n > shape[best]:
                best = d
    return best


def _fsdp_axis_choice(spec: list, shape: tuple, axis_size: int) -> list:
    """Add the data axis to the largest still-unsharded, divisible dim.

    ZeRO-3 placement as a GSPMD sharding rule: the weight shard lives
    where its gradient shard will be reduce-scattered to, XLA inserts
    the all-gather at use and the reduce-scatter in the backward — no
    hand-written bucketing/hooks like torch-FSDP needs. Dims already
    carrying a mesh axis (tensor/expert-parallel annotations) are left
    alone, so FSDP composes with TP/EP instead of fighting it.
    """
    # Spec entries may be tuples of axis names (legal PartitionSpec
    # form) — flatten before testing, or a tuple containing "data"
    # would get the axis added twice and NamedSharding would raise.
    if any(AXIS_DATA in (e if isinstance(e, tuple) else (e,))
           for e in spec):  # already data-annotated: nothing to add
        return spec
    best = fsdp_scatter_dim(
        tuple(shape), axis_size,
        taken=tuple(d for d, e in enumerate(spec) if e is not None))
    if best >= 0:
        spec = list(spec)
        spec[best] = AXIS_DATA
    return spec


def param_sharding(mesh: Mesh, tree: Any, fsdp: bool = False,
                   fsdp_min_size: int = FSDP_MIN_SIZE) -> Any:
    """NamedSharding tree for a (possibly metadata-boxed) param pytree.

    ``fsdp=True``: ZeRO-style sharding — every large-enough leaf also
    shards one dim over the "data" axis, so params AND the optimizer
    slots that mirror them (train.state matches slots to param
    shardings) are partitioned across data-parallel devices instead of
    replicated. Memory per device drops ~1/data for the big tensors;
    the per-step cost is the all-gather/reduce-scatter pair GSPMD
    emits, which rides ICI like every other collective here.

    Leaves wrapped by ``nn.with_partitioning`` map their axis names onto
    the mesh; bare leaves are replicated.
    """
    axis_size = mesh.shape[AXIS_DATA]

    def one(leaf):
        if isinstance(leaf, nn.Partitioned):
            spec = list(leaf.names)
            shape = leaf.value.shape
        else:
            shape = getattr(leaf, "shape", ())
            spec = [None] * len(shape)
        if (fsdp and axis_size > 1 and shape
                and int(np.prod(shape)) >= fsdp_min_size):
            spec = _fsdp_axis_choice(spec, shape, axis_size)
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(
        one, tree, is_leaf=lambda x: isinstance(x, nn.Partitioned))


def process_slice(batch: Any, mesh: Optional[Mesh] = None) -> Any:
    """Slice a replicated host batch down to this process's rows.

    ``shard_batch`` expects PROCESS-LOCAL rows under multi-host (the
    train stream's ShardedBatcher already yields them); eval paths that
    materialize the same full batch on every process go through this
    first. Single-process: identity.

    ``mesh``: when given, the slice follows the mesh's data-axis
    process layout (parallel.mesh.process_batch_role) — processes that
    share a data coordinate (a cross-process seq/model/pipe axis) keep
    identical full slices instead of wrongly-disjoint ones. Without a
    mesh, falls back to the plain per-process split (correct only when
    the data axis spans all processes).
    """
    if jax.process_count() == 1:
        return batch
    if mesh is not None:
        pc, pi = process_batch_role(mesh)
    else:
        pc, pi = jax.process_count(), jax.process_index()
    if pc == 1:
        return batch

    def one(x):
        x = np.asarray(x)
        if x.shape[0] % pc:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by {pc} processes")
        n = x.shape[0] // pc
        return x[pi * n:(pi + 1) * n]

    return jax.tree_util.tree_map(one, batch)


def shard_batch(mesh: Mesh, batch: Any, seq_axis: Optional[int] = None) -> Any:
    """device_put a host batch as a globally-sharded array.

    Replaces the reference's per-step feed_dict host->runtime copy
    (mnist_python_m.py:291-294, SURVEY.md N14). On one host this splits
    the (full) global batch over local devices. Under multi-host each
    process passes only its local shard (the process-disjoint rows from
    ``data.ShardedBatcher``) and the pieces are assembled into one
    global array via ``make_array_from_process_local_data`` — the global
    batch keeps its full size B, each host contributing B/P rows.
    """
    multihost = jax.process_count() > 1

    def one(x):
        x = np.asarray(x)
        sharding = batch_sharding(mesh, x.ndim, seq_axis)
        if multihost:
            if seq_axis is not None and mesh.shape[AXIS_SEQ] > 1:
                # A cross-process seq axis: hand JAX exactly this
                # process's seq block, or it infers a doubled global
                # seq dim (parallel.mesh.process_axis_range).
                lo, hi = process_axis_range(mesh, AXIS_SEQ,
                                            x.shape[seq_axis])
                if (lo, hi) != (0, x.shape[seq_axis]):
                    sl = [slice(None)] * x.ndim
                    sl[seq_axis] = slice(lo, hi)
                    x = x[tuple(sl)]
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(one, batch)
