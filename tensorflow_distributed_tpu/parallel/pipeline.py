"""Pipeline parallelism: GPipe microbatch schedule over the "pipe" axis.

The reference has no pipeline parallelism (single-stage model,
SURVEY.md §2b checklist) — this is a beyond-reference capability,
designed TPU-first rather than ported:

- Layer stacks live as ONE stacked pytree (leaves [S, ...], leading dim
  sharded over the "pipe" mesh axis) instead of per-stage modules —
  XLA sees one program, each device holding its stage's slice.
- The schedule is a ``lax.scan`` over T = M + S - 1 ticks inside a
  ``shard_map`` restricted to the pipe axis (``axis_names={"pipe"}``),
  so data/tensor/sequence sharding of the activations continues to be
  handled by the surrounding GSPMD partitioner.
- Activations hop stage s -> s+1 once per tick via ``lax.ppermute`` —
  neighbor ICI traffic, the TPU-native analog of NCCL P2P send/recv.
- Bubble ticks compute on garbage and are masked with ``jnp.where``
  (predication, not control flow — the compiled program is static).
  Bubble fraction is the standard (S-1)/(M+S-1).

Everything is differentiable: the backward pipeline falls out of AD
(scan reverses, ppermute transposes to the opposite rotation).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import AXIS_PIPE


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, mesh: Mesh,
                   num_microbatches: int) -> jax.Array:
    """Run ``x`` through S pipeline stages with an M-microbatch schedule.

    stage_params: pytree whose leaves have leading dim S (sharded
    ``P("pipe")``); ``stage_fn(one_stage_params, x_mb) -> y_mb`` must
    preserve the microbatch shape (a transformer block stack does).
    x: [B, ...] with B % num_microbatches == 0. Returns [B, ...].
    """
    S = mesh.shape[AXIS_PIPE]
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if M < S:
        raise ValueError(f"need microbatches >= stages ({M} < {S})")
    mb = B // M

    def per_pipe(params, x):
        # Local leaves arrive [1, ...] (this stage's slice); drop the
        # stage dim.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        s = jax.lax.axis_index(AXIS_PIPE)
        xm = x.reshape(M, mb, *x.shape[1:])
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outs = carry
            # Stage 0 ingests microbatch t; later stages eat the
            # activation their neighbor pushed last tick.
            feed = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            y = stage_fn(params, jnp.where(s == 0, feed, state))
            # The last stage commits finished microbatch t-(S-1).
            oidx = jnp.clip(t - (S - 1), 0, M - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0,
                                                keepdims=False)
            write = jnp.logical_and(s == S - 1, t >= S - 1)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, y, prev), oidx, 0)
            return (jax.lax.ppermute(y, AXIS_PIPE, perm), outs), None

        outs0 = jnp.zeros_like(xm)
        (_, outs), _ = jax.lax.scan(tick, (jnp.zeros_like(xm[0]), outs0),
                                    jnp.arange(M + S - 1))
        # Stage-stacked output: only the last stage's slice is real.
        return outs.reshape(B, *x.shape[1:])[None]

    out = jax.shard_map(
        per_pipe, mesh=mesh, axis_names={AXIS_PIPE},
        in_specs=(P(AXIS_PIPE), P()), out_specs=P(AXIS_PIPE),
        check_vma=False)(stage_params, x)
    return out[-1]


def stack_stage_params(layer_params: Any, num_stages: int) -> Any:
    """[n_layers, ...] stacked layer params -> [S, layers_per_stage, ...]
    stage-major grouping (stage s owns layers [s*Lps, (s+1)*Lps))."""
    def regroup(p):
        n = p.shape[0]
        if n % num_stages:
            raise ValueError(
                f"{n} layers not divisible by {num_stages} stages")
        return p.reshape(num_stages, n // num_stages, *p.shape[1:])
    return jax.tree_util.tree_map(regroup, layer_params)
