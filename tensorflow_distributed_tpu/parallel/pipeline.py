"""Pipeline parallelism: GPipe microbatch schedule over the "pipe" axis.

The reference has no pipeline parallelism (single-stage model,
SURVEY.md §2b checklist) — this is a beyond-reference capability,
designed TPU-first rather than ported:

- Layer stacks live as ONE stacked pytree (leaves [S, ...], leading dim
  sharded over the "pipe" mesh axis) instead of per-stage modules —
  XLA sees one program, each device holding its stage's slice.
- The schedule is a ``lax.scan`` over T = M + S - 1 ticks inside a
  ``shard_map`` restricted to the pipe axis (``axis_names={"pipe"}``),
  so data/tensor/sequence sharding of the activations continues to be
  handled by the surrounding GSPMD partitioner.
- Activations hop stage s -> s+1 once per tick via ``lax.ppermute`` —
  neighbor ICI traffic, the TPU-native analog of NCCL P2P send/recv.
- GPipe bubble ticks compute on garbage and are masked with
  ``jnp.where`` — a MEASURED choice, not an oversight: wrapping the
  stage in ``lax.cond`` and letting AD differentiate through it was
  tried and is SLOWER (2332 vs 1746 ms/step at S=4, M=4 on the 8-way
  CPU mesh — cond blocks fusion and complicates the scan's saved
  residuals on the AD path). Bubble fraction is the standard
  (S-1)/(M+S-1). The 1F1B schedule below DOES skip bubble work with
  real ``lax.cond`` branches — its backward is hand-rolled, so
  nothing ADs through the cond. Same S=4/M=4 measurement: 1F1B went
  2729 (old where-masked form) -> 831 ms/step (3.3x), which also puts
  it 2.1x ahead of GPipe's 1746 ms — hence 1f1b is the config
  default.

Everything is differentiable: the backward pipeline falls out of AD
(scan reverses, ppermute transposes to the opposite rotation).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import AXIS_PIPE


def pipeline_apply(stage_fn: Callable[..., jax.Array],
                   stage_params: Any, x: jax.Array, mesh: Mesh,
                   num_microbatches: int,
                   rng: Any = None, stage_aux: bool = False):
    """Run ``x`` through S pipeline stages with an M-microbatch schedule.

    stage_params: pytree whose leaves have leading dim S (sharded
    ``P("pipe")``); ``stage_fn(one_stage_params, x_mb) -> y_mb`` must
    preserve the microbatch shape (a transformer block stack does).
    x: [B, ...] with B % num_microbatches == 0. Returns [B, ...].

    ``rng``: optional PRNG key for in-stage dropout. When given,
    stage_fn is called as ``stage_fn(params, x_mb, key)`` with a key
    folded over (microbatch, stage) so no two (mb, stage) pairs share
    masks; bubble ticks reuse a clipped mb index (their output is
    masked out at commit, so their mask content is irrelevant).

    ``stage_aux``: when True, stage_fn returns ``(y_mb, aux)`` with
    ``aux`` a pytree of scalars (e.g. MoE router losses); bubble-tick
    aux is masked out and the call returns ``(out, aux_sums)`` where
    aux_sums are summed over all (stage, microbatch) pairs —
    differentiable, so AD through this schedule back-propagates router
    losses too.
    """
    S = mesh.shape[AXIS_PIPE]
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if M < S:
        raise ValueError(f"need microbatches >= stages ({M} < {S})")
    mb = B // M

    def per_pipe(params, x):
        # Local leaves arrive [1, ...] (this stage's slice); drop the
        # stage dim.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        s = jax.lax.axis_index(AXIS_PIPE)
        xm = x.reshape(M, mb, *x.shape[1:])
        perm = [(i, (i + 1) % S) for i in range(S)]

        def run_stage(t, inp):
            if rng is None:
                out = stage_fn(params, inp)
            else:
                key = jax.random.fold_in(
                    jax.random.fold_in(rng, jnp.clip(t - s, 0, M - 1)), s)
                out = stage_fn(params, inp, key)
            return out if stage_aux else (out, ())

        if stage_aux:
            aux0 = jax.eval_shape(lambda: run_stage(0, xm[0])[1])
            aux0 = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, a.dtype), aux0)
        else:
            aux0 = ()

        def tick(carry, t):
            state, outs, aux_acc = carry
            # Stage 0 ingests microbatch t; later stages eat the
            # activation their neighbor pushed last tick.
            feed = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            y, aux = run_stage(t, jnp.where(s == 0, feed, state))
            # Stage s does real work for microbatch t - s only.
            valid = jnp.logical_and(t - s >= 0, t - s < M)
            aux_acc = jax.tree_util.tree_map(
                lambda a, b: a + jnp.where(valid, b, 0), aux_acc, aux)
            # The last stage commits finished microbatch t-(S-1).
            oidx = jnp.clip(t - (S - 1), 0, M - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0,
                                                keepdims=False)
            write = jnp.logical_and(s == S - 1, t >= S - 1)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, y, prev), oidx, 0)
            return (jax.lax.ppermute(y, AXIS_PIPE, perm), outs,
                    aux_acc), None

        outs0 = jnp.zeros_like(xm)
        (_, outs, aux_acc), _ = jax.lax.scan(
            tick, (jnp.zeros_like(xm[0]), outs0, aux0),
            jnp.arange(M + S - 1))
        # Stage-stacked output: only the last stage's slice is real.
        # Aux is real on EVERY stage; psum totals it over the pipe.
        aux_tot = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, AXIS_PIPE), aux_acc)
        return outs.reshape(B, *x.shape[1:])[None], aux_tot

    out, aux = jax.shard_map(
        per_pipe, mesh=mesh, axis_names={AXIS_PIPE},
        in_specs=(P(AXIS_PIPE), P()),
        out_specs=(P(AXIS_PIPE), P()),
        check_vma=False)(stage_params, x)
    return (out[-1], aux) if stage_aux else out[-1]


def bubble_fraction(num_microbatches: int, num_stages: int,
                    schedule: str = "gpipe") -> float:
    """Fraction of stage-ticks spent idle (computing masked garbage).

    gpipe: the classic (S-1)/(M+S-1) over M+S-1 forward ticks (the
    backward pipeline mirrors it under AD). 1f1b: the paired
    fwd+bwd schedule runs M + 2(S-1) tick pairs, of which 2(S-1) are
    ramp-up/drain bubbles.

    On interleaved (virtual-stage) schedules — analyzed across rounds
    3-4, IMPLEMENTED for correctness in round 5
    (``interleaved_pipeline_value_and_grad``; the [S, V, lps] layout,
    [S*V]-deep virtual ring, parity-pinned in
    tests/test_pipeline_1f1b.py). The analysis stands and the
    implementation embodies it: every schedule here is a lockstep
    ``lax.scan`` whose tick runs one fwd + one bwd slot per (device,
    chunk) between ppermutes, so wall time is ticks x slot time
    regardless of which devices' slots are cond-skipped. Folding V
    chunk-columns per device makes the chunk round-robin pipe SV
    chunks deep with MV chunk-jobs per device: utilization
    MV/(MV + 2(SV-1)) — STRICTLY WORSE than the plain M/(M + 2(S-1))
    for V > 1 (M=8, S=4: 57% plain, 53% at V=2). Megatron's bubble/V
    win does not come from interleaving alone but from its ASYMMETRIC
    grouped schedule: warmup ticks run fwd-ONLY chunk bursts (up to
    S-1+2(V-1) forwards queued per device before the first backward)
    so ramp chunks overlap useful steady-state work — a schedule a
    uniform one-fwd-one-bwd tick cannot express. Expressing it would
    need per-tick static slot tables driving variable work per tick;
    on this hardware (single-chip S=1 — no bubble at all, PARITY.md)
    the asymmetric form buys nothing measurable, so the uniform-tick
    implementation is the correctness vehicle and the schedule-level
    A/B is an owed on-chip measurement. What DOES pay, and IS
    implemented, is making bubble half-ticks free:
    pipeline_value_and_grad's tick wraps each half in a real
    ``lax.cond`` (possible because its backward is hand-rolled —
    nothing ADs through the cond), skipping ramp/drain garbage compute
    instead of where-masking it. Measured 3.3x per-step at S=4, M=4
    (see module docstring); the reported 2(S-1)/(M+2(S-1)) fraction
    remains the SLOT accounting — the skipped slots now cost ~0 time
    rather than a full stage pass. (Exception: stages carrying seq
    collectives run where-masked — the ``bubble`` switch — because a
    collective under per-pipe-rank control flow is not SPMD-legal.)"""
    M, S = num_microbatches, num_stages
    if schedule == "gpipe":
        return (S - 1) / (M + S - 1)
    if schedule == "1f1b":
        return 2 * (S - 1) / (M + 2 * (S - 1))
    raise ValueError(f"schedule {schedule!r}; have ('gpipe', '1f1b')")


def variant_residual_mask(res_fn: Callable[[Any, jax.Array, jax.Array],
                                           list],
                          params: Any, x0: jax.Array) -> list:
    """Which vjp-residual leaves actually vary per microbatch?

    ``res_fn(params, x_mb, m) -> flat residual leaves`` (m: the
    microbatch index that seeds dropout keys). Returns a bool per leaf:
    True = depends on (x_mb, m) and must be ring-buffered per in-flight
    microbatch; False = a pure function of the stage params (weight
    matrices and their compute-dtype casts — the transpose operands
    ``jax.vjp`` captures alongside the activations), identical for
    every microbatch, so the stash backward computes it ONCE per step
    instead of storing D copies.

    The split is read off the jaxpr: seed the variant set with the
    (x, m) input vars and propagate — any equation consuming a variant
    var marks all its outputs variant. Call/scan/cond/remat equations
    are handled at the equation level, i.e. conservatively: a false
    positive only stashes more than needed, never corrupts a gradient.
    """
    flat_p, tree_p = jax.tree_util.tree_flatten(params)
    n_p = len(flat_p)

    def flat_fn(*args):
        p = jax.tree_util.tree_unflatten(tree_p, args[:n_p])
        return res_fn(p, args[n_p], args[n_p + 1])

    from jax.extend.core import Literal

    closed = jax.make_jaxpr(flat_fn)(*flat_p, x0, jnp.int32(0))
    jaxpr = closed.jaxpr
    variant = set(jaxpr.invars[n_p:])  # the x and m vars
    for eqn in jaxpr.eqns:
        if any(not isinstance(v, Literal) and v in variant
               for v in eqn.invars):
            variant.update(eqn.outvars)
    return [not isinstance(v, Literal) and v in variant
            for v in jaxpr.outvars]


def split_by_mask(leaves, mask):
    """(variant_leaves, const_leaves) per the bool mask — the single
    inverse pair with merge_by_mask; all stash bookkeeping goes
    through these two so the pairing can't drift."""
    if len(leaves) != len(mask):
        raise AssertionError(f"{len(leaves)} leaves vs {len(mask)} mask")
    return ([l for l, v in zip(leaves, mask) if v],
            [l for l, v in zip(leaves, mask) if not v])


def merge_by_mask(variant_leaves, const_leaves, mask):
    """Inverse of split_by_mask: reassemble the full leaf list."""
    vs, cs = iter(variant_leaves), iter(const_leaves)
    out = [next(vs) if v else next(cs) for v in mask]
    for leftover in (vs, cs):
        if next(leftover, None) is not None:
            raise AssertionError("leaf count mismatch in merge_by_mask")
    return out


def _select_tree(pred, new, old):
    """``jnp.where`` over matching pytrees — the single predication
    primitive for the ``bubble="where"`` paths (one implementation so
    every select site in both schedules stays in lockstep)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(pred, n, o), new, old)


def pipeline_value_and_grad(stage_fn: Callable[..., jax.Array],
                            last_fn: Callable[[Any, jax.Array, Any],
                                              tuple],
                            stage_params: Any, last_params: Any,
                            x: jax.Array, aux: Any, mesh: Mesh,
                            num_microbatches: int, rng: Any = None,
                            cotangent_scale: Any = 1.0,
                            stage_aux_cotangent: Any = None,
                            backward: str = "recompute",
                            bubble: str = "cond"):
    """1F1B pipeline: hand-scheduled forward AND backward in one pass.

    GPipe (``pipeline_apply`` + outer AD) must finish every forward
    before the first backward, so each stage holds O(M) microbatch
    residuals. Here backward for microbatch m starts as soon as m
    clears the last stage — the per-microbatch loss (``last_fn``) is
    computed AT the last stage inside the schedule, seeding the
    cotangent that flows back up the ring while later microbatches are
    still flowing down. Peak per-stage state is the input stash of
    depth min(2S, M) — INDEPENDENT of M — plus the gradient
    accumulators; backward ticks recompute the stage forward from the
    stashed input (jax.vjp), the same trade per-stage remat makes.

    Schedule: T = M + 2(S-1) tick pairs; at tick t stage s runs
    forward for microbatch t - s and backward for t - 2(S-1) + s (when
    in range). The last stage's backward of microbatch m lands on the
    same tick as its forward. Bubble half-ticks are SKIPPED with real
    ``lax.cond`` branches (safe here precisely because the backward is
    hand-rolled — nothing ADs through the cond), so ramp/drain costs
    ~no compute; skip branches return exact zeros, which is what the
    plain-add accumulators rely on. Per tick each stage ppermutes its
    activation DOWN the ring and its input-cotangent UP — neighbor ICI
    traffic both ways.

    Interfaces:
      stage_fn(params, x_mb[, key]) -> y_mb       (same as pipeline_apply)
      last_fn(last_params, y_mb, aux_mb) -> (scalar_sum, metrics_sums)
        — UNNORMALIZED per-microbatch sums; the caller normalizes.
      aux: pytree with leading dim B (targets, masks, ...), microbatch-
        sliced alongside x.
      cotangent_scale: seed for d(scalar_sum) — e.g. 1/total_mask so
        the accumulated grads equal the mean-loss grads exactly.

    Returns (value_sum, metrics_sums, (d_stage_params, d_last_params,
    d_x)) — d_stage_params stage-stacked [S, ...] like stage_params,
    d_x [B, ...] (feeds the embedding vjp outside).

    ``stage_aux_cotangent``: when not None, stage_fn returns
    ``(y_mb, aux)`` (aux a pytree of scalars — MoE router losses) and
    this argument is the matching pytree of objective weights: each
    backward tick seeds the stage vjp with (d_y, stage_aux_cotangent),
    so router-loss gradients flow into both the stage params and the
    upstream activations exactly like any other loss term. The return
    grows a 4th element: aux sums over all (stage, microbatch) pairs
    — (value_sum, metrics_sums, aux_sums, grads).

    ``backward``: what each stage stashes between a microbatch's
    forward and backward ticks.
      "recompute" (default) — stash the stage INPUT; the backward tick
        re-runs the stage forward under jax.vjp to rebuild residuals.
        Minimal memory (D copies of one activation), but every
        microbatch pays the stage forward twice: 4x forward-equivalent
        FLOPs per token instead of AD's 3x — measured as the dominant
        pipelined-MFU cost on chip (24.8% vs 46.5% unpipelined at
        matched shapes, LMBENCH_r04 vs r03_pipelined sweep).
      "stash" — run jax.vjp at the FORWARD tick and stash the vjp
        residuals themselves: ``jax.vjp``'s pulled-back function is a
        ``jax.tree_util.Partial`` — a pytree — so its leaves stash
        into per-slot ring buffers like any activation, and the
        backward tick re-attaches them to the (static) treedef
        obtained via ``jax.eval_shape`` — no recompute, Megatron's
        default memory/compute trade. Ring-buffered leaves are only
        the MICROBATCH-VARIANT residuals: ``variant_residual_mask``
        reads the residual jaxpr and splits out the leaves that are a
        pure function of params (the stage weight matrices and their
        compute-dtype casts, which jax.vjp captures as transpose
        operands) — those are computed once per step instead of D
        copies per ring. Before this hoist, the weight copies
        dominated stash's HBM traffic and made it measurably SLOWER
        than recompute on v5e at GPT-2-small shapes (19.9% vs 30.8%
        MFU, PARITY.md) — that measurement predates the hoist and is
        owed a re-run; stash stays opt-in until it's re-measured.

    ``bubble``: how ramp/drain slots are suppressed.
      "cond" (default) — real ``lax.cond`` branches skip the bubble
        compute entirely (the measured 3.3x win, module docstring).
        REQUIRES the stage to contain no cross-device collectives:
        the predicate varies per pipe rank, and XLA SPMD cannot honor
        a collective under non-uniform control flow — with ring
        attention's seq-ppermutes inside the branch this silently
        computes garbage (measured: wrong loss, NaN under learned
        pos-emb, on the virtual mesh).
      "where" — compute every slot and mask the results (the GPipe-
        style predication this schedule used before round 4): bubble
        slots cost a full stage pass, but every collective executes
        unconditionally on every rank. train.pipeline_step selects
        this automatically when mesh.seq > 1 routes the stage through
        ring attention.
    """
    S = mesh.shape[AXIS_PIPE]
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if M < S:
        raise ValueError(f"need microbatches >= stages ({M} < {S})")
    if bubble not in ("cond", "where"):
        raise ValueError(f"bubble {bubble!r}; have ('cond', 'where')")
    if backward not in ("recompute", "stash"):
        raise ValueError(f"backward {backward!r}; "
                         "have ('recompute', 'stash')")
    stash_residuals = backward == "stash"
    mb = B // M
    D = min(2 * S, M)  # stash depth >= max in-flight (2S - 1)

    def per_pipe(params, last_p, x, aux, scale):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        s = jax.lax.axis_index(AXIS_PIPE)
        xm = x.reshape(M, mb, *x.shape[1:])
        auxm = jax.tree_util.tree_map(
            lambda a: a.reshape(M, mb, *a.shape[1:]), aux)
        down = [(i, (i + 1) % S) for i in range(S)]
        up = [((i + 1) % S, i) for i in range(S)]
        is_last = s == S - 1

        aux_on = stage_aux_cotangent is not None

        def with_key(m):
            if rng is None:
                fn = lambda p, xx: stage_fn(p, xx)  # noqa: E731
            else:
                key = jax.random.fold_in(jax.random.fold_in(rng, m), s)
                fn = lambda p, xx: stage_fn(p, xx, key)  # noqa: E731
            # Normalize to (y, aux) so forward/backward share one shape.
            return fn if aux_on else (lambda p, xx: (fn(p, xx), ()))

        def head(m, y):
            aux_mb = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, m, 0, keepdims=False), auxm)
            val, vjp_fn, met = jax.vjp(
                lambda lp, yy: last_fn(lp, yy, aux_mb), last_p, y,
                has_aux=True)
            dlast, dy = vjp_fn(jnp.asarray(scale, val.dtype))
            return val, met, dlast, dy

        zero_dp = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        zero_dlast = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), last_p)
        if aux_on:
            aux_abs = jax.eval_shape(
                lambda: with_key(0)(params, xm[0])[1])
            zero_aux = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, a.dtype), aux_abs)
            aux_seed = jax.tree_util.tree_map(
                lambda w, a: jnp.asarray(w, a.dtype),
                stage_aux_cotangent, zero_aux)
        else:
            zero_aux, aux_seed = (), ()
        met_abs = jax.eval_shape(
            lambda lp, yy, am: last_fn(lp, yy, am)[1], last_p, xm[0],
            jax.tree_util.tree_map(lambda a: a[0], auxm))
        zero_met = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), met_abs)

        zero_dp_step = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), params)

        if stash_residuals:
            # The vjp pullback is a Partial — a pytree. Abstract-trace
            # it once for the (static) treedef + residual shapes; the
            # treedef is microbatch-invariant (tracing is shape-based;
            # the dropout key's VALUE lives in the stashed leaves, so
            # the right fwd-tick masks reach the backward).
            vjp_abs = jax.eval_shape(
                lambda p, xx: jax.vjp(with_key(jnp.int32(0)), p, xx)[1],
                params, xm[0])
            res_treedef = jax.tree_util.tree_structure(vjp_abs)
            abs_leaves = jax.tree_util.tree_leaves(vjp_abs)
            # Ring-buffer only the leaves that actually vary per
            # microbatch. The rest — the stage weights and their
            # compute-dtype casts, which jax.vjp captures as transpose
            # operands — are a pure function of params: compute them
            # ONCE per step instead of storing D copies (at GPT-scale
            # stages the weight copies dominated the stash's HBM
            # traffic and made it lose to recompute, PARITY.md).
            res_mask = variant_residual_mask(
                lambda p, xx, m: jax.tree_util.tree_leaves(
                    jax.vjp(with_key(m), p, xx)[1]),
                params, xm[0])
            if all(res_mask):
                const_leaves = []
            else:
                # x enters as zeros; every computation feeding only the
                # discarded variant outputs is dead code XLA removes,
                # so this costs the casts, not a stage forward.
                res0 = jax.vjp(with_key(jnp.int32(0)), params,
                               jnp.zeros_like(xm[0]))[1]
                _, const_leaves = split_by_mask(
                    jax.tree_util.tree_leaves(res0), res_mask)
            variant_abs, _ = split_by_mask(abs_leaves, res_mask)
            stash0 = tuple(
                jnp.zeros((D,) + l.shape, l.dtype) for l in variant_abs)
        else:
            stash0 = jnp.zeros((D,) + xm[0].shape, xm.dtype)

        def tick(carry, t):
            (fwd_msg, bwd_msg, stash, dp_acc, dlast_acc, dx_buf,
             val_acc, met_acc, aux_acc) = carry

            # ---- forward half: stage s runs microbatch t - s.
            # REAL branch (lax.cond), not where-masking: a ramp/drain
            # tick whose forward slot is a bubble SKIPS the stage
            # compute instead of computing on garbage and masking the
            # result — the 2(S-1)-tick bubble costs half the naive
            # predicated schedule's wall clock.
            mf = t - s
            mf_valid = jnp.logical_and(mf >= 0, mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            inp = jnp.where(
                s == 0,
                jax.lax.dynamic_index_in_dim(xm, mf_c, 0, keepdims=False),
                fwd_msg)

            def fwd_run(inp, stash):
                slot = jnp.mod(mf_c, D)
                if stash_residuals:
                    (y, aux_v), vjp_fn = jax.vjp(with_key(mf_c), params,
                                                 inp)
                    # strict: a residual-structure drift between the
                    # eval_shape template and this trace must fail
                    # loudly, not silently stash stale zeros.
                    vleaves, _ = split_by_mask(
                        jax.tree_util.tree_leaves(vjp_fn), res_mask)
                    stash = tuple(
                        jax.lax.dynamic_update_index_in_dim(sb, l, slot, 0)
                        for sb, l in zip(stash, vleaves, strict=True))
                    return y, aux_v, stash
                y, aux_v = with_key(mf_c)(params, inp)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, inp, slot, 0)
                return y, aux_v, stash

            def fwd_skip(inp, stash):
                return jnp.zeros_like(inp), zero_aux, stash

            if bubble == "cond":
                y, aux_v, stash = jax.lax.cond(mf_valid, fwd_run,
                                               fwd_skip, inp, stash)
            else:
                # "where": run unconditionally (collectives inside the
                # stage execute on every rank), select the results.
                y_r, aux_r, stash_r = fwd_run(inp, stash)
                y = _select_tree(mf_valid, y_r, jnp.zeros_like(inp))
                aux_v = _select_tree(mf_valid, aux_r, zero_aux)
                stash = _select_tree(mf_valid, stash_r, stash)
            # Skipped slots contribute exact zeros — plain adds suffice.
            aux_acc = jax.tree_util.tree_map(
                lambda a, b: a + b, aux_acc, aux_v)

            # ---- last-stage loss + cotangent seed for the SAME tick's
            # backward. Branch on (is_last AND valid): non-last stages
            # no longer pay the head's vocab matmul every tick.
            take_head = jnp.logical_and(is_last, mf_valid)

            def head_run(y):
                return head(mf_c, y)

            def head_skip(y):
                return (jnp.zeros((), jnp.float32), zero_met,
                        zero_dlast, jnp.zeros_like(y))

            if bubble == "cond":
                hval, hmet, hdlast, hdy = jax.lax.cond(
                    take_head, head_run, head_skip, y)
            else:
                hval, hmet, hdlast, hdy = _select_tree(
                    take_head, head_run(y),
                    (jnp.zeros((), jnp.float32), zero_met, zero_dlast,
                     jnp.zeros_like(y)))
            val_acc = val_acc + hval
            met_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(a.dtype), met_acc, hmet)
            dlast_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(a.dtype), dlast_acc, hdlast)

            # ---- backward half: stage s runs microbatch t-2(S-1)+s,
            # same real-branch treatment.
            mbk = t - 2 * (S - 1) + s
            b_valid = jnp.logical_and(mbk >= 0, mbk < M)
            mb_c = jnp.clip(mbk, 0, M - 1)

            def bwd_run(stash, hdy, bwd_msg):
                slot = jnp.mod(mb_c, D)
                cot = jnp.where(is_last, hdy, bwd_msg)
                if stash_residuals:
                    stashed = [
                        jax.lax.dynamic_index_in_dim(sb, slot, 0,
                                                     keepdims=False)
                        for sb in stash]
                    vjp_fn = jax.tree_util.tree_unflatten(
                        res_treedef,
                        merge_by_mask(stashed, const_leaves, res_mask))
                    return vjp_fn((cot.astype(xm.dtype), aux_seed))
                x_saved = jax.lax.dynamic_index_in_dim(
                    stash, slot, 0, keepdims=False)
                _, vjp_fn = jax.vjp(with_key(mb_c), params, x_saved)
                return vjp_fn((cot.astype(x_saved.dtype), aux_seed))

            def bwd_skip(stash, hdy, bwd_msg):
                return zero_dp_step, jnp.zeros_like(xm[0])

            if bubble == "cond":
                dp, dx = jax.lax.cond(b_valid, bwd_run, bwd_skip,
                                      stash, hdy, bwd_msg)
            else:
                dp, dx = _select_tree(
                    b_valid, bwd_run(stash, hdy, bwd_msg),
                    (zero_dp_step, jnp.zeros_like(xm[0])))
            dp_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(a.dtype), dp_acc, dp)
            take_dx = jnp.logical_and(b_valid, s == 0)
            prev_dx = jax.lax.dynamic_index_in_dim(dx_buf, mb_c, 0,
                                                   keepdims=False)
            dx_buf = jax.lax.dynamic_update_index_in_dim(
                dx_buf, jnp.where(take_dx, dx.astype(dx_buf.dtype),
                                  prev_dx), mb_c, 0)

            # ---- ring hops: activations down, cotangents up.
            if S > 1:
                fwd_msg = jax.lax.ppermute(y, AXIS_PIPE, down)
                bwd_msg = jax.lax.ppermute(dx, AXIS_PIPE, up)
            return (fwd_msg, bwd_msg, stash, dp_acc, dlast_acc, dx_buf,
                    val_acc, met_acc, aux_acc), None

        zero_x = jnp.zeros_like(xm[0])
        carry0 = (zero_x, zero_x, stash0,
                  zero_dp, zero_dlast,
                  jnp.zeros((M,) + xm[0].shape, x.dtype),
                  jnp.zeros((), jnp.float32), zero_met, zero_aux)
        T = M + 2 * (S - 1)
        (_, _, _, dp_acc, dlast_acc, dx_buf, val_acc, met_acc,
         aux_acc), _ = jax.lax.scan(tick, carry0, jnp.arange(T))

        # Only the owning stage holds real values for dlast (last
        # stage), dx/val/metrics (stage 0 / last) — everyone else holds
        # zeros, so a pipe-psum replicates the true values. Stage aux is
        # real on EVERY stage; its psum is the total over stages.
        dlast_acc = jax.lax.psum(dlast_acc, AXIS_PIPE)
        dx_out = jax.lax.psum(dx_buf, AXIS_PIPE).reshape(B, *x.shape[1:])
        val_acc = jax.lax.psum(val_acc, AXIS_PIPE)
        met_acc = jax.lax.psum(met_acc, AXIS_PIPE)
        aux_out = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, AXIS_PIPE), aux_acc)
        dp_out = jax.tree_util.tree_map(lambda g: g[None], dp_acc)
        return dp_out, dlast_acc, dx_out, val_acc, met_acc, aux_out

    dp, dlast, dx, val, met, aux_sums = jax.shard_map(
        per_pipe, mesh=mesh, axis_names={AXIS_PIPE},
        in_specs=(P(AXIS_PIPE), P(), P(), P(), P()),
        out_specs=(P(AXIS_PIPE), P(), P(), P(), P(), P()),
        check_vma=False)(stage_params, last_params, x, aux,
                         cotangent_scale)
    if stage_aux_cotangent is not None:
        return val, met, aux_sums, (dp, dlast, dx)
    return val, met, (dp, dlast, dx)


def interleaved_pipeline_value_and_grad(
        stage_fn: Callable[..., jax.Array],
        last_fn: Callable[[Any, jax.Array, Any], tuple],
        stage_params: Any, last_params: Any,
        x: jax.Array, aux: Any, mesh: Mesh,
        num_microbatches: int, virtual_stages: int, rng: Any = None,
        cotangent_scale: Any = 1.0, stage_aux_cotangent: Any = None,
        bubble: str = "cond"):
    """Interleaved (virtual-stage) 1F1B: Megatron's chunked layout.

    Each device owns V model CHUNKS instead of one contiguous stage:
    virtual stage j = v*S + s (chunk v on device s) holds layers
    [j*lps, (j+1)*lps) with lps = L/(S*V) — stage_params leaves are
    [S, V, lps, ...] (stack_stage_params with ``virtual``). A
    microbatch crosses the ring V times; because consecutive virtual
    stages j, j+1 sit on consecutive devices (j+1 lives on
    (s+1) mod S), every hop is still the one-position-down ppermute —
    the V in-flight activations ride as ONE stacked [V, ...] message,
    and the ring wrap (device S-1 -> 0) shifts chunk slot v -> v+1
    (``jnp.roll`` on the chunk dim, device-0 side).

    Schedule: the uniform one-chunk-fwd + one-chunk-bwd-per-slot tick
    over T = M + 2(S*V - 1) ticks; at tick t virtual stage j runs
    forward for microbatch t - j and backward for t - 2(S*V-1) + j,
    each slot a real ``lax.cond`` (the V slots per device are
    compile-time unrolled — V is small and static). The loss head
    fires at j = S*V - 1 (chunk V-1, device S-1), seeding the same
    tick's backward exactly like the plain schedule. Utilization of
    this uniform tick form is MV/(MV + 2(SV-1)) — STRICTLY WORSE than
    plain 1F1B's M/(M + 2(S-1)) for V > 1 (bubble_fraction's analysis,
    measured assumptions unchanged); what V buys in Megatron is the
    asymmetric fwd-burst warmup this lockstep scan cannot express.
    This implementation exists for CORRECTNESS of the [S, V, lps]
    regrouping — schedule-level wins stay an explicitly-owed
    measurement (PARITY.md). Backward is "recompute" only (the stash
    variant's per-chunk residual treedefs are a follow-up; recompute
    is the measured-on-chip default).

    Same contract as pipeline_value_and_grad otherwise (including the
    ``bubble`` cond/where predication switch — "where" when the stage
    carries seq collectives); d_stage_params comes back
    [S, V, lps, ...] like stage_params.
    """
    S = mesh.shape[AXIS_PIPE]
    V = virtual_stages
    Sv = S * V
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if M < Sv:
        raise ValueError(f"need microbatches >= virtual stages "
                         f"({M} < {Sv} = {S} stages x {V} chunks)")
    if bubble not in ("cond", "where"):
        raise ValueError(f"bubble {bubble!r}; have ('cond', 'where')")
    mb = B // M
    D = min(2 * Sv, M)  # stash depth per chunk >= max in-flight

    def per_pipe(params, last_p, x, aux, scale):
        params = jax.tree_util.tree_map(lambda p: p[0], params)  # [V,...]
        s = jax.lax.axis_index(AXIS_PIPE)
        xm = x.reshape(M, mb, *x.shape[1:])
        auxm = jax.tree_util.tree_map(
            lambda a: a.reshape(M, mb, *a.shape[1:]), aux)
        down = [(i, (i + 1) % S) for i in range(S)]
        up = [((i + 1) % S, i) for i in range(S)]
        is_last = s == S - 1

        aux_on = stage_aux_cotangent is not None

        def chunk_params(v):
            return jax.tree_util.tree_map(lambda p: p[v], params)

        def with_key(v, m):
            # Keys fold over (microbatch, VIRTUAL stage) so no two
            # (mb, chunk) pairs share dropout masks; at V=1 the virtual
            # index j = s matches the plain schedule's fold exactly.
            if rng is None:
                fn = lambda p, xx: stage_fn(p, xx)  # noqa: E731
            else:
                j = v * S + s
                key = jax.random.fold_in(jax.random.fold_in(rng, m), j)
                fn = lambda p, xx: stage_fn(p, xx, key)  # noqa: E731
            return fn if aux_on else (lambda p, xx: (fn(p, xx), ()))

        def head(m, y):
            aux_mb = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, m, 0, keepdims=False), auxm)
            val, vjp_fn, met = jax.vjp(
                lambda lp, yy: last_fn(lp, yy, aux_mb), last_p, y,
                has_aux=True)
            dlast, dy = vjp_fn(jnp.asarray(scale, val.dtype))
            return val, met, dlast, dy

        zero_dp = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        zero_dlast = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), last_p)
        if aux_on:
            aux_abs = jax.eval_shape(
                lambda: with_key(0, 0)(chunk_params(0), xm[0])[1])
            zero_aux = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, a.dtype), aux_abs)
            aux_seed = jax.tree_util.tree_map(
                lambda w, a: jnp.asarray(w, a.dtype),
                stage_aux_cotangent, zero_aux)
        else:
            zero_aux, aux_seed = (), ()
        met_abs = jax.eval_shape(
            lambda lp, yy, am: last_fn(lp, yy, am)[1], last_p, xm[0],
            jax.tree_util.tree_map(lambda a: a[0], auxm))
        zero_met = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), met_abs)

        def tick(carry, t):
            (fwd_msgs, bwd_msgs, stash, dp_acc, dlast_acc, dx_buf,
             val_acc, met_acc, aux_acc) = carry

            # ---- forward slots: chunk v runs microbatch t - (v*S+s).
            y_stack = jnp.zeros_like(fwd_msgs)
            head_dy = jnp.zeros_like(xm[0])
            for v in range(V):
                mf = t - (v * S + s)
                mf_valid = jnp.logical_and(mf >= 0, mf < M)
                mf_c = jnp.clip(mf, 0, M - 1)
                # Virtual stage 0 (chunk 0, device 0) ingests fresh
                # microbatches; every other virtual stage eats the
                # message its predecessor pushed last tick.
                inp = fwd_msgs[v]
                if v == 0:
                    feed = jax.lax.dynamic_index_in_dim(
                        xm, mf_c, 0, keepdims=False)
                    inp = jnp.where(s == 0, feed, inp)
                cp = chunk_params(v)

                def fwd_run(inp, stash, v=v, mf_c=mf_c, cp=cp):
                    slot = jnp.mod(mf_c, D)
                    y, aux_v = with_key(v, mf_c)(cp, inp)
                    st = jax.lax.dynamic_update_index_in_dim(
                        stash[v], inp, slot, 0)
                    return y, aux_v, st

                def fwd_skip(inp, stash, v=v):
                    return jnp.zeros_like(inp), zero_aux, stash[v]

                if bubble == "cond":
                    y, aux_v, st_v = jax.lax.cond(mf_valid, fwd_run,
                                                  fwd_skip, inp, stash)
                else:
                    y_r, aux_r, st_r = fwd_run(inp, stash)
                    y = _select_tree(mf_valid, y_r,
                                     jnp.zeros_like(inp))
                    aux_v = _select_tree(mf_valid, aux_r, zero_aux)
                    st_v = _select_tree(mf_valid, st_r, stash[v])
                stash = stash.at[v].set(st_v)
                y_stack = y_stack.at[v].set(y)
                aux_acc = jax.tree_util.tree_map(
                    lambda a, b: a + b, aux_acc, aux_v)

                if v == V - 1:
                    # Loss head at the final virtual stage; its dy
                    # seeds the SAME tick's chunk-(V-1) backward.
                    take_head = jnp.logical_and(is_last, mf_valid)

                    def head_run(y, mf_c=mf_c):
                        return head(mf_c, y)

                    def head_skip(y):
                        return (jnp.zeros((), jnp.float32), zero_met,
                                zero_dlast, jnp.zeros_like(y))

                    if bubble == "cond":
                        hval, hmet, hdlast, hdy = jax.lax.cond(
                            take_head, head_run, head_skip, y)
                    else:
                        hval, hmet, hdlast, hdy = _select_tree(
                            take_head, head_run(y),
                            (jnp.zeros((), jnp.float32), zero_met,
                             zero_dlast, jnp.zeros_like(y)))
                    val_acc = val_acc + hval
                    met_acc = jax.tree_util.tree_map(
                        lambda a, b: a + b.astype(a.dtype), met_acc,
                        hmet)
                    dlast_acc = jax.tree_util.tree_map(
                        lambda a, b: a + b.astype(a.dtype), dlast_acc,
                        hdlast)
                    head_dy = hdy

            # ---- backward slots: chunk v runs t - 2(Sv-1) + (v*S+s).
            dx_stack = jnp.zeros_like(bwd_msgs)
            for v in range(V):
                j = v * S + s
                mbk = t - 2 * (Sv - 1) + j
                b_valid = jnp.logical_and(mbk >= 0, mbk < M)
                mb_c = jnp.clip(mbk, 0, M - 1)
                cot_in = bwd_msgs[v]
                if v == V - 1:
                    cot_in = jnp.where(is_last, head_dy, cot_in)
                cp = chunk_params(v)

                def bwd_run(stash, cot, v=v, mb_c=mb_c, cp=cp):
                    slot = jnp.mod(mb_c, D)
                    x_saved = jax.lax.dynamic_index_in_dim(
                        stash[v], slot, 0, keepdims=False)
                    _, vjp_fn = jax.vjp(with_key(v, mb_c), cp, x_saved)
                    return vjp_fn((cot.astype(x_saved.dtype), aux_seed))

                def bwd_skip(stash, cot, v=v):
                    return (jax.tree_util.tree_map(
                        lambda p: jnp.zeros(p.shape, p.dtype),
                        chunk_params(v)), jnp.zeros_like(xm[0]))

                if bubble == "cond":
                    dp, dx = jax.lax.cond(b_valid, bwd_run, bwd_skip,
                                          stash, cot_in)
                else:
                    dp_r, dx_r = bwd_run(stash, cot_in)
                    dp = _select_tree(
                        b_valid, dp_r,
                        jax.tree_util.tree_map(jnp.zeros_like, dp_r))
                    dx = _select_tree(b_valid, dx_r,
                                      jnp.zeros_like(xm[0]))
                dp_acc = jax.tree_util.tree_map(
                    lambda a, b, v=v: a.at[v].add(b.astype(a.dtype)),
                    dp_acc, dp)
                dx_stack = dx_stack.at[v].set(dx)
                if v == 0:
                    take_dx = jnp.logical_and(b_valid, s == 0)
                    prev_dx = jax.lax.dynamic_index_in_dim(
                        dx_buf, mb_c, 0, keepdims=False)
                    dx_buf = jax.lax.dynamic_update_index_in_dim(
                        dx_buf, jnp.where(take_dx,
                                          dx.astype(dx_buf.dtype),
                                          prev_dx), mb_c, 0)

            # ---- ring hops: the stacked activations go down, the
            # stacked cotangents up; the wrap shifts chunk slots
            # (j -> j+1 crosses S-1 -> 0 into the NEXT chunk; the
            # reverse for cotangents).
            if S > 1:
                recv = jax.lax.ppermute(y_stack, AXIS_PIPE, down)
                fwd_msgs = jnp.where(s == 0, jnp.roll(recv, 1, axis=0),
                                     recv)
                recv_up = jax.lax.ppermute(dx_stack, AXIS_PIPE, up)
                bwd_msgs = jnp.where(s == S - 1,
                                     jnp.roll(recv_up, -1, axis=0),
                                     recv_up)
            else:
                # S == 1: every hop is the intra-device chunk handoff.
                fwd_msgs = jnp.roll(y_stack, 1, axis=0)
                bwd_msgs = jnp.roll(dx_stack, -1, axis=0)
            return (fwd_msgs, bwd_msgs, stash, dp_acc, dlast_acc,
                    dx_buf, val_acc, met_acc, aux_acc), None

        zero_msgs = jnp.zeros((V,) + xm[0].shape, xm.dtype)
        stash0 = jnp.zeros((V, D) + xm[0].shape, xm.dtype)
        carry0 = (zero_msgs, zero_msgs, stash0, zero_dp, zero_dlast,
                  jnp.zeros((M,) + xm[0].shape, x.dtype),
                  jnp.zeros((), jnp.float32), zero_met, zero_aux)
        T = M + 2 * (Sv - 1)
        (_, _, _, dp_acc, dlast_acc, dx_buf, val_acc, met_acc,
         aux_acc), _ = jax.lax.scan(tick, carry0, jnp.arange(T))

        dlast_acc = jax.lax.psum(dlast_acc, AXIS_PIPE)
        dx_out = jax.lax.psum(dx_buf, AXIS_PIPE).reshape(B, *x.shape[1:])
        val_acc = jax.lax.psum(val_acc, AXIS_PIPE)
        met_acc = jax.lax.psum(met_acc, AXIS_PIPE)
        aux_out = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, AXIS_PIPE), aux_acc)
        dp_out = jax.tree_util.tree_map(lambda g: g[None], dp_acc)
        return dp_out, dlast_acc, dx_out, val_acc, met_acc, aux_out

    dp, dlast, dx, val, met, aux_sums = jax.shard_map(
        per_pipe, mesh=mesh, axis_names={AXIS_PIPE},
        in_specs=(P(AXIS_PIPE), P(), P(), P(), P()),
        out_specs=(P(AXIS_PIPE), P(), P(), P(), P(), P()),
        check_vma=False)(stage_params, last_params, x, aux,
                         cotangent_scale)
    if stage_aux_cotangent is not None:
        return val, met, aux_sums, (dp, dlast, dx)
    return val, met, (dp, dlast, dx)


def stack_stage_params(layer_params: Any, num_stages: int,
                       virtual: int = 1) -> Any:
    """[n_layers, ...] stacked layer params -> stage-major grouping.

    ``virtual == 1``: [S, layers_per_stage, ...] — stage s owns layers
    [s*Lps, (s+1)*Lps). ``virtual > 1`` (interleaved 1F1B): [S, V,
    Lps, ...] — virtual stage j = v*S + s owns layers [j*Lps,
    (j+1)*Lps), i.e. device s holds V non-contiguous depth chunks
    (Megatron's interleaved assignment). The v-major-in-j order makes
    the [S*V] -> [V, S] reshape direct; the transpose puts the
    device-sharded S dim first."""
    def regroup(p):
        n = p.shape[0]
        if n % (num_stages * virtual):
            raise ValueError(
                f"{n} layers not divisible by {num_stages} stages"
                + (f" x {virtual} virtual chunks" if virtual > 1
                   else ""))
        lps = n // (num_stages * virtual)
        if virtual == 1:
            return p.reshape(num_stages, lps, *p.shape[1:])
        return p.reshape(virtual, num_stages, lps,
                         *p.shape[1:]).swapaxes(0, 1)
    return jax.tree_util.tree_map(regroup, layer_params)
