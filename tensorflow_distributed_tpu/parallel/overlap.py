"""Overlap-aware gradient sync: bucketed reduce-scatter / all-gather.

The implicit SPMD train step (train/step.py) pays gradient aggregation
as one GSPMD-inserted allreduce after the backward pass — a serial
communication tail the device sits idle behind. This module restates
the weight update the way "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv 2004.13336, PAPERS.md)
prescribes, with every collective written out by hand so it is
censusable (analysis/jaxprcheck) and schedulable:

1. the grad pytree is partitioned into deterministic, size-bounded,
   dtype-keyed **buckets** (:func:`plan_buckets` — the ladder idea of
   serve's prefill buckets applied to gradient leaves);
2. each bucket is **reduce-scattered** (``lax.psum_scatter``) over the
   "data" axis as one fused collective. Because each bucket depends
   only on its own leaves' backward contributions, XLA's latency-hiding
   scheduler is free to start a bucket's reduce-scatter while the
   backward pass for earlier layers is still computing — the collective
   hides under compute instead of trailing it;
3. the optimizer update runs **sharded** (ZeRO-1): each device updates
   only its 1/N slice of every bucket, against optimizer slots that
   live permanently sharded over "data" (``param_partition=zero1``'s
   exact layout — ``parallel.sharding.fsdp_scatter_dim`` is the shared
   dim rule, so the scattered gradient block lands on the device that
   already holds the matching m/v block);
4. updated params are **all-gathered** back per bucket (again fused,
   again free to interleave), restoring the replicated layout the next
   forward expects. Slots are never gathered — they stay sharded.

Numerics: the serial and overlap formulations are BIT-IDENTICAL —
psum_scatter + all_gather compute the same per-element sums as the
pmean they replace, and the elementwise optimizer math is blocking-
invariant (pinned by tests/test_overlap.py, including the
``skip_nonfinite`` discarded-step path, Adam slots, and EMA).

Leaves too small to shard (below ``fsdp_min_size``, or with no dim
divisible by the axis — the same threshold ZeRO-1 slot placement uses)
ride replicated psum buckets and take a full local update, exactly as
they do under plain zero1.

Builders:
- :func:`make_explicit_train_step` — the full-featured step
  (``grad_sync="overlap"`` / ``"serial"`` / ``"unsynced"``), reached
  from the CLI as ``--grad-sync`` via train/step.py's dispatch.
  "serial" is the A/B baseline: same shard_map skeleton, one monolithic
  pmean, full-tree replicated update — the serial psum tail, made
  explicit. "unsynced" drops the collectives entirely (WRONG math; it
  exists only as benchmarks/gradsync.py's compute floor for the
  exposed-communication estimate).
- :func:`plan_buckets` / :func:`comm_bytes_per_step` — the partition
  and its per-device traffic estimate (observe surfaces the
  exposed-vs-hidden split from it).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.observe import health as observe_health
from tensorflow_distributed_tpu.parallel.mesh import AXIS_DATA
from tensorflow_distributed_tpu.parallel.sharding import (
    FSDP_MIN_SIZE, fsdp_scatter_dim, path_key)
from tensorflow_distributed_tpu.train.state import TrainState, ema_update
from tensorflow_distributed_tpu.train.step import (
    Batch, LossFn, Metrics, _pop_taps, default_batch_shardings, loss_fn)
from tensorflow_distributed_tpu.utils import prng

GRAD_SYNC_MODES = ("serial", "overlap", "unsynced")

#: Default bucket bound. ~4 MB keeps a GPT-2-small grad tree (~500 MB
#: f32) in ~100 collectives — large enough to amortize collective
#: launch latency, small enough that the first reduce-scatter can
#: start long before the backward pass finishes.
DEFAULT_BUCKET_BYTES = 4 << 20


# --- bucket planning (deterministic; shapes only) -----------------------

@dataclasses.dataclass(frozen=True)
class LeafPlan:
    """One grad leaf's place in the sync plan."""

    index: int                 # position in jax tree-flatten order
    path: Tuple[str, ...]      # param path (diagnostics / module attribution)
    shape: Tuple[int, ...]
    dtype: str
    scatter_dim: int           # -1 = replicated psum path
    size: int = 0              # elements (host-computed at plan time)
    nbytes: int = 0


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The full partition: scatter buckets (reduce-scatter + sharded
    update + all-gather) and replicated buckets (fused psum + full
    local update)."""

    axis_size: int
    bucket_bytes: int
    scatter: Tuple[Tuple[LeafPlan, ...], ...]
    replicated: Tuple[Tuple[LeafPlan, ...], ...]
    n_leaves: int

    @property
    def scatter_bytes(self) -> int:
        return sum(lp.nbytes for b in self.scatter for lp in b)

    @property
    def replicated_bytes(self) -> int:
        return sum(lp.nbytes for b in self.replicated for lp in b)

    def describe(self) -> dict:
        """Serializable summary (bench artifacts, plan records)."""
        return {
            "axis_size": self.axis_size,
            "bucket_bytes": self.bucket_bytes,
            "scatter_buckets": len(self.scatter),
            "replicated_buckets": len(self.replicated),
            "scatter_bytes": self.scatter_bytes,
            "replicated_bytes": self.replicated_bytes,
            "leaves": self.n_leaves,
        }


def plan_buckets(params: Any, axis_size: int,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 fsdp_min_size: int = FSDP_MIN_SIZE) -> BucketPlan:
    """Partition a param/grad pytree into size-bounded buckets.

    Deterministic by construction: leaves are visited in jax
    tree-flatten order and greedily packed into the current bucket for
    their (scatterable?, dtype) key; a bucket closes when adding the
    next leaf would exceed ``bucket_bytes`` (a single leaf larger than
    the bound gets its own bucket). Dtype-keyed because a fused
    collective is one array — mixed dtypes can't concatenate.

    A leaf is scatterable when it meets the SAME rule ZeRO-1 slot
    placement applies (``parallel.sharding``): total size >=
    ``fsdp_min_size`` and some dim divisible by ``axis_size`` (the
    largest such dim, ``fsdp_scatter_dim``). Everything else is
    replicated: psum'd fused, updated in full on every device.
    """
    if axis_size < 1:
        raise ValueError(f"axis_size must be >= 1, got {axis_size}")
    if bucket_bytes < 1:
        raise ValueError(f"bucket_bytes must be >= 1, got {bucket_bytes}")
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    leaves: List[LeafPlan] = []
    for i, (path, leaf) in enumerate(flat):
        shape = tuple(int(s) for s in getattr(leaf, "shape", ()))
        dtype = np.dtype(getattr(leaf, "dtype", np.float32)).name
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        dim = -1
        if axis_size > 1 and size >= fsdp_min_size:
            dim = fsdp_scatter_dim(shape, axis_size)
        leaves.append(LeafPlan(
            index=i, path=path_key(path), shape=shape, dtype=dtype,
            scatter_dim=dim, size=size,
            nbytes=size * np.dtype(dtype).itemsize))

    open_buckets: dict = {}   # (scatterable, dtype) -> (leaves, bytes)
    scatter: List[Tuple[LeafPlan, ...]] = []
    replicated: List[Tuple[LeafPlan, ...]] = []

    def close(key):
        group, _ = open_buckets.pop(key)
        (scatter if key[0] else replicated).append(tuple(group))

    for lp in leaves:
        key = (lp.scatter_dim >= 0, lp.dtype)
        group, nbytes = open_buckets.get(key, ([], 0))
        if group and nbytes + lp.nbytes > bucket_bytes:
            close(key)
            group, nbytes = [], 0
        group.append(lp)
        open_buckets[key] = (group, nbytes + lp.nbytes)
    # Close in deterministic key order (open_buckets insertion order
    # follows leaf order, which is already deterministic).
    for key in list(open_buckets):
        close(key)
    return BucketPlan(axis_size=axis_size, bucket_bytes=bucket_bytes,
                      scatter=tuple(scatter), replicated=tuple(replicated),
                      n_leaves=len(leaves))


def comm_bytes_per_step(plan: BucketPlan) -> float:
    """Estimated per-device collective traffic of ONE overlap step:
    reduce-scatter of every grad bucket + all-gather of every updated
    param bucket (ring cost: each moves (N-1)/N of the full tree per
    device), plus the allreduce (2x ring) of the replicated leaves.
    The serial psum pays the same total — the overlap win is hiding
    it, not shrinking it; observe uses this as the comm term of the
    exposed-vs-hidden estimate."""
    n = plan.axis_size
    if n <= 1:
        return 0.0
    ring = (n - 1) / n
    return (2.0 * ring * plan.scatter_bytes
            + 2.0 * ring * plan.replicated_bytes)


# --- block layout helpers -----------------------------------------------
#
# Canonical forms for a scatterable leaf of shape S with scatter dim d
# over an axis of size N:
#   rows:  [N, size/N]  — moveaxis(d, 0) then reshape; row i flattened
#          is device i's block. What psum_scatter consumes (fused along
#          columns) and all_gather produces.
#   block: S with S[d]/N at position d — the per-device shard in
#          ORIGINAL dim order, i.e. exactly the slot shard a zero1
#          NamedSharding (P with "data" at d) hands shard_map.

def _leaf_to_rows(x: jax.Array, dim: int, n: int) -> jax.Array:
    return jnp.moveaxis(x, dim, 0).reshape(n, -1)


def _moved_shape(lp: LeafPlan) -> Tuple[int, ...]:
    """lp.shape with the scatter dim moved to the front (what
    moveaxis(d, 0) produces — remaining dims keep relative order)."""
    s, d = lp.shape, lp.scatter_dim
    return (s[d],) + s[:d] + s[d + 1:]


def _rows_to_leaf(rows: jax.Array, lp: LeafPlan, n: int) -> jax.Array:
    x = rows.reshape(_moved_shape(lp))
    return jnp.moveaxis(x, 0, lp.scatter_dim)


def _flat_to_block(flat: jax.Array, lp: LeafPlan, n: int) -> jax.Array:
    moved = _moved_shape(lp)
    block_moved = (moved[0] // n,) + moved[1:]
    return jnp.moveaxis(flat.reshape(block_moved), 0, lp.scatter_dim)


def _block_to_flat(block: jax.Array, lp: LeafPlan) -> jax.Array:
    return jnp.moveaxis(block, lp.scatter_dim, 0).reshape(-1)


def _block_slice(full: jax.Array, lp: LeafPlan, n: int,
                 idx: jax.Array) -> jax.Array:
    """This device's block of a REPLICATED full leaf (local read)."""
    blk = lp.shape[lp.scatter_dim] // n
    return jax.lax.dynamic_slice_in_dim(full, idx * blk, blk,
                                        axis=lp.scatter_dim)


# --- the sync engines (traced context, inside shard_map) ----------------

def _sync_overlap(grads: Any, plan: BucketPlan) -> Any:
    """Bucketed reduce-scatter: returns the grad tree with scatterable
    leaves replaced by this device's mean-reduced BLOCK and replicated
    leaves by the full mean (fused psums)."""
    n = plan.axis_size
    flat, treedef = jax.tree_util.tree_flatten(grads)
    out: List[Any] = list(flat)
    for bucket in plan.scatter:
        rows = [_leaf_to_rows(flat[lp.index], lp.scatter_dim, n)
                for lp in bucket]
        fused = rows[0] if len(rows) == 1 else jnp.concatenate(rows,
                                                               axis=1)
        shard = jax.lax.psum_scatter(fused, AXIS_DATA,
                                     scatter_dimension=0,
                                     tiled=False) / n
        off = 0
        for lp in bucket:
            k = lp.size // n
            out[lp.index] = _flat_to_block(
                jax.lax.slice_in_dim(shard, off, off + k), lp, n)
            off += k
    for bucket in plan.replicated:
        fused = (flat[bucket[0].index].reshape(-1)
                 if len(bucket) == 1 else jnp.concatenate(
                     [flat[lp.index].reshape(-1) for lp in bucket]))
        red = jax.lax.psum(fused, AXIS_DATA) / n
        off = 0
        for lp in bucket:
            out[lp.index] = jax.lax.slice_in_dim(
                red, off, off + lp.size).reshape(lp.shape)
            off += lp.size
    return jax.tree_util.tree_unflatten(treedef, out)


def _gather_params(new_blocks: Any, plan: BucketPlan) -> Any:
    """Bucketed all-gather of updated param blocks back to full
    (replicated) leaves; replicated leaves pass through."""
    n = plan.axis_size
    flat, treedef = jax.tree_util.tree_flatten(new_blocks)
    out: List[Any] = list(flat)
    for bucket in plan.scatter:
        fused = (_block_to_flat(flat[bucket[0].index], bucket[0])
                 if len(bucket) == 1 else jnp.concatenate(
                     [_block_to_flat(flat[lp.index], lp)
                      for lp in bucket]))
        rows = jax.lax.all_gather(fused, AXIS_DATA, axis=0, tiled=False)
        off = 0
        for lp in bucket:
            k = lp.size // n
            out[lp.index] = _rows_to_leaf(
                jax.lax.slice_in_dim(rows, off, off + k, axis=1), lp, n)
            off += k
    return jax.tree_util.tree_unflatten(treedef, out)


def _shard_params(params: Any, plan: BucketPlan) -> Any:
    """Per-device param view matching the scattered grads: blocks for
    scatterable leaves (local slices of the replicated full arrays),
    full leaves otherwise."""
    n = plan.axis_size
    idx = jax.lax.axis_index(AXIS_DATA)
    flat, treedef = jax.tree_util.tree_flatten(params)
    out = list(flat)
    for bucket in plan.scatter:
        for lp in bucket:
            out[lp.index] = _block_slice(flat[lp.index], lp, n, idx)
    return jax.tree_util.tree_unflatten(treedef, out)


def _sharded_sq_norms(tree: Any, plan: BucketPlan,
                      by_module: bool = False):
    """Per-tree (or per-top-level-module) sum-of-squares split into the
    part that needs a psum (block leaves — each device holds 1/N) and
    the part that doesn't (replicated leaves). Caller psums the first
    and adds the second."""
    flat = jax.tree_util.tree_flatten(tree)[0]
    scatter_idx = {lp.index for b in plan.scatter for lp in b}
    modules: dict = {}
    lps = sorted((lp for b in plan.scatter for lp in b),
                 key=lambda lp: lp.index) + sorted(
        (lp for b in plan.replicated for lp in b),
        key=lambda lp: lp.index)
    for lp in lps:
        mod = lp.path[0] if (by_module and lp.path) else ""
        sc, rep = modules.get(mod, (jnp.zeros((), jnp.float32),
                                    jnp.zeros((), jnp.float32)))
        sq = jnp.sum(jnp.square(flat[lp.index].astype(jnp.float32)))
        if lp.index in scatter_idx:
            sc = sc + sq
        else:
            rep = rep + sq
        modules[mod] = (sc, rep)
    return modules


def _global_grad_norm(shard_grads: Any, plan: BucketPlan) -> jax.Array:
    """The TRUE global gradient norm from the sharded view: one scalar
    psum over the block contributions (device blocks partition each
    leaf, so the psum'd sum-of-squares is exact) plus the replicated
    leaves' local sum."""
    (sc, rep), = _sharded_sq_norms(shard_grads, plan).values()
    return jnp.sqrt(jax.lax.psum(sc, AXIS_DATA) + rep)


def _clip_tree(tree: Any, g_norm: jax.Array, max_norm: float) -> Any:
    """Clip-by-global-norm with a CALLER-supplied norm — optax's exact
    elementwise semantics (`lax.select` on `g_norm < max_norm`, scale
    by `max_norm / g_norm` otherwise), detached from optax's own
    `global_norm` so both explicit grad-sync modes can feed the SAME
    psum-reconstructed scalar:

    - overlap: the norm comes from the scattered blocks
      (:func:`_global_grad_norm` — psum of block sums-of-squares);
    - serial: the norm comes from the SAME formulation applied to the
      pmean'd full tree's local block slices (``_shard_params``), so
      the scalar — and therefore the clipped update — is bit-identical
      to overlap's, which is what lets the serial-vs-overlap identity
      gate keep running under clip (tests/test_overlap.py).

    The chain clip in train/optim.py is correspondingly OMITTED for
    explicit grad-sync runs: inside the shard_map tx sees grad BLOCKS,
    and a chain clip would use each device's local norm."""
    trigger = g_norm < max_norm

    def clip_leaf(t):
        return jax.lax.select(
            jnp.broadcast_to(trigger, t.shape), t,
            (t / g_norm.astype(t.dtype)) * jnp.asarray(
                max_norm, t.dtype))

    return jax.tree_util.tree_map(clip_leaf, tree)


def _sharded_health(params: Any, shard_grads: Any, shard_updates: Any,
                    plan: BucketPlan, step: jax.Array,
                    health_every: int) -> dict:
    """observe.health's per-module vitals from the SHARDED grad/update
    view: block sum-of-squares are combined across devices with ONE
    fused psum of a small stacked vector (grads + updates per module),
    params are replicated so their norms are local. Same keys and emit
    flag as observe_health.stats; unlike the implicit step's variant
    the reductions run unconditionally (a collective inside a
    lax.cond branch is scheduling trouble) — the blocks are 1/N-sized,
    so the per-step cost is the sharded update's own order."""
    g_mods = _sharded_sq_norms(shard_grads, plan, by_module=True)
    u_mods = _sharded_sq_norms(shard_updates, plan, by_module=True)
    names = sorted(g_mods)
    stacked = jnp.stack([g_mods[m][0] for m in names]
                        + [u_mods[m][0] for m in names])
    stacked = jax.lax.psum(stacked, AXIS_DATA)
    out: dict = {}
    import math
    p_flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for i, m in enumerate(names):
        g = jnp.sqrt(stacked[i] + g_mods[m][1])
        u = jnp.sqrt(stacked[len(names) + i] + u_mods[m][1])
        leaves = [leaf for path, leaf in p_flat
                  if (path_key(path)[0] if path_key(path) else "") == m]
        p = optax.global_norm(leaves).astype(jnp.float32)
        size = sum(x.size for x in leaves)
        key = m or "params"
        out[f"{observe_health.PREFIX}{key}/grad_norm"] = g
        out[f"{observe_health.PREFIX}{key}/update_ratio"] = (
            u / (p + 1e-12))
        out[f"{observe_health.PREFIX}{key}/param_rms"] = (
            p / math.sqrt(max(size, 1)))
    emit = ((step + 1) % health_every) == 0
    out[observe_health.EMIT_KEY] = emit.astype(jnp.float32)
    return out


# --- the step builder ---------------------------------------------------

def make_explicit_train_step(mesh: Mesh, state_template: TrainState,
                             seed: int = 0, loss: LossFn = loss_fn,
                             batch_shardings: Any = None,
                             grad_sync: str = "overlap",
                             bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                             fsdp_min_size: int = FSDP_MIN_SIZE,
                             donate: bool = True,
                             grad_norm_metric: bool = False,
                             ema_decay: float = 0.0,
                             params_out_shardings: Any = None,
                             skip_nonfinite: bool = False,
                             health_every: int = 0,
                             grad_clip_norm: float = 0.0,
                             jit: bool = True
                             ) -> Callable[[TrainState, Batch],
                                           Tuple[TrainState, Metrics]]:
    """Build the explicit-collective train step for a pure-data mesh.

    ``state_template`` pins the state pytree (and, for "overlap", the
    zero1 slot shardings the per-bucket update runs against — pass the
    state the loop will actually thread through, created with
    ``opt_fsdp=True`` and the SAME ``fsdp_min_size``; an abstract
    ``ShapeDtypeStruct`` state from train.state.abstract_train_state
    works too, which is how the auto-layout planner scores this
    strategy without allocating).

    Per-shard semantics (shared with parallel.collectives'
    ``make_shardmap_train_step`` and documented there): the loss is the
    mean over each device's LOCAL shard and the synced gradient the
    mean of per-shard means — identical to the global mean for
    uniformly-weighted losses, a slight reweighting for masked losses
    with unequal per-shard mask counts (the grad_accum_steps caveat,
    verbatim); dropout draws an independent stream per data shard;
    BatchNorm models normalize with local per-shard stats.

    The optimizer must be ELEMENTWISE for "overlap" (adam/adamw/sgd —
    each element's update depends only on that element's grad/slots,
    so a block computes exactly the full update's slice); adafactor's
    factored second moments are not, and config.validate rejects the
    combination. ``skip_nonfinite`` / EMA / ``params_out_shardings`` /
    ``health_every`` compose exactly as in train.step — skip selects
    on the full param view and the slot blocks, EMA tracks the
    gathered params, health reads the sharded grads/updates through
    psum-reconstructed full-tree norms.

    ``grad_clip_norm`` > 0 clips by the TRUE global norm before the
    elementwise update, reconstructed from block sums-of-squares with
    one scalar psum — the identical formulation in both modes, so
    serial+clip and overlap+clip stay bit-equal (see
    :func:`_clip_tree`; the optax chain clip is omitted for explicit
    grad-sync runs by train/optim.py — pass the UNCLIPPED tx here).
    """
    if grad_sync not in GRAD_SYNC_MODES:
        raise ValueError(f"unknown grad_sync {grad_sync!r}; have "
                         f"{GRAD_SYNC_MODES}")
    axis_size = mesh.shape[AXIS_DATA]
    nondata = {a: int(s) for a, s in mesh.shape.items()
               if a != AXIS_DATA and int(s) > 1}
    if nondata:
        raise ValueError(
            f"explicit grad-sync needs a pure data mesh; axes "
            f"{nondata} > 1 (tensor/seq/pipe/expert params are managed "
            f"by GSPMD or shard_map schedules the explicit formulation "
            f"doesn't reproduce)")
    if grad_sync == "overlap" and axis_size < 2:
        raise ValueError(
            "grad_sync=overlap reduce-scatters over the data axis; "
            f"data={axis_size} leaves nothing to scatter — use the "
            "implicit step on a single data shard")
    if batch_shardings is None:
        batch_shardings = default_batch_shardings(mesh)
    plan = plan_buckets(state_template.params, axis_size,
                        bucket_bytes=bucket_bytes,
                        fsdp_min_size=fsdp_min_size)

    state_specs = jax.tree_util.tree_map(
        lambda a: a.sharding.spec, state_template)
    batch_specs = jax.tree_util.tree_map(
        lambda s: s.spec, batch_shardings)

    def per_shard(state: TrainState, batch: Batch):
        dkey = prng.step_key(seed, state.step)
        # Independent dropout stream per data shard (the precedent and
        # the caveat live in parallel.collectives' docstring).
        dkey = jax.random.fold_in(dkey, jax.lax.axis_index(AXIS_DATA))
        grad_fn = jax.value_and_grad(
            partial(loss, state.apply_fn), has_aux=True)
        (_, (metrics, new_extra)), grads = grad_fn(
            state.params, state.extra, batch, dkey, True)
        metrics, new_extra = _pop_taps(metrics, new_extra)
        metrics = jax.lax.pmean(metrics, AXIS_DATA)
        new_extra = jax.lax.pmean(new_extra, AXIS_DATA)

        if grad_sync == "overlap":
            shard_grads = _sync_overlap(grads, plan)
            shard_params = _shard_params(state.params, plan)
            norm = None
            if grad_clip_norm or grad_norm_metric or skip_nonfinite:
                norm = _global_grad_norm(shard_grads, plan)
            if grad_norm_metric:
                metrics = dict(metrics, grad_norm=norm)
            ok = None
            if skip_nonfinite:
                ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(norm)
                metrics = dict(metrics,
                               skipped_nonfinite=jnp.where(ok, 0.0, 1.0))
            if grad_clip_norm:
                # Clip by the psum-reconstructed TRUE global norm
                # before the elementwise update (the chain clip is
                # omitted for explicit grad-sync — train/optim.py).
                # Pre-clip norm feeds the metric and the skip flag,
                # matching the implicit step's semantics.
                shard_grads = _clip_tree(shard_grads, norm,
                                         grad_clip_norm)
            # The ZeRO-1 sharded update: slots arrive as blocks (their
            # persisted sharding IS the in_spec), params as local
            # slices, grads as scattered blocks. Elementwise optimizer
            # math makes each block exactly the full update's slice.
            updates, new_opt = state.tx.update(
                shard_grads, state.opt_state, shard_params)
            if health_every:
                metrics = dict(metrics, **_sharded_health(
                    state.params, shard_grads, updates, plan,
                    state.step, health_every))
                metrics = observe_health.gate(
                    metrics, metrics[observe_health.EMIT_KEY] > 0)
            new_blocks = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), shard_params,
                updates)
            new_params = _gather_params(new_blocks, plan)
        else:
            if grad_sync == "serial":
                # THE serial psum tail, written out: one monolithic
                # mean-allreduce, then every device repeats the full
                # update.
                grads = jax.lax.pmean(grads, AXIS_DATA)
            norm = None
            if grad_clip_norm:
                # The SAME block-partitioned reconstruction overlap
                # uses (this device's local slices of the full tree →
                # block sums-of-squares → one psum), NOT
                # optax.global_norm: the scalar is bit-identical to
                # the overlap path's, so clipped serial and clipped
                # overlap stay bit-equal — the identity gate's
                # requirement.
                norm = _global_grad_norm(_shard_params(grads, plan),
                                         plan)
            if grad_norm_metric:
                metrics = dict(metrics,
                               grad_norm=(norm if norm is not None
                                          else optax.global_norm(grads)))
            ok = None
            if skip_nonfinite:
                skip_norm = (norm if norm is not None
                             else optax.global_norm(grads))
                ok = (jnp.isfinite(metrics["loss"])
                      & jnp.isfinite(skip_norm))
                metrics = dict(metrics,
                               skipped_nonfinite=jnp.where(ok, 0.0, 1.0))
            if grad_clip_norm:
                grads = _clip_tree(grads, norm, grad_clip_norm)
            updates, new_opt = state.tx.update(
                grads, state.opt_state, state.params)
            if health_every:
                metrics = dict(metrics, **observe_health.stats(
                    state.params, grads, updates, state.step,
                    health_every))
                metrics = observe_health.gate(
                    metrics, metrics[observe_health.EMIT_KEY] > 0)
            new_params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), state.params,
                updates)

        if ok is not None:
            # Discard the whole update on a non-finite step — the
            # train.step contract, applied to the full param view and
            # the per-device slot blocks alike (where is elementwise;
            # the old blocks are exactly the in_spec'd state views).
            def keep_old(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(ok, a, b), new, old)

            new_params = keep_old(new_params, state.params)
            new_opt = keep_old(new_opt, state.opt_state)
            new_extra = keep_old(new_extra, state.extra)
        new_ema = state.ema
        if ema_decay and state.ema is not None:
            new_ema = ema_update(state.ema, new_params, ema_decay,
                                 state.step)
            if ok is not None:
                new_ema = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(ok, a, b), new_ema,
                    state.ema)
        new_state = state.replace(step=state.step + 1,
                                  params=new_params, opt_state=new_opt,
                                  extra=new_extra, ema=new_ema)
        return new_state, metrics

    shmapped = jax.shard_map(per_shard, mesh=mesh,
                             in_specs=(state_specs, batch_specs),
                             out_specs=(state_specs, P()),
                             check_vma=False)

    def step(state: TrainState, batch: Batch):
        new_state, metrics = shmapped(state, batch)
        if params_out_shardings is not None:
            # The zero1 invariant from train.step: pin the gathered
            # params back to their state-creation layout so GSPMD
            # never propagates a stray sharding into later steps.
            new_params = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_state.params,
                params_out_shardings)
            new_state = new_state.replace(params=new_params)
        return new_state, metrics

    # The built step carries its own plan so callers (train/loop's
    # grad_sync record) read the EXACT partition the compiled program
    # executes instead of re-deriving it.
    if not jit:
        step.bucket_plan = plan
        return step
    with mesh:
        wrapped = observe_device.instrument_jit(
            f"train_step_{grad_sync}", step,
            in_shardings=(None, batch_shardings),
            donate_argnums=(0,) if donate else (),
        )
    wrapped.bucket_plan = plan
    return wrapped
