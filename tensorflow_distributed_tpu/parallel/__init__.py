"""Parallelism substrate: mesh bootstrap, sharding rules, collectives.

Replaces the reference's entire distribution/coordination layer
(ClusterSpec + tf.train.Server + replica_device_setter +
SyncReplicasOptimizer + Supervisor, mnist_python_m.py:146-282) with
mesh construction + sharding annotations; XLA's SPMD partitioner inserts
the collectives.
"""

from tensorflow_distributed_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    bootstrap,
    is_chief,
    make_mesh,
)
from tensorflow_distributed_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    param_sharding,
    replicated,
    shard_batch,
)
