"""Explicit collective formulations of gradient sync.

Three formulations of the same synchronous data-parallel semantics, used
to *prove* and to *measure* what `train.step` does implicitly:

1. ``make_shardmap_train_step`` — the reference's
   ``SyncReplicasOptimizer`` (mnist_python_m.py:210-233, SURVEY.md N5)
   re-expressed the TPU way: each data shard computes grads, one
   ``lax.pmean`` over the "data" axis is the entire sync protocol (no
   accumulators, token queues, or chief thread). Tests assert it is
   numerically identical to the implicit-jit step *with dropout
   disabled and no BatchNorm*; with dropout on, this formulation draws
   an independent mask per data shard (fold_in by axis_index, like the
   reference's workers' independent draws) while the implicit-jit step
   draws one mask over the global batch — same distribution, different
   streams. BatchNorm models likewise normalize with local per-shard
   stats here vs global-batch stats in the jit step (see NOTE inline).

2. ``ps_style_grad_sync`` — an honest emulation of the reference's
   parameter-server topology for the BASELINE.json latency A/B: per-shard
   grads leave the device mesh to a single host "ps" (numpy), are
   averaged there, and re-broadcast — weights and gradients crossing the
   host boundary every step exactly as they crossed TCP in the reference
   (2x full pull + 2x full push per step, SURVEY.md §5 "communication
   backend").

3. ``allreduce_latency_probe`` — times a bare psum of grad-sized buffers
   over ICI, the number the "allreduce vs ps grad-sync latency" metric
   compares against.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import AXIS_DATA
from tensorflow_distributed_tpu.train.state import TrainState
from tensorflow_distributed_tpu.train.step import loss_fn
from tensorflow_distributed_tpu.utils import prng


def make_shardmap_train_step(mesh: Mesh, seed: int = 0):
    """Train step with the gradient psum written out by hand.

    Semantics parity with the reference's sync mode, term by term:
    - ``replicas_to_aggregate == mesh data-axis size`` by construction
      (the reference required exactly N-of-N too: :216-219 with both
      flags defaulting to num_workers).
    - gradient aggregation is a mean (``lax.pmean``), matching the
      ConditionalAccumulator's take_grad mean.
    - one optimizer apply per aggregate, then step += 1 — the
      reference's ps-side ApplyAdam + global_step bump.
    """
    data_size = mesh.shape[AXIS_DATA]

    def per_shard(state: TrainState, images, labels):
        dkey = prng.step_key(seed, state.step)
        # Distinct dropout stream per data shard (the reference's workers
        # likewise had independent dropout draws).
        dkey = jax.random.fold_in(dkey, jax.lax.axis_index(AXIS_DATA))
        grad_fn = jax.value_and_grad(
            partial(loss_fn, state.apply_fn), has_aux=True)
        (_, (metrics, new_extra)), grads = grad_fn(
            state.params, state.extra, (images, labels), dkey, True)
        # THE sync protocol: one mean-allreduce over ICI. NOTE on
        # BatchNorm models: normalization here uses LOCAL per-shard
        # batch stats (torch-DDP-without-SyncBN semantics), and the
        # running stats are the mean of the per-shard updates — NOT
        # bitwise the jit step's global-batch (sync-BN) stats. The
        # numerical-parity contract with the jit step therefore holds
        # for stat-free models only; BN models agree in expectation.
        grads = jax.lax.pmean(grads, AXIS_DATA)
        metrics = jax.lax.pmean(metrics, AXIS_DATA)
        new_extra = jax.lax.pmean(new_extra, AXIS_DATA)
        updates, new_opt = state.tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), state.params, updates)
        return state.replace(step=state.step + 1, params=new_params,
                             opt_state=new_opt, extra=new_extra), metrics

    state_specs = P()  # params/opt-state replicated across data shards
    shmapped = jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(state_specs, P(AXIS_DATA), P(AXIS_DATA)),
        out_specs=(state_specs, state_specs),
        check_vma=False)

    with mesh:
        return jax.jit(lambda state, batch: shmapped(state, batch[0], batch[1]))


def make_per_shard_grads(mesh: Mesh, seed: int = 0):
    """Jitted per-shard gradient computation with NO cross-shard sync —
    the 'workers computed, nothing aggregated yet' intermediate the ps
    emulation needs. Returns grads stacked along a leading shard axis."""

    def per_shard(state: TrainState, images, labels):
        dkey = prng.step_key(seed, state.step)
        dkey = jax.random.fold_in(dkey, jax.lax.axis_index(AXIS_DATA))
        grad_fn = jax.grad(
            lambda p, b: loss_fn(state.apply_fn, p, state.extra, b,
                                 dkey, True)[0])
        grads = grad_fn(state.params, (images, labels))
        return jax.tree_util.tree_map(lambda g: g[None], grads)

    return jax.jit(jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P(AXIS_DATA), P(AXIS_DATA)),
        out_specs=P(AXIS_DATA),
        check_vma=False))


def _ps_round_trip(mesh: Mesh, stacked_grads: Any) -> Any:
    """One full ps round-trip on per-shard-stacked grads: device -> host
    numpy (the gradient "push", mnist_python_m.py:222 / N4's Send),
    numpy mean (the ps accumulator take_grad), device_put of the
    averaged grads to every device (the weight "pull")."""
    host_grads = jax.tree_util.tree_map(np.asarray, stacked_grads)
    mean_grads = jax.tree_util.tree_map(
        lambda g: g.mean(axis=0), host_grads)
    device_grads = jax.tree_util.tree_map(
        lambda g: jax.device_put(g, NamedSharding(mesh, P())), mean_grads)
    # Same dependent-scalar readback the allreduce probe uses: on
    # tunneled runtimes block_until_ready alone can return before the
    # pull lands, which would undertime the ps side of the A/B.
    jax.block_until_ready(device_grads)
    leaf = jax.tree_util.tree_leaves(device_grads)[0]
    float(jax.device_get(jax.numpy.ravel(leaf)[0]))
    return device_grads


def ps_style_grad_sync(mesh: Mesh, seed: int = 0):
    """The reference's star topology, emulated honestly on TPU hosts.

    Used only by the latency A/B benchmark — this is the baseline the
    psum path beats.
    """
    grad_step = make_per_shard_grads(mesh, seed)

    def sync(state: TrainState, batch) -> Tuple[Any, float]:
        t0 = time.perf_counter()
        stacked = grad_step(state, batch[0], batch[1])
        device_grads = _ps_round_trip(mesh, stacked)
        return device_grads, time.perf_counter() - t0

    return sync


def ps_style_sync_probe(mesh: Mesh, stacked_grads: Any) -> Callable[[], float]:
    """Time ONLY the sync portion of the ps emulation — the apples-to-
    apples counterpart of ``allreduce_latency_probe``.

    Input is a per-shard-stacked grads pytree already resident on the
    mesh (what ``make_per_shard_grads`` produces). One probe call is one
    full ps round-trip (``_ps_round_trip``): device->host pull of every
    shard's gradients (the reference's 2x full gradient push over TCP,
    SURVEY.md §5), host-side numpy mean (the ConditionalAccumulator
    take_grad, mnist_python_m.py:216-219), and device_put of the
    averaged result to every device (the weight pull). Grad
    *computation* is excluded from the timed span, exactly as it is in
    the allreduce probe.

    jax.Array caches its host copy after the first ``np.asarray``, which
    would let every timed iteration after the first skip the
    device->host transfer entirely; each probe call therefore first
    materializes FRESH device arrays (an untimed on-device identity op)
    so the pull is genuinely paid every time.
    """
    refresh = jax.jit(partial(jax.tree_util.tree_map, lambda g: g + 0))

    def probe() -> float:
        fresh = refresh(stacked_grads)
        # Same honest barrier as the allreduce probe: make sure the
        # refresh op has truly finished before t0, or its execution
        # would be charged to the timed ps round-trip.
        leaf = jax.tree_util.tree_leaves(fresh)[0]
        float(jax.device_get(jax.numpy.ravel(leaf)[0]))
        t0 = time.perf_counter()
        _ps_round_trip(mesh, fresh)
        return time.perf_counter() - t0

    return probe


def allreduce_latency_probe(mesh: Mesh, grads_like: Any) -> Callable[[], float]:
    """Time one psum-mean over the data axis for grad-shaped buffers.

    The returned probe is WARM: one untimed dispatch (with the same
    dependent-scalar readback the timed path uses) runs here, so the
    first timed call measures the collective, not trace+compile wall.
    For a usable communication floor (the overlap A/B's baseline,
    benchmarks/gradsync.py) take :func:`min_latency` over several
    calls — the minimum is the schedulable cost; the median carries
    host scheduling noise.
    """
    psum = jax.jit(
        jax.shard_map(
            lambda t: jax.lax.pmean(t, AXIS_DATA), mesh=mesh,
            in_specs=P(), out_specs=P(), check_vma=False))

    def probe() -> float:
        t0 = time.perf_counter()
        out = psum(grads_like)
        # Host readback of a dependent scalar: on tunneled TPU runtimes
        # block_until_ready can return before remote execution finishes,
        # which would make this probe dishonestly fast vs the ps side
        # (whose device_get is a real barrier).
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(jax.device_get(jax.numpy.ravel(leaf)[0]))
        return time.perf_counter() - t0

    # Warm-up dispatch: psum compile wall must never leak into the
    # first timed sample (it used to — the probe was unusable as a
    # comm floor until its caller happened to add its own warmup).
    warm = psum(grads_like)
    leaf = jax.tree_util.tree_leaves(warm)[0]
    float(jax.device_get(jax.numpy.ravel(leaf)[0]))
    return probe


def min_latency(probe: Callable[[], float], iters: int = 10) -> float:
    """Min-of-N of a latency probe, in seconds: the schedulable cost
    of the operation, robust to host scheduling noise — what the
    gradsync A/B reports as the communication floor."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    return min(probe() for _ in range(iters))
