"""Ring attention: sequence-parallel exact attention over the mesh.

The reference has no sequence models at all (SURVEY.md §5 "long-context:
absent" — its inputs are fixed 784-px images), but long-context is
first-class in this framework: attention over sequences sharded across
the "seq" mesh axis, computed exactly (not approximated) by rotating
key/value blocks around the ring with ``lax.ppermute`` while queries
stay resident.

Method (blockwise streaming softmax, flash-attention style):
each device holds Q,K,V for its L/S-token block. For S ring steps it
computes partial attention of its Q block against the currently-held
K,V block, folds the result into a running (max, sum, weighted-value)
accumulator in f32, and passes the K,V block to the next device on the
ring. After S steps every Q block has attended to every K,V block —
total comms = each K,V block traverses the ring once over ICI, overlap-
friendly, and no device ever materializes the full [L, L] score matrix
or the full K,V.

Per-shard compute stays MXU-shaped: the inner op is a batched matmul
[B*H, L/S, D] x [B*H, D, L/S]. bf16 matmuls, f32 softmax statistics.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL, AXIS_SEQ


_MASK = -1e30  # large-finite additive mask (matches ops.flash_attention)


def _block_attend(q, k, v, bias):
    """One Q-block vs one K,V-block partial attention.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; bias: [B, Lq, Lk] or None.
    Returns (scores_max [B,H,Lq], exp-sum [B,H,Lq], weighted-V
    [B,Lq,H,D]) — the streaming-softmax partials, all f32.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)))
    if bias is not None:
        s = s + bias[:, None, :, :]
    # Clamp the row max away from the mask value so a fully-masked row
    # (a skipped causal ring block) yields p == exp(-huge) == 0 and a
    # zero l contribution, instead of exp(0) == 1 garbage.
    m = jnp.maximum(jnp.max(s, axis=-1), 0.1 * _MASK)  # [B,H,Lq]
    p = jnp.exp(s - m[..., None])                # [B,H,Lq,Lk]
    l = jnp.sum(p, axis=-1)                      # [B,H,Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return m, l, o


def _merge(m1, l1, o1, m2, l2, o2):
    """Fold two streaming-softmax partials into one."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = (o1 * a1.transpose(0, 2, 1)[..., None]
         + o2 * a2.transpose(0, 2, 1)[..., None])
    return m, l, o


def causal_bias(Lq: int, Lk: int) -> jax.Array:
    """[1, Lq, Lk] additive causal mask — the ONE construction shared by
    the ring path, the flash-attention dispatcher, and the test oracles
    (keep the mask constant in a single place)."""
    return jnp.triu(jnp.full((Lq, Lk), _MASK, jnp.float32), k=1)[None]


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mask: Optional[jax.Array] = None) -> jax.Array:
    """Plain exact attention (the mesh.seq == 1 path and the test
    oracle). q,k,v: [B, L, H, D]; mask: [B, L, L] additive or None.
    A fully-masked query row returns zeros (not NaN)."""
    m, l, o = _block_attend(q, k, v, mask)
    l_safe = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, mask: Optional[jax.Array] = None,
                   causal: bool = False) -> jax.Array:
    """Exact attention with the sequence axis sharded over mesh "seq".

    q,k,v are GLOBAL [B, L, H, D] arrays (call under jit; the seq axis
    carries the "seq" sharding). ``causal=True`` applies the
    autoregressive mask across the ring: at ring step s, device i holds
    the K,V block of device (i - s) mod S, so the in-block bias is built
    from the global row/col offsets i*L_loc and src*L_loc; blocks
    entirely in the future are fully masked and contribute a zero
    partial (see the clamp in _block_attend). Every device still visits
    every block — ~2x the minimal causal FLOPs; a load-balanced zigzag
    schedule is a profiling-driven follow-up. Arbitrary ``mask`` is not
    supported with S > 1 ring steps.

    Degenerate 1-shard ring: identical to full_attention.
    """
    seq_size = mesh.shape[AXIS_SEQ]
    if seq_size == 1:
        if causal:
            cmask = causal_bias(q.shape[1], k.shape[1])
            mask = cmask if mask is None else mask + cmask
        return full_attention(q, k, v, mask)
    if mask is not None:
        raise NotImplementedError(
            "arbitrary masks don't survive the ring rotation; only "
            "causal=True is supported with a sharded seq axis")

    spec = P(AXIS_DATA, AXIS_SEQ, AXIS_MODEL, None)

    def per_shard(q_blk, k_blk, v_blk):
        # q_blk etc: [B/dp, L/S, H/tp, D] local blocks.
        i = jax.lax.axis_index(AXIS_SEQ)
        l_loc = q_blk.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (l_loc, l_loc), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (l_loc, l_loc), 1)

        def bias_for(src):
            if not causal:
                return None
            allowed = (i * l_loc + rows) >= (src * l_loc + cols)
            return jnp.where(allowed, 0.0, _MASK)[None]  # [1, Lq, Lk]

        m, l, o = _block_attend(q_blk, k_blk, v_blk, bias_for(i))
        k_rot, v_rot = k_blk, v_blk
        perm = [(d, (d + 1) % seq_size) for d in range(seq_size)]
        for s in range(1, seq_size):
            k_rot = jax.lax.ppermute(k_rot, AXIS_SEQ, perm)
            v_rot = jax.lax.ppermute(v_rot, AXIS_SEQ, perm)
            src = (i - s) % seq_size
            m2, l2, o2 = _block_attend(q_blk, k_rot, v_rot, bias_for(src))
            m, l, o = _merge(m, l, o, m2, l2, o2)
        out = o / l.transpose(0, 2, 1)[..., None]
        return out.astype(q_blk.dtype)

    return jax.shard_map(per_shard, mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
