"""Ring attention: sequence-parallel exact attention over the mesh.

The reference has no sequence models at all (SURVEY.md §5 "long-context:
absent" — its inputs are fixed 784-px images), but long-context is
first-class in this framework: attention over sequences sharded across
the "seq" mesh axis, computed exactly (not approximated) by rotating
key/value blocks around the ring with ``lax.ppermute`` while queries
stay resident.

Method (blockwise streaming softmax, flash-attention style):
each device holds Q,K,V for its L/S-token block. For S ring steps it
computes partial attention of its Q block against the currently-held
K,V block, folds the result into a running (max, sum, weighted-value)
accumulator in f32, and passes the K,V block to the next device on the
ring. After S steps every Q block has attended to every K,V block —
total comms = each K,V block traverses the ring once over ICI, overlap-
friendly, and no device ever materializes the full [L, L] score matrix
or the full K,V.

Per-shard compute stays MXU-shaped: the inner op is a batched matmul
[B*H, L/S, D] x [B*H, D, L/S]. bf16 matmuls, f32 softmax statistics.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL, AXIS_SEQ


_MASK = -1e30  # large-finite additive mask (matches ops.flash_attention)


def _block_attend(q, k, v, bias):
    """One Q-block vs one K,V-block partial attention.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; bias: [B, Lq, Lk] or None.
    Returns (scores_max [B,H,Lq], exp-sum [B,H,Lq], weighted-V
    [B,Lq,H,D]) — the streaming-softmax partials, all f32.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)))
    if bias is not None:
        s = s + bias[:, None, :, :]
    # Clamp the row max away from the mask value so a fully-masked row
    # (a skipped causal ring block) yields p == exp(-huge) == 0 and a
    # zero l contribution, instead of exp(0) == 1 garbage.
    m = jnp.maximum(jnp.max(s, axis=-1), 0.1 * _MASK)  # [B,H,Lq]
    p = jnp.exp(s - m[..., None])                # [B,H,Lq,Lk]
    l = jnp.sum(p, axis=-1)                      # [B,H,Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return m, l, o


def _partial_attend(q, k, v, causal: bool = False):
    """Block partial attention for the zigzag ring: the Pallas
    partial-softmax kernel (ops.flash_attention.flash_attention_partial)
    on TPU when shapes allow, the einsum oracle otherwise — the ring's
    local compute rides the flash kernel's VMEM streaming instead of
    materializing [B, H, Lq, Lk] f32 score blocks in HBM.
    TFD_FLASH_INTERPRET=1 forces the kernel (interpreter) off-TPU so
    the CPU-mesh tests exercise the exact TPU code path."""
    from tensorflow_distributed_tpu.ops.flash_attention import (
        flash_attention_partial, use_flash)
    B, Lq, H, D = q.shape
    if use_flash(Lq, k.shape[1], D):
        return flash_attention_partial(q, k, v, causal=causal)
    bias = causal_bias(Lq, k.shape[1]) if causal else None
    return _block_attend(q, k, v, bias)


def _merge(m1, l1, o1, m2, l2, o2):
    """Fold two streaming-softmax partials into one."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = (o1 * a1.transpose(0, 2, 1)[..., None]
         + o2 * a2.transpose(0, 2, 1)[..., None])
    return m, l, o


def causal_bias(Lq: int, Lk: int) -> jax.Array:
    """[1, Lq, Lk] additive causal mask — the ONE construction shared by
    the ring path, the flash-attention dispatcher, and the test oracles
    (keep the mask constant in a single place)."""
    return jnp.triu(jnp.full((Lq, Lk), _MASK, jnp.float32), k=1)[None]


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mask: Optional[jax.Array] = None) -> jax.Array:
    """Plain exact attention (the mesh.seq == 1 path and the test
    oracle). q,k,v: [B, L, H, D]; mask: [B, L, L] additive or None.
    A fully-masked query row returns zeros (not NaN)."""
    m, l, o = _block_attend(q, k, v, mask)
    l_safe = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _naive_shard(seq_size: int, causal: bool):
    """Contiguous-block ring: every device visits every K,V block; for
    causal, future blocks are fully masked and contribute zero partials
    (the clamp in _block_attend) — correct but ~2x the minimal causal
    FLOPs and imbalanced (device S-1 is busy every step)."""

    def per_shard(q_blk, k_blk, v_blk, ids):
        # q_blk etc: [B/dp, L/S, H/tp, D] local blocks. ids: [1], this
        # device's ring position (the seq-sharded iota ring_attention
        # threads in — NOT lax.axis_index, whose residual re-lowers
        # with every axis manual under AD inside a nested shard_map
        # and trips the sdy verifier; see ring_attention).
        i = ids[0]
        l_loc = q_blk.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (l_loc, l_loc), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (l_loc, l_loc), 1)

        def bias_for(src):
            if not causal:
                return None
            allowed = (i * l_loc + rows) >= (src * l_loc + cols)
            return jnp.where(allowed, 0.0, _MASK)[None]  # [1, Lq, Lk]

        m, l, o = _block_attend(q_blk, k_blk, v_blk, bias_for(i))
        k_rot, v_rot = k_blk, v_blk
        perm = [(d, (d + 1) % seq_size) for d in range(seq_size)]
        for s in range(1, seq_size):
            k_rot = jax.lax.ppermute(k_rot, AXIS_SEQ, perm)
            v_rot = jax.lax.ppermute(v_rot, AXIS_SEQ, perm)
            src = (i - s) % seq_size
            m2, l2, o2 = _block_attend(q_blk, k_rot, v_rot, bias_for(src))
            m, l, o = _merge(m, l, o, m2, l2, o2)
        out = o / l.transpose(0, 2, 1)[..., None]
        return out.astype(q_blk.dtype)

    return per_shard


def _zigzag_causal_shard(S: int):
    """Load-balanced causal ring (the zigzag schedule).

    Layout: split the global sequence into 2S half-blocks h_0..h_{2S-1}
    (size nh = L/(2S)); zigzag device d owns the pair {h_d, h_{2S-1-d}}
    — one early half, one mirrored late half. With that pairing, at
    every ring step s > 0 each device does EXACTLY two unmasked
    half-attends (its late half always attends the rotated early K
    half; its early or late half attends the other rotated half
    depending on sign(src - d)) — so causal work is ~half the naive
    schedule's FLOPs AND every device is equally busy; the ring step
    time is no longer set by the last device. Step s = 0 adds the two
    triangular diagonal blocks. Total per device: 2S + 1 half-attends
    vs the naive 4S (measured 2.6x wall-clock on the 8-way CPU mesh at
    L=8192 — the naive path also paid softmax on masked garbage, so
    the win exceeds the 2x FLOP model; the committed single-chip
    attention-path numbers live in LMBENCH_r03.json at the repo root).

    The model's activations stay CONTIGUOUSLY seq-sharded everywhere
    else, so the conversion contiguous -> zigzag (and back for the
    output) happens here, as two half-block ppermutes each way: the
    maps d -> 2d (early halves) and d -> 2d+1 (late halves), folded
    by 2S-1-g reflection into device space, are permutations of the
    ring. Comms per ring step is unchanged (two half K,V pairs == one
    full K,V block); the conversion adds 2 + 2 one-hop permutes total.

    All selection is elementwise jnp.where on same-shape buffers —
    no divergent control flow, SPMD-uniform, MXU-shaped.
    """

    # Static conversion permutations (device d holds contiguous halves
    # h_{2d}, h_{2d+1}; zigzag owner of h_g is g if g < S else 2S-1-g).
    dstA = [2 * d if 2 * d < S else 2 * S - 1 - 2 * d for d in range(S)]
    dstB = [2 * d + 1 if 2 * d + 1 < S else 2 * S - 2 - 2 * d
            for d in range(S)]
    permA = [(d, dstA[d]) for d in range(S)]
    permB = [(d, dstB[d]) for d in range(S)]
    permA_inv = [(dstA[d], d) for d in range(S)]
    permB_inv = [(dstB[d], d) for d in range(S)]

    def to_zigzag(x, e):
        """Local [B, n, H, D] contiguous block -> (g1, g2) halves.
        ``e``: this device's ring position (threaded, not
        lax.axis_index — see _naive_shard's note)."""
        nh = x.shape[1] // 2
        recvA = jax.lax.ppermute(x[:, :nh], AXIS_SEQ, permA)
        recvB = jax.lax.ppermute(x[:, nh:], AXIS_SEQ, permB)
        # Even devices get their early half (g1 = e) via the A route,
        # odd ones via B (see permutation construction above).
        even = (e % 2 == 0)
        g1 = jnp.where(even, recvA, recvB)
        g2 = jnp.where(even, recvB, recvA)
        return g1, g2

    def from_zigzag(o1, o2, e):
        """(g1, g2) outputs -> local contiguous [B, n, H, D] block."""
        even = (e % 2 == 0)
        sendA = jnp.where(even, o1, o2)   # the half that arrived via A
        sendB = jnp.where(even, o2, o1)
        first = jax.lax.ppermute(sendA, AXIS_SEQ, permA_inv)
        second = jax.lax.ppermute(sendB, AXIS_SEQ, permB_inv)
        return jnp.concatenate([first, second], axis=1)

    def per_shard(q_blk, k_blk, v_blk, ids):
        d = ids[0]
        q1, q2 = to_zigzag(q_blk, d)
        k1, k2 = to_zigzag(k_blk, d)
        v1, v2 = to_zigzag(v_blk, d)
        # In-half triangular masking for the two diagonal blocks (global
        # offsets of q and k halves coincide, so offsets cancel) —
        # causal=True in _partial_attend, which dispatches to the Pallas
        # partial kernel on TPU (einsum oracle elsewhere).

        # s = 0: both diagonals (triangular) + late-vs-early (full:
        # q2's rows start at (2S-1-d)*nh >= S*nh, past every k1 col).
        acc1 = _partial_attend(q1, k1, v1, causal=True)
        acc2 = _merge(*_partial_attend(q2, k2, v2, causal=True),
                      *_partial_attend(q2, k1, v1))

        perm = [(i, (i + 1) % S) for i in range(S)]
        k1r, k2r, v1r, v2r = k1, k2, v1, v2
        for s in range(1, S):
            k1r = jax.lax.ppermute(k1r, AXIS_SEQ, perm)
            k2r = jax.lax.ppermute(k2r, AXIS_SEQ, perm)
            v1r = jax.lax.ppermute(v1r, AXIS_SEQ, perm)
            v2r = jax.lax.ppermute(v2r, AXIS_SEQ, perm)
            src = (d - s) % S
            # Always needed: late q vs rotated early k (full).
            acc2 = _merge(*acc2, *_partial_attend(q2, k1r, v1r))
            # Exactly one of {q1 x k1r (src < d), q2 x k2r (src > d)}
            # is needed — both are FULLY visible, so select operands
            # elementwise and attend once; fold into the right
            # accumulator with the same predicate.
            pred = src < d
            q_sel = jnp.where(pred, q1, q2)
            k_sel = jnp.where(pred, k1r, k2r)
            v_sel = jnp.where(pred, v1r, v2r)
            part = _partial_attend(q_sel, k_sel, v_sel)
            new1 = _merge(*acc1, *part)
            new2 = _merge(*acc2, *part)
            acc1 = tuple(jnp.where(pred, a, b) for a, b in zip(new1, acc1))
            acc2 = tuple(jnp.where(pred, b, a) for a, b in zip(new2, acc2))

        def finish(acc):
            m, l, o = acc
            return (o / l.transpose(0, 2, 1)[..., None]).astype(
                q_blk.dtype)

        return from_zigzag(finish(acc1), finish(acc2), d)

    return per_shard


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, mask: Optional[jax.Array] = None,
                   causal: bool = False,
                   schedule: str = "zigzag") -> jax.Array:
    """Exact attention with the sequence axis sharded over mesh "seq".

    q,k,v are GLOBAL [B, L, H, D] arrays (call under jit; the seq axis
    carries the "seq" sharding). ``causal=True`` applies the
    autoregressive mask across the ring; with ``schedule="zigzag"``
    (default) the load-balanced half-block schedule skips the
    fully-masked future blocks (~2x fewer FLOPs, every device equally
    busy — see _zigzag_causal_shard); ``schedule="naive"`` keeps the
    visit-everything formulation (the A/B baseline, and the fallback
    when the local block length is odd). Arbitrary ``mask`` is not
    supported with S > 1 ring steps.

    Degenerate 1-shard ring: identical to full_attention.
    """
    if schedule not in ("zigzag", "naive"):
        raise ValueError(f"ring schedule {schedule!r}; have "
                         "('zigzag', 'naive')")
    seq_size = mesh.shape[AXIS_SEQ]
    if seq_size == 1:
        if causal:
            cmask = causal_bias(q.shape[1], k.shape[1])
            mask = cmask if mask is None else mask + cmask
        return full_attention(q, k, v, mask)
    if mask is not None:
        raise NotImplementedError(
            "arbitrary masks don't survive the ring rotation; only "
            "causal=True is supported with a sharded seq axis")

    spec = P(AXIS_DATA, AXIS_SEQ, AXIS_MODEL, None)
    use_zigzag = (causal and schedule == "zigzag"
                  and (q.shape[1] // seq_size) % 2 == 0)
    per_shard = (_zigzag_causal_shard(seq_size) if use_zigzag
                 else _naive_shard(seq_size, causal))
    # Ring position as a seq-sharded iota ARGUMENT instead of
    # lax.axis_index inside per_shard: under AD, axis_index's
    # device-id arithmetic is re-lowered as a residual computation
    # with EVERY mesh axis manual, which trips the sdy verifier when
    # this shard_map nests inside the pipelined family's pipe-manual
    # region ("operates on axis already bound by a parent") — an
    # argument slice carries the same value through both schedules'
    # AD with no axis reference at all.
    ids = jnp.arange(seq_size, dtype=jnp.int32)
    ctx = jax.sharding.get_abstract_mesh()
    if ctx.manual_axes:
        # Inside an enclosing shard_map (the pipelined family's
        # pipe-manual region): re-manualizing "pipe" is illegal, so
        # nest over exactly the remaining auto axes, against the
        # CONTEXT abstract mesh — the same idiom as the flash
        # dispatcher (ops.flash_attention.attention). The ring's
        # ppermutes name only "seq", which is in the remaining set.
        remaining = set(ctx.axis_names) - set(ctx.manual_axes)
        from jax.sharding import NamedSharding
        ids = jax.lax.with_sharding_constraint(
            ids, NamedSharding(ctx, P(AXIS_SEQ)))
        return jax.shard_map(per_shard, mesh=ctx,
                             in_specs=(spec, spec, spec, P(AXIS_SEQ)),
                             out_specs=spec, axis_names=remaining,
                             check_vma=False)(q, k, v, ids)
    return jax.shard_map(per_shard, mesh=mesh,
                         in_specs=(spec, spec, spec, P(AXIS_SEQ)),
                         out_specs=spec, check_vma=False)(q, k, v, ids)
