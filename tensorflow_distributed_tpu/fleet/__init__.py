"""Fleet serving: a health-aware router + lifecycle controller over N
engine-replica processes (README "Fleet serving").

The serve/ engine is a single process: one scheduler, one slot cache,
one journal. Every resilience mechanism the repo has built — fault
plans, the restart supervisor, journal resume, anomaly detection,
elastic restarts, hot weight swap — protects exactly that one process.
This package is the layer above: a **fleet** that stays within SLO
while individual replicas die, restart, resize, and hot-swap
checkpoints (the source paper's fault-tolerant multi-process serving
claim restated at fleet scale — PAPERS.md 1605.08695, 1811.02084).

- :mod:`fleet.replica` — the per-replica contract: an append-only
  JSONL **inbox** each replica tails for requests and control commands
  (``--serve.inbox``), the per-epoch workspace layout, and the handle
  the router/controller read snapshots and journals through.
- :mod:`fleet.router` — SLO-class-aware dispatch across replicas,
  driven by each replica's ``--observe.export-path`` snapshot
  (occupancy, queue depth, per-class TTFT p95, live anomaly state).
  A replica with an active anomaly or a stale/frozen snapshot is
  QUARANTINED from new admissions and its in-flight requests are
  re-dispatched as journal-style continuations (token-identical by
  greedy determinism — the PR-6 contract); per-dispatch timeout +
  capped-backoff retry; lowest-class load shedding when the whole
  fleet is saturated (shed, never hang).
- :mod:`fleet.controller` — replica lifecycle (spawn/restart with the
  supervisor's leg semantics and capped backoff, drain-before-stop),
  a checkpoint-directory watch, and ROLLING weight swaps: new weights
  reach the fleet one replica at a time via the live ``swap_params``
  path (sha256-verified, EMA-preferred), so serving capacity never
  drops below N-1 during an upgrade; model staleness (steps between
  trained and served weights) is tracked per replica.
- :mod:`fleet.run` — the front-end driver gluing the three together
  (and the ``python -m tensorflow_distributed_tpu.fleet.run`` CLI).

Everything here is host-side policy — stdlib + numpy, no jax — so the
router/controller suites run on fake replicas with a fake clock
(tests/test_fleet.py). benchmarks/fleetbench.py gates the real thing:
a 3-replica CPU fleet under a diurnal trace with a trainer emitting
checkpoints and injected faults (replica SIGKILL, slot NaN, a forced
stale-snapshot window) — goodput, p99 TTFT inside recovery windows,
model staleness, zero lost requests.
"""
