"""Health-aware replica router: SLO-class dispatch over a fleet.

The router owns the fleet-level request lifecycle. Each request is
dispatched to exactly one replica at a time (an inbox append); the
replica's journal is the acknowledgement channel — tokens and
completions are read back from it, so the data plane is crash-durable
by construction and a replica death loses nothing the journal already
holds.

Health is driven entirely by each replica's ``--observe.export-path``
snapshot:

- **liveness**: the snapshot's monotonic ``seq`` must keep advancing;
  a snapshot frozen (or missing) for ``stale_s`` marks the replica
  STALE — indistinguishable from a wedged process, so it is
  quarantined (the ``seq``/``wall_ts``/``pid`` triplet exists exactly
  so a frozen file is distinguishable from a healthy idle replica,
  which keeps exporting).
- **anomaly**: an active detector from ``quarantine_detectors`` in
  the snapshot's live anomaly state (observe/anomaly.py) quarantines
  the replica. The default set is the critical containment signal
  (``slot_nonfinite``); latency-spike detectors are deliberately NOT
  in it — router-induced re-queueing shows up as TTFT spikes, and
  quarantining on them would self-amplify.

A quarantined replica takes no new admissions and its in-flight
requests are re-dispatched to peers as journal-style CONTINUATIONS
(prompt + tokens journaled so far, remaining budget — the PR-6
contract, so greedy determinism keeps the final stream
token-identical); a ``cancel`` command tells the still-running
replica to drop the moved work. When its snapshot freshens and the
anomaly clears, it REJOINS — quarantine is never permanent capacity
loss. Death (the controller's liveness signal) takes the same
evacuation path, minus the cancel.

Every dispatch carries a timeout: no token within
``dispatch_timeout_s`` re-dispatches with capped exponential backoff;
``retry_budget`` exhaustion sheds the request (loudly — shed, never
hang). When every healthy replica is saturated (load >=
``queue_high``), requests that have waited past ``shed_wait_s`` are
shed lowest-class-first, at most one per step — graceful degradation
with a pinned shedding order.

Pure host policy (stdlib + numpy-free), driven by ``step(now)`` from
an external loop with an injectable clock — the whole suite runs on
fake replicas in tests/test_fleet.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: Class rank, best first — mirrors serve.scheduler.SLO_CLASSES
#: (duplicated as a plain tuple so this module stays import-light;
#: parity is pinned in tests/test_fleet.py).
SLO_CLASSES = ("high", "standard", "batch")
_RANK = {c: i for i, c in enumerate(SLO_CLASSES)}


@dataclasses.dataclass
class RouterConfig:
    """Dispatch/health policy knobs (seconds are router-clock)."""

    stale_s: float = 2.0            # frozen-snapshot quarantine bar
    dispatch_timeout_s: float = 20.0  # dispatch -> first token bound
    retry_budget: int = 3           # re-dispatches before shedding
    backoff_base_s: float = 0.25    # retry backoff (capped exp)
    backoff_max_s: float = 2.0
    queue_high: int = 8             # per-replica load = saturated
    shed_wait_s: float = 10.0       # waited past this + saturated -> shed
    quarantine_detectors: Tuple[str, ...] = ("slot_nonfinite",)
    redispatch_on_quarantine: bool = True
    # Anomaly-quarantine decay: the hub's active-anomaly horizon runs
    # on the replica's DECODE-step clock, which freezes once the
    # router stops sending it work — so an idle quarantined replica
    # could never clear. After this cooldown, a fresh snapshot whose
    # anomaly COUNT has not grown since the quarantine rejoins (a
    # replica still firing new anomalies stays out).
    anomaly_cooldown_s: float = 5.0

    def validate(self) -> None:
        if self.stale_s <= 0 or self.dispatch_timeout_s <= 0:
            raise ValueError(
                "router stale_s and dispatch_timeout_s must be > 0")
        if self.retry_budget < 0:
            raise ValueError(
                f"router retry_budget must be >= 0, "
                f"got {self.retry_budget}")
        if self.queue_high < 1:
            raise ValueError(
                f"router queue_high must be >= 1, got {self.queue_high}")


@dataclasses.dataclass
class _Track:
    """One request's fleet-level lifecycle."""

    rid: int
    prompt: List[int]
    max_new: int
    eos: int
    arrival_s: float              # offset from router start
    slo: str = "standard"
    tenant: str = ""
    session: str = ""             # multi-turn conversation id
    state: str = "pending"        # pending|waiting|dispatched|done|shed
    owner: Optional[Tuple[str, int]] = None   # (replica, epoch)
    base: List[int] = dataclasses.field(default_factory=list)
    cur: List[int] = dataclasses.field(default_factory=list)
    retries: int = 0              # re-dispatches survived
    dispatches: int = 0
    dispatch_t: float = 0.0
    next_t: float = 0.0           # backoff: earliest next dispatch
    first_tok_t: Optional[float] = None
    progress_t: float = 0.0       # last time a new token was observed
    done_t: Optional[float] = None
    redispatched: bool = False
    shed_reason: str = ""
    avoid: str = ""               # replica the last attempt failed on
    # The journal identity of the CURRENT dispatch: rid * 1024 +
    # dispatch number. Each dispatch gets its OWN journal entry, so a
    # re-dispatch that lands back on a replica whose journal already
    # holds an earlier generation of this request can never fold the
    # two token streams together (that double-count corrupted the
    # assembled stream — found in review, pinned in tests).
    gen_rid: int = -1

    def next_gen(self) -> int:
        self.dispatches += 1
        self.gen_rid = self.rid * 1024 + self.dispatches
        return self.gen_rid

    @property
    def tokens(self) -> List[int]:
        return self.base + self.cur

    def finished(self) -> bool:
        toks = self.tokens
        return bool(toks) and (
            len(toks) >= self.max_new
            or (self.eos >= 0 and toks[-1] == self.eos))


class _Rep:
    """Router-side state for one replica."""

    def __init__(self, handle: Any):
        self.handle = handle
        self.health = "starting"   # starting|up|quarantined|dead
        self.last_seq: Optional[int] = None
        self.seq_t = 0.0           # when seq last advanced
        self.snap: Dict[str, Any] = {}
        self.sent_since_seq = 0    # dispatches the snapshot can't see yet
        self.inflight: set = set()
        self.reason = ""
        self.epoch_seen = handle.epoch
        self.done_count = 0
        self.q_t = 0.0             # when the quarantine began
        self.q_count = 0           # anomaly count at quarantine time


class Router:
    """Drive with ``begin(t0)`` then ``step(now)`` until ``active()``
    is False. ``emit`` receives ``fleet_dispatch`` / ``fleet_shed`` /
    ``fleet_replica`` records (observe.registry.emit-shaped)."""

    def __init__(self, replicas: Sequence[Any],
                 cfg: Optional[RouterConfig] = None,
                 emit: Optional[Callable[..., Any]] = None,
                 tracer: Any = None, slo_monitor: Any = None):
        self.cfg = cfg or RouterConfig()
        self.cfg.validate()
        self.reps: Dict[str, _Rep] = {
            h.name: _Rep(h) for h in replicas}
        if len(self.reps) != len(replicas):
            raise ValueError("replica names must be unique")
        self.tracks: Dict[int, _Track] = {}
        self._arrivals: List[int] = []   # rids not yet due, by arrival
        self._waiting: List[int] = []    # due, undispatched
        self._t0: Optional[float] = None
        self._emit_fn = emit
        # Fleet observability (observe/fleet_trace.py): the router's
        # own span recorder, and a fleet-level SLOMonitor scoring
        # CLIENT-PERCEIVED latency (admission -> first token across
        # retries/failovers) on the router's step clock. Both optional
        # and None-safe.
        self.tracer = tracer
        self.slo_monitor = slo_monitor
        self._steps = 0
        # Per-replica clock-offset samples, (wall_ts, mtime) pairs
        # from the snapshot liveness triplet — the stitcher's skew
        # estimate (observe.fleet_trace.estimate_offset). Bounded.
        self.clock_samples: Dict[str, List[Tuple[float, float]]] = {}
        self.events: List[Tuple[float, str, str]] = []  # (t, kind, rep)
        # Session stickiness: a conversation's turns land on the SAME
        # replica while it stays healthy, so the paged engine's
        # session re-attach (and the scheduler's turn ordering) keep
        # working fleet-side; a failover re-pins to the new owner
        # (turns recompute — correct, just cold).
        self._session_owner: Dict[str, str] = {}
        self.quarantines = 0
        self.rejoins = 0
        self.deaths = 0

    # -- bookkeeping -------------------------------------------------------

    def _emit(self, event: str, **fields) -> None:
        if self._emit_fn is not None:
            self._emit_fn(event, **fields)

    def _now_s(self, now: float) -> float:
        return now - (self._t0 or 0.0)

    def submit(self, requests: Sequence[Dict[str, Any]]) -> None:
        """Register the workload (dicts: rid, prompt, max_new, eos,
        arrival_s, slo, tenant). Call before ``begin``; arrivals are
        offsets from the ``begin`` clock."""
        for r in requests:
            rid = int(r["rid"])
            if rid in self.tracks:
                raise ValueError(f"duplicate rid {rid}")
            self.tracks[rid] = _Track(
                rid=rid, prompt=[int(t) for t in r["prompt"]],
                max_new=int(r.get("max_new", 64)),
                eos=int(r.get("eos", -1)),
                arrival_s=float(r.get("arrival_s", 0.0)),
                slo=str(r.get("slo", "standard")),
                tenant=str(r.get("tenant", "")),
                session=str(r.get("session", "")))
        self._arrivals = sorted(
            (rid for rid in self.tracks
             if self.tracks[rid].state == "pending"),
            key=lambda rid: (self.tracks[rid].arrival_s, rid))

    def begin(self, t0: float) -> None:
        self._t0 = t0

    def active(self) -> bool:
        return any(t.state in ("pending", "waiting", "dispatched")
                   for t in self.tracks.values())

    # -- health ------------------------------------------------------------

    def mark_dead(self, name: str, now: float) -> None:
        """Controller liveness signal: the process is gone. Evacuate
        its in-flight work from the (surviving) journal file."""
        rep = self.reps[name]
        if rep.health == "dead":
            return
        rep.health = "dead"
        rep.reason = "process_exit"
        self.deaths += 1
        self.events.append((now, "death", name))
        self._emit("fleet_replica", replica=name, state="dead",
                   reason=rep.reason, t_s=round(self._now_s(now), 4))
        if self.tracer is not None:
            self.tracer.replica_event("replica_death", name,
                                      inflight=len(rep.inflight))
        self._evacuate(rep, now, cancel=False)

    def mark_restarted(self, name: str, now: float) -> None:
        """Controller respawned the replica on a fresh epoch: back to
        ``starting`` — dispatchable again once its snapshot is live."""
        rep = self.reps[name]
        rep.health = "starting"
        rep.reason = ""
        rep.last_seq = None
        rep.snap = {}
        rep.sent_since_seq = 0
        rep.epoch_seen = rep.handle.epoch
        self._emit("fleet_replica", replica=name, state="restarted",
                   epoch=rep.handle.epoch,
                   t_s=round(self._now_s(now), 4))
        if self.tracer is not None:
            self.tracer.replica_event("replica_restart", name,
                                      epoch=rep.handle.epoch)

    def _quarantine(self, rep: _Rep, now: float, reason: str) -> None:
        rep.health = "quarantined"
        rep.reason = reason
        rep.q_t = now
        rep.q_count = int(
            (rep.snap.get("anomaly") or {}).get("anomalies", 0))
        self.quarantines += 1
        self.events.append((now, "quarantine", rep.handle.name))
        self._emit("fleet_replica", replica=rep.handle.name,
                   state="quarantined", reason=reason,
                   inflight=len(rep.inflight),
                   t_s=round(self._now_s(now), 4))
        if self.tracer is not None:
            self.tracer.replica_event("quarantine", rep.handle.name,
                                      reason=reason,
                                      inflight=len(rep.inflight))
        if self.cfg.redispatch_on_quarantine:
            self._evacuate(rep, now, cancel=True)

    def _rejoin(self, rep: _Rep, now: float) -> None:
        rep.health = "up"
        rep.reason = ""
        self.rejoins += 1
        self._emit("fleet_replica", replica=rep.handle.name,
                   state="rejoined", t_s=round(self._now_s(now), 4))
        if self.tracer is not None:
            self.tracer.replica_event("rejoin", rep.handle.name)

    def _bad_anomaly(self, snap: Dict[str, Any]) -> str:
        active = (snap.get("anomaly") or {}).get("active") or []
        hits = sorted(set(active) & set(self.cfg.quarantine_detectors))
        return hits[0] if hits else ""

    def _poll_health(self, now: float) -> None:
        for rep in self.reps.values():
            if rep.health == "dead":
                continue
            if rep.handle.epoch != rep.epoch_seen:
                # Controller rotated the epoch under us (restart path
                # that skipped mark_restarted) — resync.
                self.mark_restarted(rep.handle.name, now)
            snap = rep.handle.read_snapshot()
            if snap is not None and snap.get("seq") != rep.last_seq:
                rep.last_seq = snap.get("seq")
                rep.seq_t = now
                rep.snap = snap
                rep.sent_since_seq = 0
                # Clock-offset sample: the replica stamped wall_ts
                # (its clock) into the payload, the filesystem stamped
                # mtime (the router's frame) onto the file — one
                # (wall_ts, mtime) pair per seq advance feeds the
                # trace stitcher's skew estimate. hasattr-guarded:
                # fake replicas in tests need not implement it.
                if (isinstance(snap.get("wall_ts"), (int, float))
                        and hasattr(rep.handle, "snapshot_mtime")):
                    mtime = rep.handle.snapshot_mtime()
                    if mtime is not None:
                        samples = self.clock_samples.setdefault(
                            rep.handle.name, [])
                        samples.append(
                            (float(snap["wall_ts"]), float(mtime)))
                        del samples[:-64]
            fresh = (rep.last_seq is not None
                     and now - rep.seq_t <= self.cfg.stale_s)
            if rep.health == "starting":
                if fresh:
                    rep.health = "up"
                    self._emit("fleet_replica",
                               replica=rep.handle.name, state="up",
                               epoch=rep.handle.epoch,
                               t_s=round(self._now_s(now), 4))
                continue
            bad = self._bad_anomaly(rep.snap) if fresh else ""
            count = int((rep.snap.get("anomaly") or {})
                        .get("anomalies", 0))
            if rep.health == "up":
                if not fresh:
                    self._quarantine(rep, now, "stale_snapshot")
                elif bad and count > rep.q_count:
                    # Strictly NEW anomalies since the last
                    # quarantine: a cooldown rejoin must not bounce
                    # straight back on the same stale active entry
                    # (the idle-clock problem the cooldown exists
                    # for) — only fresh firings re-quarantine.
                    self._quarantine(rep, now, f"anomaly:{bad}")
            elif rep.health == "quarantined" and fresh:
                cleared = not bad
                if bad and rep.reason.startswith("anomaly"):
                    # Cooldown decay (see RouterConfig): an idle
                    # replica's step clock is frozen, so the hub's
                    # active horizon alone cannot clear it.
                    count = int((rep.snap.get("anomaly") or {})
                                .get("anomalies", 0))
                    cleared = (now - rep.q_t
                               > self.cfg.anomaly_cooldown_s
                               and count <= rep.q_count)
                if cleared:
                    self._rejoin(rep, now)

    # -- journal absorption ------------------------------------------------

    def _absorb(self, rep: _Rep, now: float,
                journal: Optional[Dict[int, Dict[str, Any]]] = None
                ) -> None:
        if not rep.inflight:
            return
        jr = rep.handle.read_journal() if journal is None else journal
        for rid in sorted(rep.inflight):
            tr = self.tracks[rid]
            ent = jr.get(tr.gen_rid)
            if ent is None:
                continue
            if ent.get("reject"):
                rep.inflight.discard(rid)
                self._shed(tr, now, "rejected")
                continue
            toks = ent.get("tokens", [])
            if len(toks) > len(tr.cur):
                tr.cur = [int(t) for t in toks]
                tr.progress_t = now
                if tr.first_tok_t is None:
                    tr.first_tok_t = now
                    if self.tracer is not None:
                        self.tracer.first_token(rid, tr.gen_rid,
                                                rep.handle.name)
            if ent.get("done") or tr.finished():
                rep.inflight.discard(rid)
                rep.done_count += 1
                self._finish(tr, now)

    def _finish(self, tr: _Track, now: float) -> None:
        tr.state = "done"
        tr.done_t = now
        if tr.first_tok_t is None:   # completed within one poll
            tr.first_tok_t = now
        # Client-perceived latency, router clock: admission (arrival)
        # -> first token / completion, every retry and failover
        # included — the number no per-replica view can compute. One
        # fleet_request record per completion is the durable form;
        # summary(), the fleet snapshot, and observe/report.py all
        # derive per-class percentiles from this SAME population with
        # the shared nearest-rank percentile (snapshot == report).
        arr = (self._t0 or 0.0) + tr.arrival_s
        ttft_ms = 1e3 * (tr.first_tok_t - arr)
        e2e_ms = 1e3 * (now - arr)
        n_tok = len(tr.tokens)
        tok_ms = (1e3 * (now - tr.first_tok_t) / max(1, n_tok - 1))
        self._emit("fleet_request", rid=tr.rid, slo=tr.slo,
                   tenant=tr.tenant, ttft_ms=round(ttft_ms, 3),
                   e2e_ms=round(e2e_ms, 3), tok_ms=round(tok_ms, 4),
                   tokens=n_tok, retries=tr.retries,
                   redispatched=tr.redispatched,
                   t_s=round(self._now_s(now), 4))
        if self.tracer is not None:
            self.tracer.request_done(tr.rid, finish="done",
                                     tokens=n_tok, ttft_ms=ttft_ms,
                                     retries=tr.retries)
        if self.slo_monitor is not None:
            self.slo_monitor.observe(tr.slo, ttft_ms, tok_ms,
                                     self._steps)

    def _shed(self, tr: _Track, now: float, reason: str) -> None:
        tr.state = "shed"
        tr.shed_reason = reason
        tr.done_t = now
        if tr.rid in self._waiting:
            self._waiting.remove(tr.rid)
        self._emit("fleet_shed", rid=tr.rid, slo=tr.slo,
                   reason=reason, retries=tr.retries,
                   t_s=round(self._now_s(now), 4))
        if self.tracer is not None:
            self.tracer.shed(tr.rid, reason)

    # -- evacuation / retry ------------------------------------------------

    def _evacuate(self, rep: _Rep, now: float, cancel: bool) -> None:
        """Move a dead/quarantined replica's in-flight requests back
        to the waiting queue as continuations: one final journal read
        freezes everything the replica managed to serve, the rest
        re-derives elsewhere (greedy determinism => token-identical)."""
        try:
            jr = rep.handle.read_journal()
        except OSError:
            jr = {}
        self._absorb(rep, now, journal=jr)   # completions first
        for rid in sorted(rep.inflight):
            tr = self.tracks[rid]
            if self.tracer is not None:
                self.tracer.leg_failed(rid, tr.gen_rid,
                                       rep.handle.name,
                                       rep.reason or "evacuated")
            tr.base = tr.base + tr.cur
            tr.cur = []
            tr.owner = None
            tr.avoid = rep.handle.name
            tr.redispatched = True
            tr.retries += 1
            if cancel:
                # Cancel FIRST, shed or not: a still-running replica
                # must stop burning slots on work the fleet has moved
                # (or given up on).
                try:
                    rep.handle.send({"cmd": "cancel",
                                     "rid": tr.gen_rid})
                except OSError:
                    pass  # replica may be unreachable; the restart
                    #       epoch rollover drops the work anyway
            if tr.retries > self.cfg.retry_budget:
                self._shed(tr, now, "retry_budget")
                continue
            tr.state = "waiting"
            tr.next_t = now + min(
                self.cfg.backoff_base_s * 2 ** (tr.retries - 1),
                self.cfg.backoff_max_s)
            self._waiting.append(rid)
        rep.inflight.clear()

    def _timeouts(self, now: float) -> None:
        """A dispatched request with no (new) token for
        ``dispatch_timeout_s`` re-dispatches — its replica may be
        healthy but wedged on exactly this request, which per-replica
        health cannot see."""
        for rep in self.reps.values():
            for rid in sorted(rep.inflight):
                tr = self.tracks[rid]
                if now - max(tr.dispatch_t, tr.progress_t) \
                        <= self.cfg.dispatch_timeout_s:
                    continue
                if self.tracer is not None:
                    self.tracer.leg_failed(rid, tr.gen_rid,
                                           rep.handle.name, "timeout")
                tr.base = tr.base + tr.cur
                tr.cur = []
                tr.owner = None
                tr.avoid = rep.handle.name
                tr.redispatched = True
                tr.retries += 1
                rep.inflight.discard(rid)
                self.events.append((now, "timeout", rep.handle.name))
                try:
                    # Cancel even when the retry budget is done: the
                    # replica must not keep decoding shed work.
                    rep.handle.send({"cmd": "cancel",
                                     "rid": tr.gen_rid})
                except OSError:
                    pass
                if tr.retries > self.cfg.retry_budget:
                    self._shed(tr, now, "retry_budget")
                    continue
                tr.state = "waiting"
                tr.next_t = now + min(
                    self.cfg.backoff_base_s * 2 ** (tr.retries - 1),
                    self.cfg.backoff_max_s)
                self._waiting.append(rid)

    # -- dispatch ----------------------------------------------------------

    def _load(self, rep: _Rep) -> int:
        snap = rep.snap
        return (int(snap.get("queue_depth", 0))
                + int(snap.get("requests_live", 0))
                + rep.sent_since_seq)

    def _score(self, rep: _Rep, slo: str) -> Tuple:
        """Least-loaded wins; ties break on the replica's recent
        per-class TTFT p95 (the SLO-aware part: a replica that has
        been slow for THIS class ranks behind an equally-loaded peer),
        then on name for determinism."""
        p95 = rep.snap.get(f"ttft_ms_p95_{slo}")
        return (self._load(rep),
                float(p95) if isinstance(p95, (int, float)) else 0.0,
                rep.handle.name)

    def _candidates(self, tr: _Track) -> List[_Rep]:
        out = []
        for rep in self.reps.values():
            if rep.health != "up":
                continue
            if self._load(rep) >= self.cfg.queue_high:
                continue
            max_len = rep.snap.get("max_len")
            if (isinstance(max_len, int)
                    and len(tr.prompt) + tr.max_new > max_len):
                continue
            out.append(rep)
        if tr.avoid and len(out) > 1:
            # A retry prefers any OTHER replica over the one it just
            # failed on (which may be wedged on exactly this request
            # while still reporting healthy) — unless it is the only
            # one left.
            out = [r for r in out if r.handle.name != tr.avoid] or out
        return out

    def _payload(self, tr: _Track) -> Dict[str, Any]:
        """The inbox line: a continuation re-sends prompt + everything
        served so far with the remaining budget (serve/scheduler.py's
        continuation contract, fleet-side). The wire rid is the
        DISPATCH GENERATION id (see _Track.gen_rid) — call
        ``next_gen()`` before building the payload."""
        import time as _time
        out = {"rid": tr.gen_rid, "prompt": tr.prompt + tr.base,
               "max_new": tr.max_new - len(tr.base),
               "eos": tr.eos, "slo": tr.slo, "tenant": tr.tenant,
               # Wall-clock enqueue stamp: the replica's InboxFeed
               # measures intake-minus-stamp as inbox_poll_lag_ms —
               # the latency decomposition's replica-side anchor.
               "enq_ts": round(_time.time(), 6)}
        if tr.session:
            out["session"] = tr.session
        return out

    def _dispatch(self, now: float) -> None:
        self._waiting.sort(
            key=lambda rid: (_RANK.get(self.tracks[rid].slo, 1),
                             self.tracks[rid].arrival_s, rid))
        still: List[int] = []
        for rid in self._waiting:
            tr = self.tracks[rid]
            if now < tr.next_t:
                still.append(rid)
                continue
            cands = self._candidates(tr)
            if not cands:
                still.append(rid)
                continue
            if tr.session:
                owner = self._session_owner.get(tr.session)
                sticky = [r for r in cands
                          if r.handle.name == owner]
                if sticky:
                    cands = sticky
            rep = min(cands, key=lambda r: self._score(r, tr.slo))
            if tr.session:
                self._session_owner[tr.session] = rep.handle.name
            tr.next_gen()
            rep.handle.send(self._payload(tr))
            rep.inflight.add(rid)
            rep.sent_since_seq += 1
            tr.owner = (rep.handle.name, rep.handle.epoch)
            tr.state = "dispatched"
            tr.dispatch_t = now
            if self.tracer is not None:
                self.tracer.dispatch(rid, tr.gen_rid,
                                     rep.handle.name,
                                     retry=tr.retries)
            self._emit("fleet_dispatch", rid=rid,
                       replica=rep.handle.name,
                       kind="redispatch" if tr.retries else "fresh",
                       retry=tr.retries, slo=tr.slo,
                       base_tokens=len(tr.base),
                       t_s=round(self._now_s(now), 4))
        self._waiting = still

    def _shed_pass(self, now: float) -> None:
        """Saturation shedding: when nothing can take new work, the
        longest-expired LOWEST class request is shed — at most one per
        step (rate-limited graceful degradation; the order is pinned:
        batch before standard before high)."""
        if not self._waiting:
            return
        if any(rep.health == "up"
               and self._load(rep) < self.cfg.queue_high
               for rep in self.reps.values()):
            return
        expired = [
            rid for rid in self._waiting
            if (self._now_s(now) - self.tracks[rid].arrival_s
                > self.cfg.shed_wait_s)]
        if not expired:
            return
        victim = max(expired, key=lambda rid: (
            _RANK.get(self.tracks[rid].slo, 1),
            -self.tracks[rid].arrival_s, -rid))
        self._shed(self.tracks[victim], now, "saturated")

    # -- the step ----------------------------------------------------------

    def step(self, now: float) -> None:
        if self._t0 is None:
            raise RuntimeError("call begin(t0) before step()")
        self._poll_health(now)
        for rep in self.reps.values():
            if rep.health != "dead":
                self._absorb(rep, now)
        self._timeouts(now)
        while self._arrivals and (
                self.tracks[self._arrivals[0]].arrival_s
                <= self._now_s(now)):
            rid = self._arrivals.pop(0)
            tr = self.tracks[rid]
            tr.state = "waiting"
            self._waiting.append(rid)
            if self.tracer is not None:
                self.tracer.request_queued(rid, slo=tr.slo,
                                           prompt_len=len(tr.prompt))
        self._dispatch(now)
        self._shed_pass(now)
        self._steps += 1
        if self.slo_monitor is not None:
            self.slo_monitor.on_step(self._steps)
        if self.tracer is not None:
            self.tracer.counters(
                waiting=float(len(self._waiting)),
                inflight=float(sum(len(r.inflight)
                                   for r in self.reps.values())))

    # -- summary -----------------------------------------------------------

    def _percentile(self, vals: List[float], q: float) -> float:
        from tensorflow_distributed_tpu.observe.slo import percentile
        return percentile(sorted(vals), q)

    def token_streams(self) -> Dict[int, List[int]]:
        """Completed requests' assembled streams (dead-leg base +
        current-owner tokens) — fleetbench's token-identity gate
        compares these against a single-replica reference run."""
        return {t.rid: t.tokens for t in self.tracks.values()
                if t.state == "done"}

    def summary(self) -> Dict[str, Any]:
        tracks = list(self.tracks.values())
        done = [t for t in tracks if t.state == "done"]
        shed = [t for t in tracks if t.state == "shed"]
        hist: Dict[str, int] = {}
        for t in tracks:
            if t.state in ("done", "shed"):
                hist[str(t.retries)] = hist.get(str(t.retries), 0) + 1
        shed_by_class: Dict[str, int] = {}
        shed_reasons: Dict[str, int] = {}
        for t in shed:
            shed_by_class[t.slo] = shed_by_class.get(t.slo, 0) + 1
            shed_reasons[t.shed_reason] = (
                shed_reasons.get(t.shed_reason, 0) + 1)
        out: Dict[str, Any] = {
            "requests": len(tracks),
            "requests_done": len(done),
            "requests_shed": len(shed),
            "requests_lost": len(tracks) - len(done) - len(shed),
            "shed_by_class": dict(sorted(shed_by_class.items())),
            "shed_reasons": dict(sorted(shed_reasons.items())),
            "dispatches": sum(t.dispatches for t in tracks),
            "redispatches": sum(t.retries for t in tracks),
            "dispatch_retry_hist": dict(
                sorted(hist.items(), key=lambda kv: int(kv[0]))),
            "quarantines": self.quarantines,
            "rejoins": self.rejoins,
            "deaths": self.deaths,
            "replica_done": {name: rep.done_count
                             for name, rep in sorted(self.reps.items())},
            "total_new_tokens": sum(len(t.tokens) for t in done),
        }
        ttfts = [1e3 * (t.first_tok_t - (self._t0 + t.arrival_s))
                 for t in done if t.first_tok_t is not None]
        if ttfts:
            for q in (50, 95, 99):
                out[f"ttft_ms_p{q}"] = round(
                    self._percentile(ttfts, q), 3)
        # Per-class END-TO-END TTFT (router clock, admission -> first
        # token, retries and failovers included — what the client
        # sees, which per-replica p95s structurally cannot). Same
        # population + same nearest-rank percentile as the fleet
        # snapshot and observe/report.py's fleet_request fold, so all
        # three agree exactly.
        by_cls: Dict[str, List[float]] = {}
        for t in done:
            if t.first_tok_t is not None:
                by_cls.setdefault(t.slo, []).append(
                    1e3 * (t.first_tok_t - (self._t0 + t.arrival_s)))
        for cls, vals in sorted(by_cls.items()):
            out[f"ttft_ms_p50_{cls}"] = round(
                self._percentile(vals, 50), 3)
            out[f"ttft_ms_p95_{cls}"] = round(
                self._percentile(vals, 95), 3)
        if self.slo_monitor is not None:
            out.update({"fleet_" + k: v
                        for k, v in self.slo_monitor.summary().items()})
        # Recovery population: a replica death/quarantine/timeout fell
        # inside the request's arrival -> first-token window, or the
        # request itself was re-dispatched (firebench's
        # recovery_window semantics, fleet-side).
        rec = []
        for t in done:
            if t.first_tok_t is None:
                continue
            arr = self._t0 + t.arrival_s
            window = t.redispatched or any(
                arr <= et <= t.first_tok_t
                for et, _, _ in self.events)
            if window:
                rec.append(1e3 * (t.first_tok_t - arr))
        out["recovery_requests"] = len(rec)
        if rec:
            out["ttft_ms_p99_recovery"] = round(
                self._percentile(rec, 99), 3)
        if done:
            t_last = max(t.done_t for t in done)
            out["wall_s"] = round(t_last - self._t0, 4)
            out["tokens_per_sec"] = round(
                out["total_new_tokens"] / max(out["wall_s"], 1e-9), 2)
        return out

    def fleet_snapshot(self, now: float) -> Dict[str, Any]:
        """The control-plane feed payload (``--fleet.export-path``):
        aggregate occupancy/queue, per-class end-to-end TTFT p50/p95
        (same population + percentile as :meth:`summary` — the PR-11
        snapshot==report contract at fleet level), per-replica health
        with snapshot staleness, the quarantine set, and the fleet SLO
        error budget — exactly what the ROADMAP item-2 elastic scaler
        and item-5 autopilot will poll."""
        slots = slots_live = queue = 0
        per_rep: Dict[str, Any] = {}
        quarantined: List[str] = []
        for name, rep in sorted(self.reps.items()):
            snap = rep.snap or {}
            slots += int(snap.get("num_slots", 0))
            slots_live += int(snap.get("requests_live", 0))
            queue += int(snap.get("queue_depth", 0))
            if rep.health == "quarantined":
                quarantined.append(name)
            per_rep[name] = {
                "health": rep.health,
                "epoch": rep.handle.epoch,
                "load": self._load(rep),
                "inflight": len(rep.inflight),
                "done": rep.done_count,
                "reason": rep.reason,
                "stale_s": (round(now - rep.seq_t, 3)
                            if rep.last_seq is not None else None),
                "ckpt_step": snap.get("ckpt_step"),
                # PER-DEVICE capacity facts (scheduler's
                # _capacity_fields): a tensor-parallel replica's cache
                # spend per device is 1/tp_width of the logical bytes
                # — headroom math over the logical figure would
                # overcount a TP replica tp_width-fold.
                "tp_width": snap.get("tp_width", 1),
                "per_device_cache_bytes": snap.get(
                    "per_device_cache_bytes"),
            }
            if "tune_actions" in snap:
                # Autopilot-armed replica: how many knobs its
                # controller has moved — a replica self-tuning hard is
                # a replica whose workload shifted (observe/
                # autopilot.py; surfaces in fleetview).
                per_rep[name]["tune_actions"] = snap["tune_actions"]
        done = [t for t in self.tracks.values() if t.state == "done"]
        by_cls: Dict[str, List[float]] = {}
        for t in done:
            if t.first_tok_t is not None:
                by_cls.setdefault(t.slo, []).append(
                    1e3 * (t.first_tok_t
                           - ((self._t0 or 0.0) + t.arrival_s)))
        out: Dict[str, Any] = {
            "t_s": round(self._now_s(now), 4),
            "step": self._steps,
            "requests": len(self.tracks),
            "requests_done": len(done),
            "requests_shed": sum(
                1 for t in self.tracks.values() if t.state == "shed"),
            "waiting": len(self._waiting),
            "inflight": sum(len(r.inflight)
                            for r in self.reps.values()),
            "slots": slots,
            "slots_live": slots_live,
            "queue_depth": queue,
            "quarantined": quarantined,
            "deaths": self.deaths,
            "replicas": per_rep,
        }
        for cls, vals in sorted(by_cls.items()):
            out[f"ttft_ms_p50_{cls}"] = round(
                self._percentile(vals, 50), 3)
            out[f"ttft_ms_p95_{cls}"] = round(
                self._percentile(vals, 95), 3)
        if self.slo_monitor is not None:
            out["slo"] = self.slo_monitor.snapshot()
            out["slo_budget_remaining_min"] = min(
                (e["budget_remaining"]
                 for e in out["slo"].values()), default=1.0)
            out["slo_alerting"] = self.slo_monitor.any_alerting()
        return out
