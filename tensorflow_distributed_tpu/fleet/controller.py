"""Fleet controller: replica lifecycle + the continuous train→serve loop.

Owns the replica subprocesses the router dispatches to:

- **spawn/restart** with the supervisor's leg semantics
  (resilience.supervisor.build_leg_args — serve children relaunch
  with the unchanged command) and capped exponential backoff; each
  restart rotates the replica onto a FRESH epoch directory (new
  inbox/journal/snapshot), because the router re-dispatches the dead
  leg's in-flight work to peers — a restarted replica resuming its
  old journal would double-serve it. A child that exits 2 (DIVERGED —
  SlotRetryExhausted) is NOT restarted, exactly like the supervisor.
- **checkpoint watch + rolling swap**: a trainer writes checkpoints
  into ``ckpt_dir`` concurrently; when a new step lands, the
  controller rolls it across the fleet ONE replica at a time — a
  ``swap`` inbox command triggers the replica's live ``swap_params``
  (sha256-verified, EMA-preferred, slots live), and the next replica
  is told only after the previous one's snapshot reports the new
  ``ckpt_step`` — so serving capacity never drops below N-1 during an
  upgrade. Model STALENESS (latest trained step minus each replica's
  served step) is sampled continuously; a restarted replica self-heals
  (its startup restore takes the newest verifiable checkpoint).
- **drain-before-stop**: ``request_stop`` sends every live replica a
  ``drain`` command (finish in-flight work, accept nothing new, exit
  0); ``wait_stopped`` escalates TERM→KILL only past the deadline.

Host-side policy only (stdlib): process handles come from an
injectable ``spawn`` callable, so the whole lifecycle suite runs on
fakes with a fake clock (tests/test_fleet.py).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from tensorflow_distributed_tpu.config import child_flag
from tensorflow_distributed_tpu.fleet.replica import ReplicaHandle

#: Native checkpoints are atomic dirs with a state.msgpack; orbax ones
#: count once the chief's commit marker lands. Duplicated from
#: train/checkpoint.py (available_steps) because that module needs
#: jax/flax and the controller must stay import-light — the contract
#: parity is pinned in tests/test_fleet.py.
_STEP_PREFIX = "step_"
_COMPLETE_MARKERS = ("state.msgpack", "ORBAX_COMMITTED")


def latest_ckpt_step(ckpt_dir: str) -> Optional[int]:
    """Newest COMPLETE checkpoint step in ``ckpt_dir`` (jax-free scan;
    None when the directory is empty/absent)."""
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return None
    best = None
    for name in os.listdir(ckpt_dir):
        if not name.startswith(_STEP_PREFIX):
            continue
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue  # step_X.tmp staging dirs, misnamed entries
        d = os.path.join(ckpt_dir, name)
        if not os.path.isdir(d):
            continue
        if any(os.path.exists(os.path.join(d, m))
               for m in _COMPLETE_MARKERS):
            best = step if best is None else max(best, step)
    return best


@dataclasses.dataclass
class ControllerConfig:
    max_restarts: int = 3          # per replica
    backoff_base_s: float = 0.5
    backoff_max_s: float = 8.0
    export_every_s: float = 0.2    # replica snapshot cadence
    swap_timeout_s: float = 120.0  # per-replica roll acknowledgement
    drain_timeout_s: float = 60.0
    ready_timeout_s: float = 300.0
    # Arm each replica's per-request ServeTracer on its per-epoch
    # trace.json, with durable (per-request-edge) flushing so a
    # SIGKILLed replica's last spans survive for the fleet stitcher
    # (observe/fleet_trace.py). Set by --fleet.trace.
    replica_trace: bool = False

    def validate(self) -> None:
        if self.max_restarts < 0:
            raise ValueError(
                f"controller max_restarts must be >= 0, "
                f"got {self.max_restarts}")
        if self.export_every_s <= 0:
            raise ValueError(
                f"controller export_every_s must be > 0, "
                f"got {self.export_every_s}")


class _Member:
    def __init__(self, handle: ReplicaHandle,
                 extra_args: Sequence[str] = ()):
        self.handle = handle
        self.extra_args = list(extra_args)
        self.proc: Any = None
        self.restarts = 0
        self.restart_at: Optional[float] = None  # backoff deadline
        self.gone = False        # dead for good (diverged / budget)
        self.swaps = 0
        self.staleness_max = 0


class FleetController:
    """``start()`` once, then drive ``poll(now)`` from the front-end
    loop. ``base_args`` is the shared ``--mode serve`` child argv; the
    controller appends the per-replica fleet wiring (inbox, journal,
    snapshot export, metrics) — argparse last-wins, so appended flags
    override base ones. ``extra_args`` maps replica name -> extra argv
    (fleetbench injects a fault plan into one replica this way)."""

    def __init__(self, handles: Sequence[ReplicaHandle],
                 base_args: Sequence[str], ckpt_dir: str = "",
                 cfg: Optional[ControllerConfig] = None,
                 extra_args: Optional[Dict[str, Sequence[str]]] = None,
                 emit: Optional[Callable[..., Any]] = None,
                 spawn: Optional[Callable[..., Any]] = None,
                 on_death: Optional[Callable[[str, float], None]] = None,
                 on_restart: Optional[Callable[[str, float],
                                               None]] = None,
                 env: Optional[Dict[str, str]] = None):
        self.cfg = cfg or ControllerConfig()
        self.cfg.validate()
        extra_args = extra_args or {}
        self.members = {
            h.name: _Member(h, extra_args.get(h.name, ()))
            for h in handles}
        self.base_args = list(base_args)
        self.ckpt_dir = ckpt_dir
        self._emit_fn = emit
        self._spawn = spawn or self._popen
        self.on_death = on_death
        self.on_restart = on_restart
        self.env = env
        self._t0: Optional[float] = None
        # Rolling-swap state: the step being rolled, the replicas
        # still to roll (one at a time), and when the current one was
        # told to swap.
        self.rolled_step: Optional[int] = None
        self._roll_queue: List[str] = []
        self._roll_sent_t: Optional[float] = None
        self._roll_timeouts = 0    # acks missed DURING the current roll
        self.rolling_swaps = 0     # fleet-wide rollouts every live
        #                            replica ACKED (a roll with a
        #                            timed-out swap is counted below
        #                            instead — the swaps_ok gate must
        #                            not pass on a rollout that never
        #                            actually converged)
        self.partial_rolls = 0
        self.swap_timeouts = 0
        self.staleness_max = 0
        self.draining = False

    # -- spawn -------------------------------------------------------------

    def _popen(self, cmd: List[str]) -> Any:
        return subprocess.Popen(cmd, env=self.env)

    def _cmd(self, m: _Member) -> List[str]:
        # The supervisor's leg-args contract (serve children relaunch
        # unchanged; --resume stays train-only), then the per-epoch
        # fleet wiring appended — last flag wins under argparse.
        from tensorflow_distributed_tpu.resilience.supervisor import (
            build_leg_args)
        h = m.handle
        args = build_leg_args(self.base_args + m.extra_args,
                              m.restarts)
        args += [
            child_flag("serve.inbox"), h.inbox,
            child_flag("serve.journal"), h.journal,
            child_flag("observe.export_path"), h.snapshot,
            child_flag("observe.export_every"),
            str(self.cfg.export_every_s),
            child_flag("observe.metrics_jsonl"), h.metrics,
        ]
        if self.cfg.replica_trace:
            args += [
                child_flag("observe.trace"), h.trace,
                child_flag("observe.trace_durable"), "true",
            ]
        return [sys.executable, "-m",
                "tensorflow_distributed_tpu.cli", *args]

    def _launch(self, m: _Member, now: float) -> None:
        m.handle.begin_epoch(m.handle.epoch)
        m.proc = self._spawn(self._cmd(m))
        m.restart_at = None
        self._emit("fleet_replica", replica=m.handle.name,
                   state="spawned", epoch=m.handle.epoch,
                   t_s=round(self._now_s(now), 4))

    def start(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._t0 = now
        # The checkpoint standing at launch is what every replica
        # restores at startup — only steps trained AFTER this roll.
        if self.ckpt_dir and self.rolled_step is None:
            self.rolled_step = latest_ckpt_step(self.ckpt_dir)
        for m in self.members.values():
            self._launch(m, now)

    # -- bookkeeping -------------------------------------------------------

    def _emit(self, event: str, **fields) -> None:
        if self._emit_fn is not None:
            self._emit_fn(event, **fields)

    def _now_s(self, now: float) -> float:
        return now - (self._t0 or 0.0)

    def alive(self, name: str) -> bool:
        m = self.members[name]
        return m.proc is not None and m.proc.poll() is None

    def wait_ready(self, timeout_s: Optional[float] = None,
                   clock=time.monotonic, sleep=time.sleep) -> bool:
        """Block until every replica has written a first snapshot (or
        the deadline passes) — the front-end starts the router clock
        only on a ready fleet, so replica cold-start (jax import +
        warmup) is not billed to the serving wall."""
        deadline = clock() + (timeout_s if timeout_s is not None
                              else self.cfg.ready_timeout_s)
        while clock() < deadline:
            missing = [m for m in self.members.values()
                       if m.handle.read_snapshot() is None]
            if not missing:
                return True
            if any(not self.alive(m.handle.name) for m in missing):
                return False   # a replica died before its first export
            sleep(0.1)
        return False

    # -- lifecycle ---------------------------------------------------------

    def _check_liveness(self, now: float) -> None:
        for m in self.members.values():
            if m.proc is None or m.gone:
                continue
            rc = m.proc.poll()
            if rc is None:
                continue
            if self.draining and rc == 0:
                m.proc = None    # clean drain exit, not a death
                continue
            m.proc = None
            rc_norm = 128 - rc if rc < 0 else rc
            self._emit("fleet_replica", replica=m.handle.name,
                       state="exited", rc=rc_norm,
                       epoch=m.handle.epoch,
                       t_s=round(self._now_s(now), 4))
            if self.on_death is not None:
                self.on_death(m.handle.name, now)
            if rc == 2:
                # DIVERGED (SlotRetryExhausted): deterministic — a
                # restart replays it. Same refusal as the supervisor.
                m.gone = True
                self._emit("fleet_replica", replica=m.handle.name,
                           state="diverged_no_restart",
                           t_s=round(self._now_s(now), 4))
                continue
            if m.restarts >= self.cfg.max_restarts:
                m.gone = True
                self._emit("fleet_replica", replica=m.handle.name,
                           state="restart_budget_exhausted",
                           restarts=m.restarts,
                           t_s=round(self._now_s(now), 4))
                continue
            m.restarts += 1
            delay = min(self.cfg.backoff_base_s
                        * 2 ** (m.restarts - 1),
                        self.cfg.backoff_max_s)
            m.restart_at = now + delay

    def _check_restarts(self, now: float) -> None:
        for m in self.members.values():
            if m.restart_at is None or now < m.restart_at \
                    or self.draining:
                continue
            m.handle.epoch += 1
            self._launch(m, now)
            if self.on_restart is not None:
                self.on_restart(m.handle.name, now)

    # -- train -> serve loop -----------------------------------------------

    @property
    def swap_in_progress(self) -> bool:
        return bool(self._roll_queue)

    def _check_rollout(self, now: float) -> None:
        latest = latest_ckpt_step(self.ckpt_dir)
        if latest is None:
            return
        # Staleness sampling rides the same snapshots the router
        # polls: trained-step minus each replica's served ckpt_step.
        for m in self.members.values():
            snap = m.handle.read_snapshot() or {}
            served = snap.get("ckpt_step")
            if isinstance(served, int):
                stale = max(0, latest - served)
                m.staleness_max = max(m.staleness_max, stale)
                self.staleness_max = max(self.staleness_max, stale)
        if not self._roll_queue:
            if self.rolled_step is not None \
                    and latest <= self.rolled_step:
                return
            self._roll_queue = [
                name for name, m in sorted(self.members.items())
                if self.alive(name)]
            if not self._roll_queue:
                return
            self.rolled_step = latest
            self._roll_sent_t = None
            self._roll_timeouts = 0
            self._emit("fleet_roll", state="begin",
                       ckpt_step=latest,
                       replicas=len(self._roll_queue),
                       t_s=round(self._now_s(now), 4))
        # Advance the roll as far as it can go THIS poll: an ack (or a
        # skipped dead replica) immediately tells the next replica to
        # swap — but a freshly-sent swap always waits for its ack, so
        # at most ONE replica is ever mid-swap (capacity >= N-1).
        while self._roll_queue:
            name = self._roll_queue[0]
            m = self.members[name]
            if not self.alive(name):
                # A dead replica's restart restores the newest
                # checkpoint anyway — skip it, keep the roll moving.
                self._roll_queue.pop(0)
                self._roll_sent_t = None
                continue
            if self._roll_sent_t is None:
                m.handle.send({"cmd": "swap"})
                self._roll_sent_t = now
                return
            snap = m.handle.read_snapshot() or {}
            served = snap.get("ckpt_step")
            acked = (isinstance(served, int)
                     and served >= self.rolled_step)
            if acked:
                m.swaps += 1
                self._emit("fleet_swap", replica=name,
                           ckpt_step=served,
                           t_s=round(self._now_s(now), 4))
            elif now - self._roll_sent_t > self.cfg.swap_timeout_s:
                self._roll_timeouts += 1
                self.swap_timeouts += 1
                self._emit("fleet_swap", replica=name,
                           state="timeout",
                           ckpt_step=self.rolled_step,
                           t_s=round(self._now_s(now), 4))
            else:
                return   # waiting on this replica's ack
            self._roll_queue.pop(0)
            self._roll_sent_t = None
        if self._roll_timeouts:
            self.partial_rolls += 1
        else:
            self.rolling_swaps += 1
        self._emit("fleet_roll",
                   state="done" if not self._roll_timeouts
                   else "done_partial",
                   ckpt_step=self.rolled_step,
                   timeouts=self._roll_timeouts,
                   t_s=round(self._now_s(now), 4))

    def poll(self, now: float) -> None:
        self._check_liveness(now)
        self._check_restarts(now)
        if self.ckpt_dir:
            self._check_rollout(now)

    # -- stop --------------------------------------------------------------

    def request_stop(self, now: Optional[float] = None) -> None:
        """Drain-before-stop: every live replica finishes its
        in-flight work and exits 0; nothing new is admitted (the
        router stopped dispatching — the caller sequences that)."""
        now = time.monotonic() if now is None else now
        self.draining = True
        self._roll_queue = []
        for m in self.members.values():
            if self.alive(m.handle.name):
                try:
                    m.handle.send({"cmd": "drain"})
                except OSError:
                    pass
        self._emit("fleet_roll", state="drain",
                   t_s=round(self._now_s(now), 4))

    def wait_stopped(self, clock=time.monotonic,
                     sleep=time.sleep) -> bool:
        """True when every replica exited by itself within the drain
        deadline; stragglers are escalated TERM -> KILL (and False
        returned — a drain that needed force is worth knowing)."""
        deadline = clock() + self.cfg.drain_timeout_s
        while clock() < deadline:
            if not any(self.alive(name) for name in self.members):
                return True
            sleep(0.1)
        clean = True
        for m in self.members.values():
            if not self.alive(m.handle.name):
                continue
            clean = False
            try:
                m.proc.send_signal(signal.SIGTERM)
            except (OSError, AttributeError):
                pass
        t_kill = clock() + 5.0
        while clock() < t_kill:
            if not any(self.alive(name) for name in self.members):
                return clean
            sleep(0.1)
        for m in self.members.values():
            if self.alive(m.handle.name):
                try:
                    m.proc.kill()
                except (OSError, AttributeError):
                    pass
        return clean

    def kill(self, name: str, sig: int = signal.SIGKILL) -> None:
        """Fault injection: SIGKILL one replica (fleetbench's
        replica-death drill)."""
        m = self.members[name]
        if m.proc is not None:
            try:
                m.proc.send_signal(sig)
            except (OSError, AttributeError):
                pass

    def summary(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.members),
            "restarts": sum(m.restarts for m in self.members.values()),
            "rolling_swaps": self.rolling_swaps,
            "partial_rolls": self.partial_rolls,
            "swap_timeouts": self.swap_timeouts,
            "rolled_step": self.rolled_step,
            "staleness_max_steps": self.staleness_max,
            "replica_swaps": {name: m.swaps for name, m in
                              sorted(self.members.items())},
            "replica_staleness_max": {
                name: m.staleness_max for name, m in
                sorted(self.members.items())},
        }
