"""Fleet front-end: glue the controller, router, and a workload.

::

    python -m tensorflow_distributed_tpu.fleet.run \\
        --replicas 3 --fleet-dir /tmp/fleet \\
        --requests workload.jsonl [--checkpoint-dir /tmp/ckpt] \\
        [--kill r1@12.5] [--hold-export r0@20:3] \\
        -- --model gpt_lm --seq-len 96 --serve.num-slots 2 ...

Everything after ``--`` is the shared replica argv (an ordinary
``--mode serve`` command line; the controller appends the per-replica
inbox/journal/snapshot wiring). The workload file is the serve
request-file schema (``{"prompt": [...], "max_new_tokens": n,
"arrival_s": t, "slo": "high"}`` per line) — rids are line order, so
a fleet run is directly comparable to a single-replica ``--mode
serve --serve.requests`` run on the same file (fleetbench's token-
identity gate does exactly that).

``--kill NAME@T`` SIGKILLs a replica T seconds into serving;
``--hold-export NAME@T:S`` freezes its snapshot exports for S seconds
(the stale-snapshot drill). Both are also available programmatically
as ``actions`` — ``(t, callable(controller, router))`` pairs —
which is how fleetbench schedules trainer legs mid-run.

The front-end emits ``fleet_*`` records (and one ``fleet_summary``)
into ``<fleet-dir>/fleet.jsonl``; ``observe.report`` folds them into
a Fleet section.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tensorflow_distributed_tpu.fleet.controller import (
    ControllerConfig, FleetController)
from tensorflow_distributed_tpu.fleet.replica import ReplicaHandle
from tensorflow_distributed_tpu.fleet.router import Router, RouterConfig


def load_workload(path: str) -> List[Dict[str, Any]]:
    """A serve request file as router-submittable dicts (rid = line
    order — the single-replica comparability contract)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            out.append({
                "rid": len(out),
                "prompt": [int(t) for t in obj["prompt"]],
                "max_new": int(obj.get("max_new_tokens", 64)),
                "eos": int(obj.get("eos_id", -1)),
                "arrival_s": float(obj.get("arrival_s", 0.0)),
                "slo": str(obj.get("slo", "standard")),
                "tenant": str(obj.get("tenant", "")),
                "session": str(obj.get("session", "")),
            })
    if not out:
        raise ValueError(f"{path} names no requests")
    return out


def run_fleet(*, fleet_dir: str, replicas: int,
              base_args: Sequence[str],
              workload: Sequence[Dict[str, Any]],
              ckpt_dir: str = "",
              router_cfg: Optional[RouterConfig] = None,
              controller_cfg: Optional[ControllerConfig] = None,
              extra_args: Optional[Dict[str, Sequence[str]]] = None,
              actions: Sequence[Tuple[float, Callable]] = (),
              env: Optional[Dict[str, str]] = None,
              poll_s: float = 0.05, timeout_s: float = 900.0,
              linger: Optional[Callable[..., bool]] = None,
              jsonl: str = "") -> Dict[str, Any]:
    """Serve ``workload`` on a ``replicas``-wide fleet; returns the
    merged router+controller summary. ``actions`` fire once each at
    their offset from serving start (clock = time.monotonic);
    ``linger(controller, router)`` keeps the loop (and the fleet)
    alive past the last completion while it returns True — how
    fleetbench waits out a trainer leg so its checkpoint still rolls."""
    os.makedirs(fleet_dir, exist_ok=True)
    registry = None
    emit = None
    if jsonl:
        from tensorflow_distributed_tpu.observe.registry import (
            JsonlSink, MetricsRegistry)
        registry = MetricsRegistry([JsonlSink(jsonl)],
                                   tags={"role": "fleet"})
        emit = registry.emit
    handles = [ReplicaHandle(f"r{i}", os.path.join(fleet_dir, f"r{i}"))
               for i in range(replicas)]
    router = Router(handles, router_cfg, emit=emit)
    ctl = FleetController(handles, base_args, ckpt_dir=ckpt_dir,
                          cfg=controller_cfg, extra_args=extra_args,
                          emit=emit, env=env,
                          on_death=router.mark_dead,
                          on_restart=router.mark_restarted)
    clock = time.monotonic
    summary: Dict[str, Any] = {}
    try:
        ctl.start(clock())
        if not ctl.wait_ready():
            raise RuntimeError(
                "fleet: replicas never became ready (no snapshot "
                "within the ready deadline) — check the replica "
                "metrics/stderr under " + fleet_dir)
        router.submit(workload)
        t0 = clock()
        router.begin(t0)
        pending_actions = sorted(actions, key=lambda ta: ta[0])
        fired = 0
        timed_out = False
        while True:
            now = clock()
            while (fired < len(pending_actions)
                   and now - t0 >= pending_actions[fired][0]):
                pending_actions[fired][1](ctl, router)
                fired += 1
            ctl.poll(now)
            router.step(now)
            if not router.active() and not ctl.swap_in_progress \
                    and fired >= len(pending_actions) \
                    and (linger is None or not linger(ctl, router)):
                break
            if now - t0 > timeout_s:
                timed_out = True
                break
            time.sleep(poll_s)
        ctl.request_stop(clock())
        drained = ctl.wait_stopped()
        summary = {**router.summary(), **ctl.summary(),
                   "drained_clean": bool(drained),
                   "timed_out": timed_out}
        if emit is not None:
            emit("fleet_summary", **summary)
        # Returned (not emitted — records stay lean): the assembled
        # per-request streams for token-identity comparisons.
        summary["tokens"] = {
            str(rid): toks
            for rid, toks in sorted(router.token_streams().items())}
        return summary
    finally:
        # Whatever happened, never leave replica processes behind.
        for m in ctl.members.values():
            if m.proc is not None and m.proc.poll() is None:
                try:
                    m.proc.kill()
                except OSError:
                    pass
        if registry is not None:
            registry.close()


def _parse_at(spec: str) -> Tuple[str, float, float]:
    """``NAME@T`` or ``NAME@T:S`` -> (name, t, s)."""
    name, _, rest = spec.partition("@")
    if not name or not rest:
        raise ValueError(
            f"{spec!r}: expected NAME@SECONDS[:DURATION]")
    t, _, dur = rest.partition(":")
    return name, float(t), float(dur) if dur else 0.0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print("usage: python -m tensorflow_distributed_tpu.fleet.run "
              "[options] -- <serve cli args>", file=sys.stderr)
        return 2
    split = argv.index("--")
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.fleet.run",
        description="health-aware fleet front-end over N serve "
        "replicas")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--fleet-dir", required=True)
    parser.add_argument("--requests", required=True,
                        help="serve request-file JSONL (rid = line "
                        "order)")
    parser.add_argument("--checkpoint-dir", default="",
                        help="trainer output to watch for rolling "
                        "swaps (also pass it in the serve args so "
                        "replicas restore/swap from it)")
    parser.add_argument("--kill", action="append", default=[],
                        metavar="NAME@T",
                        help="SIGKILL replica NAME at T seconds")
    parser.add_argument("--hold-export", action="append", default=[],
                        metavar="NAME@T:S",
                        help="freeze NAME's snapshot exports for S "
                        "seconds starting at T")
    parser.add_argument("--timeout", type=float, default=900.0)
    opts = parser.parse_args(argv[:split])
    base_args = argv[split + 1:]

    actions: List[Tuple[float, Callable]] = []
    for spec in opts.kill:
        name, t, _ = _parse_at(spec)
        actions.append((t, lambda ctl, router, _n=name:
                        ctl.kill(_n)))
    for spec in opts.hold_export:
        name, t, s = _parse_at(spec)
        if s <= 0:
            parser.error(f"--hold-export {spec}: needs a :DURATION")
        actions.append((t, lambda ctl, router, _n=name, _s=s:
                        ctl.members[_n].handle.send(
                            {"cmd": "hold_export", "secs": _s})))

    summary = run_fleet(
        fleet_dir=opts.fleet_dir, replicas=opts.replicas,
        base_args=base_args,
        workload=load_workload(opts.requests),
        ckpt_dir=opts.checkpoint_dir, actions=actions,
        timeout_s=opts.timeout,
        jsonl=os.path.join(opts.fleet_dir, "fleet.jsonl"))
    summary.pop("tokens", None)   # per-request streams: bulky, and
    #                               the journals already hold them
    print(json.dumps(summary))
    ok = (summary.get("requests_lost", 1) == 0
          and not summary.get("timed_out"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
