"""Fleet front-end: glue the controller, router, and a workload.

::

    python -m tensorflow_distributed_tpu.fleet.run \\
        --replicas 3 --fleet-dir /tmp/fleet \\
        --requests workload.jsonl [--checkpoint-dir /tmp/ckpt] \\
        [--kill r1@12.5] [--hold-export r0@20:3] \\
        -- --model gpt_lm --seq-len 96 --serve.num-slots 2 ...

Everything after ``--`` is the shared replica argv (an ordinary
``--mode serve`` command line; the controller appends the per-replica
inbox/journal/snapshot wiring). The workload file is the serve
request-file schema (``{"prompt": [...], "max_new_tokens": n,
"arrival_s": t, "slo": "high"}`` per line) — rids are line order, so
a fleet run is directly comparable to a single-replica ``--mode
serve --serve.requests`` run on the same file (fleetbench's token-
identity gate does exactly that).

``--kill NAME@T`` SIGKILLs a replica T seconds into serving;
``--hold-export NAME@T:S`` freezes its snapshot exports for S seconds
(the stale-snapshot drill). Both are also available programmatically
as ``actions`` — ``(t, callable(controller, router))`` pairs —
which is how fleetbench schedules trainer legs mid-run.

The front-end emits ``fleet_*`` records (and one ``fleet_summary``)
into ``<fleet-dir>/fleet.jsonl``; ``observe.report`` folds them into
a Fleet section.

The fleet observatory rides four more flags: ``--fleet.trace`` (router
spans + durable per-replica traces, stitched into
``<fleet-dir>/fleet_trace.json`` at run end — one balanced Perfetto
timeline across every process, failovers included), ``--fleet.slo``
(fleet-level burn-rate targets on CLIENT-perceived latency, emitting
``fleet_slo_alert``/``fleet_slo_ok``), and ``--fleet.export-path`` /
``--fleet.export-every`` (the atomically-rewritten control-plane
snapshot). Render everything with
``python -m tensorflow_distributed_tpu.observe.fleetview <fleet-dir>``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json
from tensorflow_distributed_tpu.fleet.controller import (
    ControllerConfig, FleetController)
from tensorflow_distributed_tpu.fleet.replica import ReplicaHandle
from tensorflow_distributed_tpu.fleet.router import Router, RouterConfig


@dataclasses.dataclass
class FleetObsConfig:
    """Fleet-observatory knobs (the ``--fleet.*`` CLI flags).

    ``trace`` arms the router's own FleetTracer AND per-replica
    durable ServeTracers (controller-appended), and stitches
    everything into ``<fleet-dir>/fleet_trace.json`` at run end.
    ``slo`` declares FLEET-level targets (observe/slo.py grammar)
    scored on client-perceived latency — admission to first token
    across retries and failovers — emitting ``fleet_slo_alert`` /
    ``fleet_slo_ok`` records. ``export_path`` is the atomically-
    rewritten control-plane snapshot (see Router.fleet_snapshot) on
    the ``export_every`` cadence (0 = one final snapshot only)."""

    trace: bool = False
    slo: str = ""
    slo_windows: str = "60,600"
    slo_burn: float = 1.0
    export_path: str = ""
    export_every: float = 0.0

    def validate(self) -> None:
        from tensorflow_distributed_tpu.observe.slo import (
            parse_slo, parse_windows)
        if self.slo:
            parse_slo(self.slo)
        parse_windows(self.slo_windows)
        if self.slo_burn <= 0:
            raise ValueError(
                f"fleet.slo_burn must be > 0, got {self.slo_burn}")
        if not self.slo and (self.slo_windows != "60,600"
                             or self.slo_burn != 1.0):
            raise ValueError(
                "fleet.slo_windows/slo_burn have no effect without "
                "fleet.slo; declare targets (--fleet.slo)")
        if self.export_every < 0:
            raise ValueError(
                f"fleet.export_every must be >= 0, "
                f"got {self.export_every}")
        if self.export_every and not self.export_path:
            raise ValueError(
                "fleet.export_every has no effect without "
                "fleet.export_path; set a snapshot file")


def load_workload(path: str) -> List[Dict[str, Any]]:
    """A serve request file as router-submittable dicts (rid = line
    order — the single-replica comparability contract)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            out.append({
                "rid": len(out),
                "prompt": [int(t) for t in obj["prompt"]],
                "max_new": int(obj.get("max_new_tokens", 64)),
                "eos": int(obj.get("eos_id", -1)),
                "arrival_s": float(obj.get("arrival_s", 0.0)),
                "slo": str(obj.get("slo", "standard")),
                "tenant": str(obj.get("tenant", "")),
                "session": str(obj.get("session", "")),
            })
    if not out:
        raise ValueError(f"{path} names no requests")
    return out


def run_fleet(*, fleet_dir: str, replicas: int,
              base_args: Sequence[str],
              workload: Sequence[Dict[str, Any]],
              ckpt_dir: str = "",
              router_cfg: Optional[RouterConfig] = None,
              controller_cfg: Optional[ControllerConfig] = None,
              extra_args: Optional[Dict[str, Sequence[str]]] = None,
              actions: Sequence[Tuple[float, Callable]] = (),
              env: Optional[Dict[str, str]] = None,
              poll_s: float = 0.05, timeout_s: float = 900.0,
              linger: Optional[Callable[..., bool]] = None,
              jsonl: str = "",
              obs: Optional[FleetObsConfig] = None) -> Dict[str, Any]:
    """Serve ``workload`` on a ``replicas``-wide fleet; returns the
    merged router+controller summary. ``actions`` fire once each at
    their offset from serving start (clock = time.monotonic);
    ``linger(controller, router)`` keeps the loop (and the fleet)
    alive past the last completion while it returns True — how
    fleetbench waits out a trainer leg so its checkpoint still rolls."""
    os.makedirs(fleet_dir, exist_ok=True)
    registry = None
    emit = None
    if jsonl:
        from tensorflow_distributed_tpu.observe.registry import (
            JsonlSink, MetricsRegistry)
        registry = MetricsRegistry([JsonlSink(jsonl)],
                                   tags={"role": "fleet"})
        emit = registry.emit
    handles = [ReplicaHandle(f"r{i}", os.path.join(fleet_dir, f"r{i}"))
               for i in range(replicas)]
    obs = obs or FleetObsConfig()
    obs.validate()
    ftracer = None
    slo_monitor = None
    if obs.trace:
        from tensorflow_distributed_tpu.observe.fleet_trace import (
            FleetTracer)
        ftracer = FleetTracer(
            os.path.join(fleet_dir, "router_trace.json"))
        # Replicas get durable per-epoch ServeTracers so every leg of
        # a failover leaves spans for the stitcher (copy: the caller's
        # config object stays untouched).
        controller_cfg = dataclasses.replace(
            controller_cfg or ControllerConfig(), replica_trace=True)
    if obs.slo:
        from tensorflow_distributed_tpu.observe.slo import (
            SLOMonitor, parse_slo, parse_windows)
        fast, slow = parse_windows(obs.slo_windows)
        slo_monitor = SLOMonitor(
            parse_slo(obs.slo), fast_window=fast, slow_window=slow,
            burn_threshold=obs.slo_burn, emit=emit,
            tracer=ftracer.tracer if ftracer is not None else None,
            event_prefix="fleet_")
    router = Router(handles, router_cfg, emit=emit, tracer=ftracer,
                    slo_monitor=slo_monitor)
    ctl = FleetController(handles, base_args, ckpt_dir=ckpt_dir,
                          cfg=controller_cfg, extra_args=extra_args,
                          emit=emit, env=env,
                          on_death=router.mark_dead,
                          on_restart=router.mark_restarted)

    def export_snapshot(now: float) -> None:
        """Atomic (tmp+rename) control-plane snapshot — a poller
        always reads a complete payload, never a torn write."""
        snap = router.fleet_snapshot(now)
        atomic_write_json(obs.export_path, snap)
        if emit is not None:
            emit("fleet_snapshot", **snap)
    clock = time.monotonic
    summary: Dict[str, Any] = {}
    try:
        ctl.start(clock())
        if not ctl.wait_ready():
            raise RuntimeError(
                "fleet: replicas never became ready (no snapshot "
                "within the ready deadline) — check the replica "
                "metrics/stderr under " + fleet_dir)
        router.submit(workload)
        t0 = clock()
        router.begin(t0)
        pending_actions = sorted(actions, key=lambda ta: ta[0])
        fired = 0
        timed_out = False
        last_export = t0
        while True:
            now = clock()
            while (fired < len(pending_actions)
                   and now - t0 >= pending_actions[fired][0]):
                pending_actions[fired][1](ctl, router)
                fired += 1
            ctl.poll(now)
            router.step(now)
            if (obs.export_path and obs.export_every
                    and now - last_export >= obs.export_every):
                last_export = now
                export_snapshot(now)
            if not router.active() and not ctl.swap_in_progress \
                    and fired >= len(pending_actions) \
                    and (linger is None or not linger(ctl, router)):
                break
            if now - t0 > timeout_s:
                timed_out = True
                break
            time.sleep(poll_s)
        ctl.request_stop(clock())
        drained = ctl.wait_stopped()
        obs_extra: Dict[str, Any] = {}
        if ftracer is not None:
            ftracer.close()
            obs_extra = _stitch_fleet(fleet_dir, router, handles, emit)
        summary = {**router.summary(), **ctl.summary(), **obs_extra,
                   "drained_clean": bool(drained),
                   "timed_out": timed_out}
        if emit is not None:
            emit("fleet_summary", **summary)
        if obs.export_path:
            # The FINAL snapshot — forced, after the fleet stopped, so
            # its per-class e2e p95 is computed over the same (now
            # frozen) done population summary() and observe.report use
            # (the PR-11 snapshot==report contract, fleet level).
            export_snapshot(clock())
        # Returned (not emitted — records stay lean): the assembled
        # per-request streams for token-identity comparisons.
        summary["tokens"] = {
            str(rid): toks
            for rid, toks in sorted(router.token_streams().items())}
        return summary
    finally:
        # Whatever happened, never leave replica processes behind.
        for m in ctl.members.values():
            if m.proc is not None and m.proc.poll() is None:
                try:
                    m.proc.kill()
                except OSError:
                    pass
        if registry is not None:
            registry.close()


def _stitch_fleet(fleet_dir: str, router: Router,
                  handles: Sequence[ReplicaHandle],
                  emit: Optional[Callable[..., Any]]
                  ) -> Dict[str, Any]:
    """End-of-run merge: router trace + every replica epoch's trace
    -> ``<fleet-dir>/fleet_trace.json``, then the per-request latency
    decomposition from the merged timeline (one ``fleet_decomp``
    record each). Returns the summary fields; never raises — a failed
    merge reports itself instead of sinking the run's summary."""
    from tensorflow_distributed_tpu.observe.fleet_trace import (
        decompose, estimate_offset, stitch)
    from tensorflow_distributed_tpu.observe.trace import load_trace
    out_path = os.path.join(fleet_dir, "fleet_trace.json")
    sources: List[Tuple[str, str, float]] = []
    for h in handles:
        offset = estimate_offset(
            router.clock_samples.get(h.name, []))
        for path in h.trace_paths():
            epoch = os.path.basename(os.path.dirname(path))
            sources.append((f"{h.name}/{epoch}", path, offset))
    try:
        stats = stitch(os.path.join(fleet_dir, "router_trace.json"),
                       sources, out_path)
    except (OSError, ValueError) as e:
        return {"stitch_error": str(e)}
    fields: Dict[str, Any] = {
        "stitch_sources": stats["sources"],
        "stitch_skipped": stats["skipped"],
        "stitch_balanced": stats["balanced"],
        "stitch_closed_at_death": stats["closed_at_death"],
        "fleet_trace": out_path,
    }
    if emit is not None:
        emit("fleet_stitch", **{k: v for k, v in fields.items()
                                if k != "fleet_trace"},
             events=stats["events"])
    try:
        decomp = decompose(load_trace(out_path))
    except (OSError, ValueError, KeyError):
        decomp = []
    fracs = []
    for d in decomp:
        if emit is not None:
            emit("fleet_decomp", **d)
        if d["e2e_ms"] > 0:
            fracs.append(abs(d["residual_ms"]) / d["e2e_ms"])
    fields["decomp_requests"] = len(decomp)
    if fracs:
        fields["decomp_residual_frac_mean"] = round(
            sum(fracs) / len(fracs), 4)
    return fields


def _parse_at(spec: str) -> Tuple[str, float, float]:
    """``NAME@T`` or ``NAME@T:S`` -> (name, t, s)."""
    name, _, rest = spec.partition("@")
    if not name or not rest:
        raise ValueError(
            f"{spec!r}: expected NAME@SECONDS[:DURATION]")
    t, _, dur = rest.partition(":")
    return name, float(t), float(dur) if dur else 0.0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print("usage: python -m tensorflow_distributed_tpu.fleet.run "
              "[options] -- <serve cli args>", file=sys.stderr)
        return 2
    split = argv.index("--")
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.fleet.run",
        description="health-aware fleet front-end over N serve "
        "replicas")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--fleet-dir", required=True)
    parser.add_argument("--requests", required=True,
                        help="serve request-file JSONL (rid = line "
                        "order)")
    parser.add_argument("--checkpoint-dir", default="",
                        help="trainer output to watch for rolling "
                        "swaps (also pass it in the serve args so "
                        "replicas restore/swap from it)")
    parser.add_argument("--kill", action="append", default=[],
                        metavar="NAME@T",
                        help="SIGKILL replica NAME at T seconds")
    parser.add_argument("--hold-export", action="append", default=[],
                        metavar="NAME@T:S",
                        help="freeze NAME's snapshot exports for S "
                        "seconds starting at T")
    parser.add_argument("--timeout", type=float, default=900.0)
    # Fleet observatory (observe/fleet_trace.py + Router.fleet_snapshot)
    parser.add_argument("--fleet.trace", dest="fleet_trace",
                        type=lambda s: s.lower() in ("1", "true", "yes"),
                        default=False,
                        help="router spans + durable replica traces, "
                        "stitched into <fleet-dir>/fleet_trace.json")
    parser.add_argument("--fleet.slo", dest="fleet_slo", default="",
                        help="fleet-level SLO targets on client-"
                        "perceived latency (observe/slo.py grammar)")
    parser.add_argument("--fleet.slo-windows", dest="fleet_slo_windows",
                        default="60,600",
                        help="fast,slow burn windows in router steps")
    parser.add_argument("--fleet.slo-burn", dest="fleet_slo_burn",
                        type=float, default=1.0)
    parser.add_argument("--fleet.export-path", dest="fleet_export_path",
                        default="",
                        help="atomically-rewritten fleet control-plane "
                        "snapshot (occupancy, per-class e2e p95, "
                        "quarantine set, per-replica health)")
    parser.add_argument("--fleet.export-every", dest="fleet_export_every",
                        type=float, default=0.0,
                        help="snapshot cadence in seconds (0 = one "
                        "final snapshot when export-path is set)")
    opts = parser.parse_args(argv[:split])
    base_args = argv[split + 1:]
    obs = FleetObsConfig(
        trace=opts.fleet_trace, slo=opts.fleet_slo,
        slo_windows=opts.fleet_slo_windows,
        slo_burn=opts.fleet_slo_burn,
        export_path=opts.fleet_export_path,
        export_every=opts.fleet_export_every)
    try:
        obs.validate()
    except ValueError as e:
        parser.error(str(e))

    actions: List[Tuple[float, Callable]] = []
    for spec in opts.kill:
        name, t, _ = _parse_at(spec)
        actions.append((t, lambda ctl, router, _n=name:
                        ctl.kill(_n)))
    for spec in opts.hold_export:
        name, t, s = _parse_at(spec)
        if s <= 0:
            parser.error(f"--hold-export {spec}: needs a :DURATION")
        actions.append((t, lambda ctl, router, _n=name, _s=s:
                        ctl.members[_n].handle.send(
                            {"cmd": "hold_export", "secs": _s})))

    summary = run_fleet(
        fleet_dir=opts.fleet_dir, replicas=opts.replicas,
        base_args=base_args,
        workload=load_workload(opts.requests),
        ckpt_dir=opts.checkpoint_dir, actions=actions,
        timeout_s=opts.timeout,
        jsonl=os.path.join(opts.fleet_dir, "fleet.jsonl"),
        obs=obs)
    summary.pop("tokens", None)   # per-request streams: bulky, and
    #                               the journals already hold them
    print(json.dumps(summary))
    ok = (summary.get("requests_lost", 1) == 0
          and not summary.get("timed_out"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
