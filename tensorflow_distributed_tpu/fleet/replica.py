"""The per-replica contract: inbox feed, workspace layout, handle.

A fleet replica is an ordinary ``--mode serve`` process with four
extra wires, all plain files under its per-epoch workspace directory:

- ``inbox.jsonl`` — append-only request/command intake the replica's
  scheduler TAILS between decode steps (``--serve.inbox``). One JSON
  object per line: either a request (``{"rid": 7, "prompt": [ids...],
  "max_new": 32, "eos": 5, "slo": "high", "tenant": "t0"}``) or a
  control command (``{"cmd": "swap" | "drain" | "cancel" |
  "hold_export", ...}``). The router/controller are the single
  writer; the replica is the single reader.
- ``journal.jsonl`` — the PR-6 request journal (``--serve.journal``):
  the router tails it to learn tokens and completions, and replays it
  after a replica death to build continuations. It doubles as the
  fleet's data plane — no sockets, crash-durable by construction.
- ``snapshot.json`` — the atomic ``--observe.export-path`` rolling
  snapshot (occupancy, queue depth, per-class TTFT p95, anomaly
  state, plus the liveness triplet ``seq``/``wall_ts``/``pid``): the
  router's health feed.
- ``metrics.jsonl`` — the replica's own observe stream.

Each restart gets a FRESH epoch directory (``e0``, ``e1``, ...): a
dead replica's in-flight work is re-dispatched to its peers from the
old epoch's journal, so the restarted process must start empty — an
epoch rollover is what makes "re-dispatch elsewhere" and "restart"
compose without double-serving.

Everything here is stdlib + numpy (the scheduler's Request type is
imported lazily), so the fake-replica router tests stay jax-free.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from tensorflow_distributed_tpu.utils.atomicio import durable_append

#: Control commands a replica's scheduler understands (see
#: serve/scheduler.py): ``swap`` = live weight swap from the newest
#: verifiable checkpoint; ``drain`` = finish in-flight work, accept
#: nothing new, exit cleanly; ``cancel`` = drop one request (the
#: router re-dispatched it elsewhere); ``hold_export`` = freeze
#: snapshot exports for ``secs`` (the stale-snapshot drill).
COMMANDS = ("swap", "drain", "cancel", "hold_export")


def append_line(path: str, obj: Dict[str, Any]) -> None:
    """Append one JSON line, flushed to the OS — the inbox write side
    (single writer per file; the reader tolerates a torn tail).
    Delegates to the blessed :func:`durable_append` so every
    cross-process append in the repo shares one spelling."""
    durable_append(path, obj)


class InboxFeed:
    """Replica-side tail of the inbox file (the scheduler's ``feed``).

    ``poll()`` returns the items appended since the last call, IN
    FILE ORDER (scheduler Request objects interleaved with command
    dicts — order is semantic: "dispatch, cancel, re-dispatch" must
    not be reordered into double service). Only COMPLETE lines are
    consumed (a line still being written is left for the next poll),
    and polls are throttled to ``poll_s`` so a fast decode loop does
    not stat the file every step. Unknown SLO classes coerce to
    "standard"; a request without a ``rid`` is a router bug and
    raises."""

    def __init__(self, path: str, default_max_new: int = 64,
                 default_eos: int = -1, poll_s: float = 0.02,
                 clock=time.perf_counter):
        self.path = path
        self.default_max_new = int(default_max_new)
        self.default_eos = int(default_eos)
        self.poll_s = float(poll_s)
        self.clock = clock
        self._offset = 0
        self._last_poll = -1e9
        # Inbox-poll lag: the router stamps each request line with its
        # wall-clock enqueue time (enq_ts); intake-minus-stamp is the
        # dispatch-file-write -> feed-intake latency — the replica-
        # side anchor of the fleet latency decomposition, and an early
        # warning for a wedged feed. Bounded recent-window deque.
        import collections
        self._lag_ms: collections.deque = collections.deque(maxlen=256)

    def _to_request(self, obj: Dict[str, Any]):
        import numpy as np

        from tensorflow_distributed_tpu.serve.scheduler import (
            Request, SLO_CLASSES)

        if "rid" not in obj:
            raise ValueError(
                f"inbox {self.path}: request line has no rid "
                f"({obj}) — the router assigns fleet-global rids")
        prompt = np.asarray([int(t) for t in obj["prompt"]], np.int32)
        if prompt.size == 0:
            raise ValueError(
                f"inbox {self.path}: rid {obj['rid']} has an empty "
                f"prompt")
        slo = str(obj.get("slo", "standard"))
        if slo not in SLO_CLASSES:
            slo = "standard"
        return Request(
            rid=int(obj["rid"]), prompt=prompt,
            max_new_tokens=int(obj.get("max_new",
                                       self.default_max_new)),
            eos_id=int(obj.get("eos", self.default_eos)),
            arrival_s=0.0, slo=slo,
            tenant=str(obj.get("tenant", "")),
            session=str(obj.get("session", "")))

    def poll(self) -> List[Any]:
        now = self.clock()
        if now - self._last_poll < self.poll_s:
            return []
        self._last_poll = now
        try:
            with open(self.path) as f:
                f.seek(self._offset)
                chunk = f.read()
        except FileNotFoundError:
            return []
        items: List[Any] = []
        for raw in chunk.splitlines(keepends=True):
            if not raw.endswith("\n"):
                break  # torn tail: the writer is mid-append
            # Consume BEFORE parsing: a malformed line raises once
            # (loudly — it is a router bug), never wedges the feed.
            self._offset += len(raw)
            line = raw.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "cmd" in obj:
                if obj["cmd"] not in COMMANDS:
                    raise ValueError(
                        f"inbox {self.path}: unknown command "
                        f"{obj['cmd']!r}; have {COMMANDS}")
                items.append(obj)
            else:
                if "enq_ts" in obj:
                    lag_ms = (time.time() - float(obj["enq_ts"])) * 1e3
                    self._lag_ms.append(max(0.0, lag_ms))
                items.append(self._to_request(obj))
        return items

    def lag_stats(self) -> Dict[str, float]:
        """Recent inbox-poll lag (ms): mean + nearest-rank p95 over
        the last requests taken in. Empty dict before any stamped
        intake (pre-PR routers send no enq_ts)."""
        if not self._lag_ms:
            return {}
        from tensorflow_distributed_tpu.observe.slo import percentile
        vals = sorted(self._lag_ms)
        return {
            "inbox_poll_lag_ms": round(sum(vals) / len(vals), 3),
            "inbox_poll_lag_ms_p95": round(percentile(vals, 95), 3),
        }


class ReplicaHandle:
    """The router/controller's view of one replica: its per-epoch
    workspace paths, the inbox write side, and tolerant readers for
    the snapshot and journal. Holds NO process — the controller owns
    the subprocess; fake replicas in tests implement this same
    surface (``name``/``epoch``/``send``/``read_snapshot``/
    ``read_journal``)."""

    def __init__(self, name: str, root: str):
        self.name = name
        self.root = root
        self.epoch = 0
        # Incremental journal tail state for the CURRENT epoch: byte
        # offset + accumulated replay dict, so the router's ~20/s
        # polls parse only NEW lines instead of re-reading the whole
        # (ever-growing) file each step.
        self._tail_epoch = -1
        self._tail_off = 0
        self._tail_acc: Dict[int, Dict[str, Any]] = {}

    def epoch_dir(self, epoch: Optional[int] = None) -> str:
        return os.path.join(self.root,
                            f"e{self.epoch if epoch is None else epoch}")

    @property
    def inbox(self) -> str:
        return os.path.join(self.epoch_dir(), "inbox.jsonl")

    @property
    def journal(self) -> str:
        return os.path.join(self.epoch_dir(), "journal.jsonl")

    @property
    def snapshot(self) -> str:
        return os.path.join(self.epoch_dir(), "snapshot.json")

    @property
    def metrics(self) -> str:
        return os.path.join(self.epoch_dir(), "metrics.jsonl")

    @property
    def trace(self) -> str:
        """The replica's per-epoch ServeTracer file (written only
        when the controller arms --observe.trace on its replicas) —
        one stitch source per epoch this replica lived through."""
        return os.path.join(self.epoch_dir(), "trace.json")

    def trace_paths(self) -> List[str]:
        """Every epoch's trace file that exists on disk, oldest
        first — a restarted replica contributes one source per life."""
        out = []
        for e in range(self.epoch + 1):
            p = os.path.join(self.epoch_dir(e), "trace.json")
            if os.path.exists(p):
                out.append(p)
        return out

    def snapshot_mtime(self) -> Optional[float]:
        """The snapshot file's mtime (the ROUTER-frame half of a
        clock-offset sample; the payload's wall_ts is the replica
        half). None when no snapshot exists yet."""
        try:
            return os.stat(self.snapshot).st_mtime
        except OSError:
            return None

    def begin_epoch(self, epoch: int) -> None:
        """Advance to a fresh epoch directory (controller restart
        path): new inbox, journal, snapshot — the restarted process
        starts empty while the old epoch's journal stays on disk for
        the router's continuation replay."""
        self.epoch = int(epoch)
        os.makedirs(self.epoch_dir(), exist_ok=True)

    def send(self, obj: Dict[str, Any]) -> None:
        append_line(self.inbox, obj)

    def read_snapshot(self) -> Optional[Dict[str, Any]]:
        """The current epoch's snapshot, or None (absent, torn, or
        not yet written — the atomic tmp+rename write side makes torn
        reads rare, but a poller must never crash on one)."""
        try:
            with open(self.snapshot) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def read_journal(self, epoch: Optional[int] = None
                     ) -> Dict[int, Dict[str, Any]]:
        """Replay the (current or a named) epoch's journal. For the
        CURRENT epoch the read is INCREMENTAL — only bytes past the
        last poll are parsed (complete lines only; a torn tail waits
        for the next poll), folded into a cached accumulator with the
        same serve.journal.replay semantics — so the router's
        per-step polls stay O(new tokens), not O(whole file). Treat
        the returned dict as read-only (it IS the cache). A named
        epoch always does a full tolerant replay."""
        from tensorflow_distributed_tpu.serve import journal
        if epoch is not None:
            return journal.replay(
                os.path.join(self.epoch_dir(epoch), "journal.jsonl"))
        if self._tail_epoch != self.epoch:
            self._tail_epoch = self.epoch
            self._tail_off = 0
            self._tail_acc = {}
        try:
            with open(self.journal) as f:
                f.seek(self._tail_off)
                chunk = f.read()
        except OSError:
            return self._tail_acc
        for raw in chunk.splitlines(keepends=True):
            if not raw.endswith("\n"):
                break
            self._tail_off += len(raw)
            line = raw.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # a kill's mid-write tail, already complete
            journal.fold_record(self._tail_acc, rec)
        return self._tail_acc
