"""Request journal: crash-durable serving progress, token granularity.

The scheduler appends three record kinds as it works — ``admit`` (the
full request: id, prompt, budget, eos), ``tok`` (one retired token for
one request), ``done`` (the request finished) — flushed to the OS once
per scheduler iteration, so a SIGKILL'd serving process leaves a
journal complete up to its last decode step. A restarted leg (the
supervisor re-runs ``--mode serve`` with the same args) replays the
journal and re-admits every unfinished request as a CONTINUATION:
prompt extended by the tokens already journaled, budget reduced by the
same count — greedy decode is deterministic, so the continuation
produces exactly the tokens the dead leg would have, and a kill costs
re-decoding at most the tokens that were in flight past the last
flush, never a request.

Semantics of an existing file: non-empty means RESUME (replay, then
append) — that is what makes the supervisor's identical restart
command re-admit instead of restart from scratch. A fresh run wants a
fresh path (benches and tests use per-run temp dirs). Truncated final
lines (the kill can land mid-write) are skipped, mirroring
observe.report.load_records.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import numpy as np


class RequestJournal:
    """Append-side handle. Opens lazily on first append; ``flush()``
    pushes buffered lines to the OS (enough for process-kill
    durability; fsync would only add OS-crash coverage serving does
    not promise)."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def _line(self, rec: Dict[str, Any]) -> None:
        if self._f is None:
            self._f = open(self.path, "a")
        self._f.write(json.dumps(rec) + "\n")

    def admit(self, rid: int, prompt, max_new_tokens: int,
              eos_id: int, slo: str = "standard",
              tenant: str = "", session: str = "") -> None:
        """``slo``/``tenant``/``session`` make the journal
        self-describing: replay re-derives requests from the run seed,
        so they are informational for the resume path — but a journal
        read standalone (firebench workload re-derivation, debugging)
        keeps the class/tenant/conversation story, and the session tag
        is how a resumed leg's multi-turn linkage survives a SIGKILL
        (the re-derived workload carries the same ids; pinned in
        tests/test_paging.py)."""
        rec = {"e": "admit", "rid": int(rid),
               "prompt": [int(t) for t in np.asarray(prompt)],
               "max_new": int(max_new_tokens),
               "eos": int(eos_id)}
        if slo != "standard":
            rec["slo"] = slo
        if tenant:
            rec["tenant"] = tenant
        if session:
            rec["sess"] = session
        self._line(rec)

    def token(self, rid: int, tok: int, t_s: float) -> None:
        """One retired token (``t_s`` = run-relative seconds, so a
        killed leg's serving wall time can be reconstructed from its
        last journaled token — benchmarks/firebench.py's goodput
        denominator)."""
        self._line({"e": "tok", "rid": int(rid), "t": int(tok),
                    "s": round(t_s, 4)})

    def done(self, rid: int) -> None:
        self._line({"e": "done", "rid": int(rid)})

    def reject(self, rid: int) -> None:
        """The request cannot be served here (does not fit the cache,
        or arrived while draining). A fleet router reading the journal
        sheds it instead of waiting forever — the replica must never
        crash over a bad dispatch."""
        self._line({"e": "reject", "rid": int(rid)})

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def fold_record(out: Dict[int, Dict[str, Any]],
                rec: Dict[str, Any]) -> None:
    """Fold ONE parsed journal record into a replay accumulator — the
    single definition of journal semantics, shared by :func:`replay`
    and the fleet router's incremental tail
    (fleet.replica.ReplicaHandle.read_journal)."""
    rid = rec.get("rid")
    if rid is None:
        return
    ent = out.setdefault(int(rid), {"req": None, "tokens": [],
                                    "done": False,
                                    "reject": False,
                                    "last_s": 0.0})
    kind = rec.get("e")
    if kind == "admit":
        ent["req"] = {"prompt": rec.get("prompt", []),
                      "max_new": rec.get("max_new", 0),
                      "eos": rec.get("eos", -1)}
    elif kind == "tok":
        ent["tokens"].append(int(rec["t"]))
        ent["last_s"] = max(ent["last_s"],
                            float(rec.get("s", 0.0)))
    elif kind == "done":
        ent["done"] = True
    elif kind == "reject":
        ent["reject"] = True


def replay(path: str) -> Dict[int, Dict[str, Any]]:
    """Read a journal back into ``{rid: {"req": {...} | None,
    "tokens": [...], "done": bool, "last_s": float}}``. Missing file =
    empty dict (a fresh run). Malformed lines (the truncated tail of a
    kill) are skipped."""
    out: Dict[int, Dict[str, Any]] = {}
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # the kill's mid-write tail
            fold_record(out, rec)
    return out


def apply_replay(requests: List[Any],
                 journal: Dict[int, Dict[str, Any]]) -> List[Any]:
    """Fold a replayed journal into a fresh workload (the restarted
    leg regenerates its requests deterministically — same seed, same
    trace — and this narrows them to the unfinished work):

    - ``done`` requests drop (already served and streamed);
    - partially-served requests become CONTINUATIONS: prompt extended
      by the journaled tokens, budget cut by the same count, arrival 0
      (they were in flight — they re-enter immediately), tagged with
      ``_base_tokens`` so the completion reports the FULL token list;
    - untouched requests keep their arrival offsets SHIFTED by the
      dead leg's elapsed serving time (the open-loop clients kept
      sending while the process was down — a request whose arrival
      already passed is due immediately, not re-waited).

    Pure function over Request-shaped objects (works on the fake
    engine's requests too — jax-free by design)."""
    out: List[Any] = []
    import dataclasses

    elapsed = max((e["last_s"] for e in journal.values()),
                  default=0.0)
    for req in requests:
        ent = journal.get(req.rid)
        if ent is None:
            out.append(dataclasses.replace(
                req, arrival_s=max(0.0, req.arrival_s - elapsed)))
            continue
        if ent["done"]:
            continue
        toks = list(ent["tokens"])
        if not toks:
            # Admitted but no token journaled (killed inside its first
            # prefill): re-serve from scratch, due immediately.
            out.append(dataclasses.replace(req, arrival_s=0.0))
            continue
        if len(toks) >= req.max_new_tokens or (
                req.eos_id >= 0 and toks[-1] == req.eos_id):
            # Every budgeted token (or the EOS) was journaled but the
            # done record didn't land — the request IS finished; don't
            # re-admit a zero-budget or past-EOS continuation.
            continue
        cont = dataclasses.replace(
            req,
            prompt=np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(toks, np.int32)]),
            max_new_tokens=req.max_new_tokens - len(toks),
            arrival_s=0.0)
        cont._base_tokens = toks
        out.append(cont)
    return out
