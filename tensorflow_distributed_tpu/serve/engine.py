"""Slot-based continuous-batching decode engine.

One jitted single-token decode program runs over a fixed
``[num_slots, max_len]`` KV cache for the life of the process. Slots
are independently occupied and freed BETWEEN steps, so the request set
changes with zero recompilation:

- **insert**: a bucketed prefill program (one compile per bucket
  length, shared with generate()'s prefill via
  models.generate.prefill_cache) fills a fresh ``[1, max_len]`` cache
  row, and one jitted ``dynamic_update_slice`` per cache leaf drops it
  into the slot — the slot index is a traced scalar, so every slot
  uses the SAME program;
- **decode**: per-row positions (models/transformer.py writes each
  row's K/V at ITS position and masks attention past it) let slot 0
  sit at depth 700 while slot 3 is at depth 12 — one program, any
  mix of depths;
- **free**: host-side bookkeeping only. A freed slot keeps riding the
  batched step (static shapes), writing into its own row at position
  0 with its mask clamped to one column — garbage that the next
  insert's full-row overwrite replaces, and that no other row can
  attend (attention never crosses rows).

Greedy sampling only: the engine's contract (pinned in
tests/test_serve.py) is token-identical output to one-shot greedy
``generate()`` per request — continuous batching must not change
results.

Serve-under-fire surface (README "Serving under faults"; all optional,
zero cost unconfigured): the decode program carries a per-slot
finiteness flag (``take_bad_slots`` — the scheduler's quarantine
signal), ``poison_slot`` injects a genuinely-NaN KV row for drills,
``swap_params`` installs fresh weights between steps without draining
slots or recompiling (structure/shape/dtype/sharding asserted), the
token fetch runs under an optional decode watchdog, and ``warmup``
moves every program's first-dispatch cost out of the first requests'
TTFT.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.analysis import runtime as graftcheck
from tensorflow_distributed_tpu.models.generate import (
    decode_token, lookup_program, prefill_cache)
from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.serve.buckets import (
    default_buckets, pick_bucket)


@functools.lru_cache(maxsize=64)
def _compiled_prefill(model, bucket: int):
    """One jitted prefill program per (model, bucket length): prompt
    padded to ``bucket`` -> (cache row [1, max_len, ...], greedy first
    token from the TRUE last position). ``true_len`` is a traced
    scalar, so every prompt length sharing a bucket shares the
    executable."""

    def run(params, prompt, true_len):
        logits, cache = prefill_cache(model, params, prompt)
        last = jax.lax.dynamic_index_in_dim(
            logits, true_len - 1, axis=1, keepdims=False)   # [1, V]
        return cache, jnp.argmax(last, axis=-1).astype(jnp.int32)

    return observe_device.instrument_jit(f"serve_prefill_b{bucket}", run)


@functools.lru_cache(maxsize=8)
def _compiled_verify(model, k: int):
    """THE speculative verify program: feed each slot's pending token
    plus its ``k`` proposals in ONE forward at positions
    ``pos .. pos + k`` (the decode cache path already writes per-row
    contiguous spans), take the greedy argmax at every fed position,
    and flag per-slot finiteness like the decode step. The host
    compares proposals against the argmax chain (speculate.
    accept_length) — everything emitted is the TARGET model's own
    greedy token, so speculation cannot change output, only how many
    tokens one dispatch yields. Fixed shapes per (model, k): one
    executable for the engine's lifetime, censused as ``serve_verify``
    in the jaxpr goldens."""

    def run(params, cache, toks, pos):
        # toks [S, k+1] (pending token + proposals), pos [S].
        positions = pos[:, None] + jnp.arange(k + 1)[None, :]
        logits, state = model.apply(
            {"params": params, "cache": cache}, toks, decode=True,
            positions=positions, mutable=["cache"])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, k+1]
        ok = jnp.isfinite(logits).all(axis=(-1, -2))
        return state["cache"], nxt, ok

    return observe_device.instrument_jit(f"serve_verify_k{k}", run)


@functools.lru_cache(maxsize=8)
def _compiled_step(model):
    """THE decode program: one greedy token for every slot at its own
    depth, plus a per-slot ``ok`` flag — logits fully finite. The flag
    is the engine's NaN containment sensor: a poisoned KV row (or a
    genuinely diverged slot) shows up HERE, on device, as part of the
    same program and the same host fetch, costing one row-wise
    reduction and zero extra transfers or collectives (census-pinned).
    Compiled once per (model, num_slots) — the shapes come from the
    arguments, so one engine reuses one executable forever."""

    def run(params, cache, tok, pos):
        last, cache = decode_token(model, params, cache, tok, pos)
        ok = jnp.isfinite(last).all(axis=-1)
        return cache, jnp.argmax(last, axis=-1).astype(jnp.int32), ok

    return observe_device.instrument_jit("serve_decode_step", run)


def _insert_row_jit(cache, row, slot):
    """Drop a prefilled [1, ...] cache row into ``slot`` of the engine
    cache — ``slot`` is traced, so all slots share the program. Scalar
    leaves (the compat ``index``) pass through: positions are the
    authority on depth."""

    def put(c, r):
        if getattr(r, "ndim", 0) and r.shape[:1] == (1,):
            return jax.lax.dynamic_update_slice(
                c, r.astype(c.dtype), (slot,) + (0,) * (c.ndim - 1))
        return c

    return jax.tree_util.tree_map(put, cache, row)


_insert_row = observe_device.instrument_jit("serve_insert_row",
                                            _insert_row_jit)


@jax.jit
def _poison_row_jit(cache, slot):
    """NaN-fill the float leaves of ``slot``'s cache row (the slot_nan
    fault drill): the poison flows through the REAL attention math, so
    that slot's next logits are genuinely non-finite — exactly what a
    corrupted KV row or a diverged slot produces. ``slot`` is traced,
    so every slot shares one program; integer leaves (token ids, the
    compat index) pass through untouched."""

    def bad(c):
        if (getattr(c, "ndim", 0)
                and jnp.issubdtype(c.dtype, jnp.floating)):
            row = jnp.full((1,) + c.shape[1:], jnp.nan, c.dtype)
            return jax.lax.dynamic_update_slice(
                c, row, (slot,) + (0,) * (c.ndim - 1))
        return c

    return jax.tree_util.tree_map(bad, cache)


def tp_width(model) -> int:
    """The model's tensor-parallel width: the "model" axis of the mesh
    it was built on (1 when mesh-less or unsharded). The ONE derivation
    every piece of per-device serve arithmetic divides by."""
    mesh = getattr(model, "mesh", None)
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get("model", 1))


def shard_cache(model, cache):
    """Place a decode-cache pytree for ``model``'s tensor-parallel
    mesh: the head axis (dim 2 of every [.., .., nk, dh] / [.., .., nk]
    leaf — dense rows, int8 scales, and the paged pool all put heads
    there) shards over "model"; scalar leaves (the compat ``index``)
    replicate. A no-op at TP width 1, so the single-device engine's
    arrays are untouched. One explicit placement here is what lets
    GSPMD keep every subsequent decode/insert/verify output in the
    same layout (asserted by the engine's first-step sharding
    contract)."""
    if tp_width(model) == 1:
        return cache
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = model.mesh

    def put(c):
        spec = (PartitionSpec(None, None, "model")
                if getattr(c, "ndim", 0) >= 3 else PartitionSpec())
        return jax.device_put(c, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, cache)


def zero_cache(model, params, num_slots: int):
    """A zeroed [num_slots, max_len, ...] decode-cache pytree for
    ``model``, shaped via eval_shape (no device work, no params
    flops). Shared by the engine and the draft speculator's mirrored
    cache (serve/speculate.py); int8 quantized caches come back with
    their scale leaves included. On a TP mesh the head axis comes back
    sharded over "model" (see :func:`shard_cache`)."""
    tok = jnp.zeros((num_slots, 1), jnp.int32)
    pos = jnp.zeros((num_slots, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda p, t, q: model.apply(
            {"params": p}, t, decode=True, positions=q,
            mutable=["cache"])[1]["cache"],
        params, tok, pos)
    return shard_cache(model, jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes))


class SlotDecodeEngine:
    """The slot cache + the programs (prefill/insert/step, plus the
    speculative verify when ``spec_tokens > 0``), with host-side slot
    bookkeeping. The scheduler (serve/scheduler.py) decides WHEN to
    prefill vs decode; this class owns WHAT runs on device."""

    def __init__(self, model, params, num_slots: int,
                 buckets: Optional[Sequence[int]] = None,
                 min_bucket: int = 16, check: bool = False,
                 fault_plan=None, watchdog=None, spec_tokens: int = 0,
                 tracer=None):
        cfg = model.cfg
        if not cfg.causal:
            raise ValueError("SlotDecodeEngine needs a causal model")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {spec_tokens}")
        self.spec_tokens = spec_tokens
        self.model = model
        self.params = params
        self.num_slots = num_slots
        self.max_len = cfg.max_len
        self.buckets: Tuple[int, ...] = (
            tuple(buckets) if buckets
            else default_buckets(cfg.max_len, min_bucket,
                                 cap=cfg.max_len))
        if max(self.buckets) > cfg.max_len:
            raise ValueError(
                f"largest bucket {max(self.buckets)} exceeds the "
                f"model's max_len {cfg.max_len}")
        # Tensor parallelism: the width comes off the mesh the model
        # was built on — the engine itself has no TP knob. At width > 1
        # the cache's head axis is sharded over "model"
        # (shard_cache), per-device accounting divides by the width,
        # and the first-step sharding contract is ALWAYS armed (layout
        # drift under TP re-lays-out every subsequent step — too
        # expensive to leave to an opt-in flag).
        self.tp_width = tp_width(model)
        self.cache = self._zero_cache()
        self.tok = np.zeros((num_slots,), np.int32)
        self.pos = np.zeros((num_slots,), np.int32)
        self.active = np.zeros((num_slots,), bool)
        # Distinct prefill programs this engine has invoked — one per
        # bucket actually used, each a single compiled executable (the
        # bench asserts <= len(buckets)); generate.compile_cache_stats()
        # carries the process-wide hit/miss view.
        self._buckets_used: set = set()
        self.prefills = 0
        self.decode_steps = 0
        self.swaps = 0
        # Serve-under-fire hooks (both optional; zero cost when None):
        # the fault plan's decode_stall is consumed INSIDE the watched
        # token fetch so the decode watchdog sees exactly the hang a
        # wedged device produces, and _last_ok carries the decode
        # program's per-slot finiteness flags for take_bad_slots().
        self._plan = fault_plan
        self._watchdog = watchdog
        # Per-request tracing (observe/serve_trace.py): engine
        # dispatches land as complete spans on the engine track —
        # decode ticks batched per STEP, prefill/insert per admission.
        # None = zero cost.
        self._tracer = tracer
        self._last_ok: Optional[np.ndarray] = None
        self._last_verify_fallback: list = []
        self._build_programs()
        self.verify_steps = 0
        # --check (graftcheck's runtime layer): the decode step runs
        # under jax.transfer_guard("disallow"), and the cache layout
        # after the first step is asserted against the layout the
        # cache was created with (analysis/runtime.py).
        self._check = check
        self._declared_cache = (graftcheck.sharding_tree(self.cache)
                                if check or self.tp_width > 1 else None)

    def _zero_cache(self):
        return zero_cache(self.model, self.params, self.num_slots)

    def _build_programs(self) -> None:
        """Bind the decode/verify executables. The paged subclass
        (serve/paging/engine.py) overrides this to bind the paged
        variants — same names, same one-program discipline, plus the
        page-table input."""
        self._step_fn = lookup_program(_compiled_step, self.model)
        self._verify_fn = (lookup_program(_compiled_verify, self.model,
                                          self.spec_tokens)
                           if self.spec_tokens else None)

    def set_spec_k(self, k: int) -> None:
        """Live speculation-depth change between decode steps — the
        autopilot's loop-3 actuator. Rebinds the verify executable at
        the new k through the same ``lookup_program`` cache the ctor
        used: a k this engine has already run is a dict hit; a new k
        pays its compile once, on the next verify dispatch. Safe with
        slots live — ``can_verify``/``verify_fallback_slots`` read
        ``spec_tokens`` per call for the headroom guard, and greedy
        verify is token-identical at any k by construction. Only an
        engine BUILT speculative can retune: k=0 engines compiled no
        verify program and the scheduler wires no speculator."""
        k = int(k)
        if k < 1:
            raise ValueError(f"spec k must be >= 1, got {k}")
        if not self.spec_tokens:
            raise ValueError(
                "set_spec_k needs an engine built with spec_tokens "
                "> 0 (a k=0 engine has no verify program to retune)")
        if k == self.spec_tokens:
            return
        self.spec_tokens = k
        self._build_programs()

    def _dispatch_step(self, tok, pos):
        """One decode-program dispatch (the paged subclass appends the
        page tables); returns (cache, next tokens, per-slot ok)."""
        with graftcheck.transfer_guard(self._check):
            return self._step_fn(self.params, self.cache, tok, pos)

    def _dispatch_verify(self, tok, pos):
        """One verify-program dispatch (paged subclass: + tables)."""
        with graftcheck.transfer_guard(self._check):
            return self._verify_fn(self.params, self.cache, tok, pos)

    def _h2d(self, a):
        """Host->device upload of a guarded-dispatch input. At TP
        width 1 this is plain ``jnp.asarray``. Under TP the upload
        places explicitly REPLICATED on the engine's mesh: a bare
        asarray lands uncommitted on one device, and the compiled
        program's broadcast to the other shards would then be a
        device-to-device transfer INSIDE the transfer guard — tripping
        --check on the engine's own designed input path."""
        if self.tp_width == 1:
            return jnp.asarray(a)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(
            a, NamedSharding(self.model.mesh, PartitionSpec()))

    def _span(self, name: str, **args):
        if self._tracer is None:
            return contextlib.nullcontext()
        return self._tracer.engine_span(name, **args)

    def cache_bytes_per_slot(self) -> int:
        """PER-DEVICE HBM the decode cache spends per slot (scale
        leaves of an int8 cache included) — the number the "choosing
        num_slots under an HBM budget" math divides by (README
        "Serving"; servebench's int8 and TP slots-at-budget gates).
        Under TP every counted leaf is head-sharded over the "model"
        axis (shard_cache's placement), so each device holds
        ``1/tp_width`` of the logical bytes — the division below is
        exact, not an estimate, and collapses to a no-op at width 1."""
        total = sum(
            int(np.prod(c.shape)) * c.dtype.itemsize
            for c in jax.tree_util.tree_leaves(self.cache)
            if getattr(c, "ndim", 0)
            and c.shape[:1] == (self.num_slots,))
        return total // (self.num_slots * self.tp_width)

    @property
    def prefill_compiles(self) -> int:
        """Distinct prefill programs invoked (one per bucket used)."""
        return len(self._buckets_used)

    def warmup(self, speculator=None) -> None:
        """Dispatch every engine program once — each bucket's prefill,
        the row insert, the decode step (and the verify program when
        speculation is armed) — against throwaway inputs, then roll
        the cache reference back. First-dispatch cost (trace/compile
        or persistent-cache deserialize, ~hundreds of ms per program
        on this box) moves to startup instead of landing in the first
        requests' TTFT — and, under a restart, inside the recovery
        window. Host bookkeeping is untouched and the pre-warmup cache
        object is restored, so a warmed engine is byte-identical to a
        fresh one.

        ``speculator``: a draft-model speculator's mirror programs
        (its bucketed prefills, row insert, and the proposal scan) are
        warmed too via its own ``warmup()`` — without this, the FIRST
        speculative round paid the draft's compiles inside the serving
        wall (pinned by a compile-counter test in
        tests/test_serve_observe.py)."""
        cache0 = self.cache
        for b in self.buckets:
            fn = lookup_program(_compiled_prefill, self.model, b)
            row, _ = fn(self.params, jnp.zeros((1, b), jnp.int32),
                        jnp.asarray(1, jnp.int32))
            self.cache = _insert_row(self.cache, row,
                                     jnp.asarray(0, jnp.int32))
        out = self._step_fn(self.params, self.cache,
                            jnp.asarray(self.tok),
                            jnp.asarray(self.pos))
        if self._verify_fn is not None:
            out = self._verify_fn(
                self.params, out[0],
                jnp.zeros((self.num_slots, self.spec_tokens + 1),
                          jnp.int32),
                jnp.zeros((self.num_slots,), jnp.int32))
        # graftcheck: disable=host-sync-in-loop -- startup-only drain
        # of the warmup dispatches; runs once per process, never in
        # the decode loop
        jax.block_until_ready(out)
        self.cache = cache0
        warm = getattr(speculator, "warmup", None)
        if warm is not None:
            warm()

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self) -> float:
        return float(self.active.sum()) / self.num_slots

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Would this request's full trajectory fit the cache?
        Deliberately WITHOUT speculative slack: a tightly-sized cache
        still serves every request — ``can_verify()`` makes the
        scheduler fall back to the plain decode step for the
        iterations where a slot lacks verify write headroom
        (serve/run.py sizes the default cache with ``spec_tokens`` of
        slack so that fallback stays rare)."""
        return (prompt_len <= max(self.buckets)
                and prompt_len + max_new_tokens <= self.max_len)

    def can_verify(self) -> bool:
        """Every active slot has verify write headroom (a continuation
        resumed onto a tightly-sized cache may not — those slots take
        the PLAIN path inside the verify dispatch instead; see
        :meth:`verify_fallback_slots`)."""
        if self._verify_fn is None:
            return False
        act = self.active
        return bool((self.pos[act] + self.spec_tokens + 1
                     <= self.max_len).all())

    def verify_fallback_slots(self) -> Optional[list]:
        """Which ACTIVE slots lack verify write headroom this
        iteration. ``None`` = speculation cannot run at all
        (``spec_tokens`` off, or a tight slot is too shallow to
        re-feed — the scheduler takes the whole-batch plain step);
        ``[]`` = full verify; a non-empty list = MIXED dispatch: the
        named slots take the plain path INSIDE the verify program
        (``verify_step``'s ``tails``) while every other slot
        speculates — one tight slot no longer costs the whole batch
        its speculation."""
        if self._verify_fn is None:
            return None
        k = self.spec_tokens
        out = []
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            if self.pos[s] + k + 1 <= self.max_len:
                continue
            if self.pos[s] < k:
                # Too shallow to re-feed a k-token window (only
                # possible when max_len < ~2k: a tiny user-pinned
                # cache) — whole-batch fallback keeps correctness.
                return None
            out.append(s)
        return out

    def verify_step(self, props: np.ndarray, tails=None
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """One SPECULATIVE decode step: verify ``props``
        [num_slots, spec_tokens] draft proposals for every slot in one
        program dispatch. Returns ``(toks, acc)`` — ``toks``
        [num_slots, spec_tokens + 1] is the target model's greedy
        chain at each fed position and ``acc[s]`` how many of its
        leading entries slot ``s`` emits this step (accepted proposals
        + the bonus token); inactive rows are garbage the scheduler
        never reads. Rollback-on-reject is pure position bookkeeping:
        a rejected proposal's cache row sits PAST the slot's new
        authoritative position, and the next verify (or insert) writes
        over it before any attend can reach it — positions, not the
        cache, are the source of truth on depth.

        **Per-slot fallback** (``tails``): a slot named by
        :meth:`verify_fallback_slots` lacks ``pos + k + 1`` write
        headroom, so instead of proposals it is fed its OWN last ``k``
        accepted tokens plus the pending one at positions
        ``pos-k .. pos`` — deterministic re-computation rewrites
        bit-identical K/V over what the cache already holds (K/V at a
        position depend only on that position's token and the cache
        BELOW it, all unchanged), and the argmax at the LAST fed
        position is exactly the plain step's next token. Same program,
        same shapes, zero census drift; the slot retires 1 token
        (``acc == 1``, surfaced in ``toks[s, 0]``) while every other
        slot speculates. ``tails[s]`` must hold the slot's last
        ``k + 1`` history tokens (ending in the pending token — the
        scheduler's ``prompt + tokens`` tail). Which slots fell back
        this dispatch is readable at ``last_verify_fallback``."""
        from tensorflow_distributed_tpu.serve.speculate import (
            accept_length)
        if self._verify_fn is None:
            raise RuntimeError(
                "verify_step needs the engine built with "
                "spec_tokens > 0")
        k = self.spec_tokens
        # graftcheck: disable=host-sync-in-loop -- normalizes the HOST
        # proposal array the speculator handed in; no device value
        props = np.asarray(props, np.int32).reshape(self.num_slots, k)
        tails = dict(tails or {})
        fallback = []
        start = self.pos.copy()
        toks_in = np.concatenate([self.tok[:, None], props], axis=1)
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            if self.pos[s] + k + 1 <= self.max_len:
                continue
            tail = tails.get(s)
            if tail is None or len(tail) < k + 1 or self.pos[s] < k:
                raise RuntimeError(
                    f"slot {s} lacks verify headroom and no usable "
                    f"tail was provided — verify_fallback_slots() is "
                    f"the guard (the scheduler supplies tails or "
                    f"falls back to step())")
            # graftcheck: disable=host-sync-in-loop -- normalizes the
            # HOST history tail the scheduler handed in (no device
            # value); only the rare headroom-starved slots
            window = np.asarray(list(tail)[-(k + 1):], np.int32)
            if window[-1] != self.tok[s]:
                raise RuntimeError(
                    f"slot {s} fallback tail must end in the pending "
                    f"token {int(self.tok[s])}, got {int(window[-1])}")
            toks_in[s] = window
            start[s] = self.pos[s] - k
            fallback.append(s)
        tok, pos = self._h2d(toks_in), self._h2d(start)
        self.cache, nxt, ok = self._dispatch_verify(tok, pos)
        step_no = self.decode_steps + 1

        def fetch():
            if self._plan:
                self._plan.decode_stall_sleep(step_no)
            # graftcheck: disable=host-sync-in-loop -- the engine's
            # OUTPUT, same contract as step(): ONE fetch per dispatch
            # (the [S, k+1] chain + per-slot ok flags) drives
            # acceptance, streaming, and NaN containment
            return jax.device_get((nxt, ok))

        with self._span("verify_step",
                        live=int(self.active.sum()),
                        fallback=len(fallback)):
            if (self._watchdog is not None
                    and self._watchdog.sync_timeout_s > 0):
                nxt, ok = self._watchdog.decode(fetch, step_no)
            else:
                nxt, ok = fetch()
        self._last_ok = ok
        # graftcheck: disable=host-sync-in-loop -- nxt is already the
        # fetched HOST array (the one watched fetch above); this is a
        # view, not a second sync
        nxt = np.asarray(nxt).copy()
        acc = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            if s in fallback:
                # Plain path inside the verify dispatch: the target's
                # next token sits at the LAST fed index (after the
                # pending token); surface it where the scheduler reads
                # retired tokens (toks[s, :acc]).
                nxt[s, 0] = nxt[s, k]
                acc[s] = 1
                self.tok[s] = nxt[s, 0]
                self.pos[s] += 1
                continue
            a = accept_length(props[s], nxt[s])
            acc[s] = a + 1                       # + the bonus token
            self.tok[s] = nxt[s, a]
            self.pos[s] += a + 1
        self.decode_steps += 1
        self.verify_steps += 1
        self._last_verify_fallback = fallback
        return nxt, acc

    @property
    def last_verify_fallback(self) -> list:
        """Slots that took the per-slot plain path in the most recent
        verify dispatch (the scheduler excludes them from speculation
        accounting)."""
        return list(self._last_verify_fallback)

    def prefill(self, prompt: np.ndarray, slot: int) -> int:
        """Admit a request into ``slot``: bucketed prefill, row insert,
        greedy first token. Returns the first generated token."""
        # graftcheck: disable=host-sync-in-loop -- normalizes the HOST
        # prompt the scheduler handed in; no device value involved
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        bucket = pick_bucket(plen, self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = prompt
        fn = lookup_program(_compiled_prefill, self.model, bucket)
        self._buckets_used.add(bucket)
        # The prefill span covers the whole admission wall — dispatch,
        # row insert (nested), and the blocking first-token fetch that
        # actually waits for the compute (dispatches are async, so a
        # span around the calls alone would show ~0 and misattribute
        # the wall to whatever blocks next).
        with self._span(f"prefill_b{bucket}", slot=slot,
                        prompt_len=plen):
            row, first = fn(self.params, jnp.asarray(padded),
                            jnp.asarray(plen, jnp.int32))
            with self._span("insert_row", slot=slot):
                self.cache = _insert_row(self.cache, row,
                                         jnp.asarray(slot, jnp.int32))
            # graftcheck: disable=host-sync-in-loop -- the TTFT point:
            # the first token must reach the host to be streamed; one
            # scalar per ADMISSION, not per decode step
            first_tok = int(jax.device_get(first)[0])
        self.tok[slot] = first_tok
        self.pos[slot] = plen
        self.active[slot] = True
        self.prefills += 1
        return first_tok

    def step(self) -> np.ndarray:
        """One decode step over every slot; returns the [num_slots]
        next-token array (entries for inactive slots are garbage — the
        scheduler only reads active ones)."""
        if (self.pos[self.active] >= self.max_len).any():
            raise RuntimeError(
                "an active slot is at max_len — the scheduler admitted "
                "a request that cannot fit (fits() is the guard)")
        # Host->device conversion of the slot scalars stays OUTSIDE the
        # transfer guard: these two tiny explicit uploads are the
        # engine's designed input path.
        tok, pos = self._h2d(self.tok), self._h2d(self.pos)
        self.cache, nxt, ok = self._dispatch_step(tok, pos)
        if self._declared_cache is not None and self.decode_steps == 0:
            # First decode step: the cache must come back in the
            # layout it was created with — sharding drift here
            # re-lays-out every subsequent step. Armed by --check, and
            # ALWAYS under TP (a drifted head shard silently
            # re-gathers the cache every step).
            graftcheck.assert_sharding_contract(
                self.cache, self._declared_cache, what="decode cache")
        step_no = self.decode_steps + 1

        def fetch():
            # An injected decode_stall sleeps here, INSIDE the watched
            # region, so the watchdog sees exactly the hang a wedged
            # device would produce.
            if self._plan:
                self._plan.decode_stall_sleep(step_no)
            # graftcheck: disable=host-sync-in-loop -- the engine's
            # OUTPUT: tokens + per-slot ok flags must land on host
            # every step for EOS/budget termination, streaming, and
            # NaN containment; ONE [num_slots] fetch per step is the
            # contract, and the decode program stays dispatched ahead
            return jax.device_get((nxt, ok))

        with self._span("decode_step", live=int(self.active.sum())):
            if (self._watchdog is not None
                    and self._watchdog.sync_timeout_s > 0):
                nxt, ok = self._watchdog.decode(fetch, step_no)
            else:
                nxt, ok = fetch()
        self._last_ok = ok
        act = self.active
        self.tok[act] = nxt[act]
        self.pos[act] += 1
        self.decode_steps += 1
        return nxt

    def free(self, slot: int) -> None:
        """Release a slot (host bookkeeping only; the row's stale cache
        is replaced wholesale by the next insert)."""
        self.active[slot] = False
        self.tok[slot] = 0
        self.pos[slot] = 0

    # -- serve-under-fire surface (scheduler-facing) ----------------------

    def take_bad_slots(self):
        """ACTIVE slots whose last decode step produced non-finite
        logits — the containment signal the scheduler acts on
        (quarantine + re-prefill of ONLY those slots). Rides the decode
        program's per-slot ok flags; no extra device work. Inactive
        rows are excluded by construction: a freed slot's stale NaN row
        keeps flagging until the next insert overwrites it, and that is
        garbage nobody reads."""
        if self._last_ok is None:
            return []
        return [s for s in range(self.num_slots)
                if self.active[s] and not self._last_ok[s]]

    def poison_slot(self, slot: int) -> None:
        """slot_nan fault drill: NaN-fill ``slot``'s KV-cache row ON
        DEVICE, so the next decode step's logits for that slot are
        genuinely non-finite through the real attention math (not a
        spoofed flag)."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(
                f"slot_nan slot {slot} out of range [0, "
                f"{self.num_slots})")
        floats = sum(
            1 for c in jax.tree_util.tree_leaves(self.cache)
            if getattr(c, "ndim", 0)
            and jnp.issubdtype(c.dtype, jnp.floating))
        if not floats:
            raise ValueError(
                "slot_nan: the decode cache has no float leaves to "
                "poison")
        self.cache = _poison_row_jit(self.cache,
                                     jnp.asarray(slot, jnp.int32))

    def swap_params(self, new_params) -> None:
        """LIVE WEIGHT SWAP: replace the serving params between decode
        steps without draining slots or recompiling. The contract that
        makes this safe — identical tree structure, leaf shapes/dtypes,
        and sharding layout — is asserted here (shapes/dtypes by direct
        comparison, placement via the graftcheck sharding-contract
        checker), because any mismatch would silently retrace the hot
        decode program instead of hitting its jit cache. In-flight KV
        caches are untouched: swapping to the same checkpoint is
        token-identical by construction (pinned in
        tests/test_serve_fire.py)."""
        if (jax.tree_util.tree_structure(new_params)
                != jax.tree_util.tree_structure(self.params)):
            raise ValueError(
                "live weight swap: new params tree structure differs "
                "from the serving params (different architecture?)")
        mismatches = []

        def cmp(path, old, new):
            if (getattr(old, "shape", None) != getattr(new, "shape",
                                                       None)
                    or getattr(old, "dtype", None) != getattr(
                        new, "dtype", None)):
                mismatches.append(
                    f"  {jax.tree_util.keystr(path)}: "
                    f"{getattr(old, 'shape', '?')}/"
                    f"{getattr(old, 'dtype', '?')} -> "
                    f"{getattr(new, 'shape', '?')}/"
                    f"{getattr(new, 'dtype', '?')}")
            return old

        jax.tree_util.tree_map_with_path(cmp, self.params, new_params)
        if mismatches:
            raise ValueError(
                "live weight swap: leaf shape/dtype drift (the hot "
                "decode program would retrace):\n"
                + "\n".join(mismatches[:10]))
        graftcheck.assert_sharding_contract(
            new_params, graftcheck.sharding_tree(self.params),
            what="swapped params")
        self.params = new_params
        self.swaps += 1
