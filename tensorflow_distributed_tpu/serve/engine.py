"""Slot-based continuous-batching decode engine.

One jitted single-token decode program runs over a fixed
``[num_slots, max_len]`` KV cache for the life of the process. Slots
are independently occupied and freed BETWEEN steps, so the request set
changes with zero recompilation:

- **insert**: a bucketed prefill program (one compile per bucket
  length, shared with generate()'s prefill via
  models.generate.prefill_cache) fills a fresh ``[1, max_len]`` cache
  row, and one jitted ``dynamic_update_slice`` per cache leaf drops it
  into the slot — the slot index is a traced scalar, so every slot
  uses the SAME program;
- **decode**: per-row positions (models/transformer.py writes each
  row's K/V at ITS position and masks attention past it) let slot 0
  sit at depth 700 while slot 3 is at depth 12 — one program, any
  mix of depths;
- **free**: host-side bookkeeping only. A freed slot keeps riding the
  batched step (static shapes), writing into its own row at position
  0 with its mask clamped to one column — garbage that the next
  insert's full-row overwrite replaces, and that no other row can
  attend (attention never crosses rows).

Greedy sampling only: the engine's contract (pinned in
tests/test_serve.py) is token-identical output to one-shot greedy
``generate()`` per request — continuous batching must not change
results.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.analysis import runtime as graftcheck
from tensorflow_distributed_tpu.models.generate import (
    decode_token, lookup_program, prefill_cache)
from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.serve.buckets import (
    default_buckets, pick_bucket)


@functools.lru_cache(maxsize=64)
def _compiled_prefill(model, bucket: int):
    """One jitted prefill program per (model, bucket length): prompt
    padded to ``bucket`` -> (cache row [1, max_len, ...], greedy first
    token from the TRUE last position). ``true_len`` is a traced
    scalar, so every prompt length sharing a bucket shares the
    executable."""

    @jax.jit
    def run(params, prompt, true_len):
        logits, cache = prefill_cache(model, params, prompt)
        last = jax.lax.dynamic_index_in_dim(
            logits, true_len - 1, axis=1, keepdims=False)   # [1, V]
        return cache, jnp.argmax(last, axis=-1).astype(jnp.int32)

    return observe_device.instrument(f"serve_prefill_b{bucket}", run)


@functools.lru_cache(maxsize=8)
def _compiled_step(model):
    """THE decode program: one greedy token for every slot at its own
    depth. Compiled once per (model, num_slots) — the shapes come from
    the arguments, so one engine reuses one executable forever."""

    @jax.jit
    def run(params, cache, tok, pos):
        last, cache = decode_token(model, params, cache, tok, pos)
        return cache, jnp.argmax(last, axis=-1).astype(jnp.int32)

    return observe_device.instrument("serve_decode_step", run)


@jax.jit
def _insert_row_jit(cache, row, slot):
    """Drop a prefilled [1, ...] cache row into ``slot`` of the engine
    cache — ``slot`` is traced, so all slots share the program. Scalar
    leaves (the compat ``index``) pass through: positions are the
    authority on depth."""

    def put(c, r):
        if getattr(r, "ndim", 0) and r.shape[:1] == (1,):
            return jax.lax.dynamic_update_slice(
                c, r.astype(c.dtype), (slot,) + (0,) * (c.ndim - 1))
        return c

    return jax.tree_util.tree_map(put, cache, row)


_insert_row = observe_device.instrument("serve_insert_row",
                                        _insert_row_jit)


class SlotDecodeEngine:
    """The slot cache + the three programs (prefill/insert/step),
    with host-side slot bookkeeping. The scheduler (serve/scheduler.py)
    decides WHEN to prefill vs decode; this class owns WHAT runs on
    device."""

    def __init__(self, model, params, num_slots: int,
                 buckets: Optional[Sequence[int]] = None,
                 min_bucket: int = 16, check: bool = False):
        cfg = model.cfg
        if not cfg.causal:
            raise ValueError("SlotDecodeEngine needs a causal model")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.model = model
        self.params = params
        self.num_slots = num_slots
        self.max_len = cfg.max_len
        self.buckets: Tuple[int, ...] = (
            tuple(buckets) if buckets
            else default_buckets(cfg.max_len, min_bucket,
                                 cap=cfg.max_len))
        if max(self.buckets) > cfg.max_len:
            raise ValueError(
                f"largest bucket {max(self.buckets)} exceeds the "
                f"model's max_len {cfg.max_len}")
        self.cache = self._zero_cache()
        self.tok = np.zeros((num_slots,), np.int32)
        self.pos = np.zeros((num_slots,), np.int32)
        self.active = np.zeros((num_slots,), bool)
        # Distinct prefill programs this engine has invoked — one per
        # bucket actually used, each a single compiled executable (the
        # bench asserts <= len(buckets)); generate.compile_cache_stats()
        # carries the process-wide hit/miss view.
        self._buckets_used: set = set()
        self.prefills = 0
        self.decode_steps = 0
        self._step_fn = lookup_program(_compiled_step, self.model)
        # --check (graftcheck's runtime layer): the decode step runs
        # under jax.transfer_guard("disallow"), and the cache layout
        # after the first step is asserted against the layout the
        # cache was created with (analysis/runtime.py).
        self._check = check
        self._declared_cache = (graftcheck.sharding_tree(self.cache)
                                if check else None)

    def _zero_cache(self):
        """A zeroed [num_slots, max_len, ...] cache pytree, shaped via
        eval_shape (no device work, no params flops)."""
        tok = jnp.zeros((self.num_slots, 1), jnp.int32)
        pos = jnp.zeros((self.num_slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p, t, q: self.model.apply(
                {"params": p}, t, decode=True, positions=q,
                mutable=["cache"])[1]["cache"],
            self.params, tok, pos)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    @property
    def prefill_compiles(self) -> int:
        """Distinct prefill programs invoked (one per bucket used)."""
        return len(self._buckets_used)

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self) -> float:
        return float(self.active.sum()) / self.num_slots

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Would this request's full trajectory fit the cache?"""
        return (prompt_len <= max(self.buckets)
                and prompt_len + max_new_tokens <= self.max_len)

    def prefill(self, prompt: np.ndarray, slot: int) -> int:
        """Admit a request into ``slot``: bucketed prefill, row insert,
        greedy first token. Returns the first generated token."""
        # graftcheck: disable=host-sync-in-loop -- normalizes the HOST
        # prompt the scheduler handed in; no device value involved
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        bucket = pick_bucket(plen, self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = prompt
        fn = lookup_program(_compiled_prefill, self.model, bucket)
        self._buckets_used.add(bucket)
        row, first = fn(self.params, jnp.asarray(padded),
                        jnp.asarray(plen, jnp.int32))
        self.cache = _insert_row(self.cache, row,
                                 jnp.asarray(slot, jnp.int32))
        # graftcheck: disable=host-sync-in-loop -- the TTFT point: the
        # first token must reach the host to be streamed; one scalar
        # per ADMISSION, not per decode step
        first_tok = int(jax.device_get(first)[0])
        self.tok[slot] = first_tok
        self.pos[slot] = plen
        self.active[slot] = True
        self.prefills += 1
        return first_tok

    def step(self) -> np.ndarray:
        """One decode step over every slot; returns the [num_slots]
        next-token array (entries for inactive slots are garbage — the
        scheduler only reads active ones)."""
        if (self.pos[self.active] >= self.max_len).any():
            raise RuntimeError(
                "an active slot is at max_len — the scheduler admitted "
                "a request that cannot fit (fits() is the guard)")
        # Host->device conversion of the slot scalars stays OUTSIDE the
        # transfer guard: these two tiny explicit uploads are the
        # engine's designed input path.
        tok, pos = jnp.asarray(self.tok), jnp.asarray(self.pos)
        with graftcheck.transfer_guard(self._check):
            self.cache, nxt = self._step_fn(self.params, self.cache,
                                            tok, pos)
        if self._check and self.decode_steps == 0:
            # First decode step: the cache must come back in the
            # layout it was created with — sharding drift here
            # re-lays-out every subsequent step.
            graftcheck.assert_sharding_contract(
                self.cache, self._declared_cache, what="decode cache")
        # graftcheck: disable=host-sync-in-loop -- the engine's OUTPUT:
        # tokens must land on host every step for EOS/budget
        # termination and streaming; [num_slots] int32 per step is the
        # contract, and the decode program itself stays dispatched
        # ahead of it
        nxt = np.asarray(jax.device_get(nxt))
        act = self.active
        self.tok[act] = nxt[act]
        self.pos[act] += 1
        self.decode_steps += 1
        return nxt

    def free(self, slot: int) -> None:
        """Release a slot (host bookkeeping only; the row's stale cache
        is replaced wholesale by the next insert)."""
        self.active[slot] = False
        self.tok[slot] = 0
        self.pos[slot] = 0
