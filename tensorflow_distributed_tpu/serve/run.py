"""``mode=serve`` driver: build/restore a causal LM, run a request
workload through the continuous-batching engine, report.

Workloads: ``--serve.requests file.jsonl`` (one JSON object per line:
``{"prompt": [ids...], "max_new_tokens": 32, "eos_id": 5,
"arrival_s": 0.25}`` — ``prompt`` may be a ``"text"`` string instead
when ``--dataset text`` supplies a tokenizer) or, with no file, a
synthetic open-loop workload: ``--serve.num-requests`` random prompts
with mixed lengths in [``--serve.prompt-len-min``,
``--serve.prompt-len-max``], arriving at ``--serve.arrival-rate``
req/s (0 = all queued at t=0).

``--checkpoint-dir`` restores trained weights (EMA preferred, like
mode=eval/generate); without one the model serves FRESH-INIT params —
a load-testing/benchmarking mode, clearly labeled in the output.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List

import numpy as np

from tensorflow_distributed_tpu.config import TrainConfig
from tensorflow_distributed_tpu.serve.buckets import (
    default_buckets, parse_buckets)
from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
from tensorflow_distributed_tpu.serve.scheduler import Request, Scheduler


def _workload(cfg: TrainConfig, vocab_size: int,
              encode=None) -> List[Request]:
    serve = cfg.serve
    if serve.requests:
        reqs = []
        with open(serve.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                if "text" in obj:
                    if encode is None:
                        raise ValueError(
                            f"{serve.requests}:{i + 1}: string prompts "
                            f"need --dataset text (its tokenizer "
                            f"defines the vocabulary)")
                    ids = encode(obj["text"])
                else:
                    ids = [int(t) for t in obj["prompt"]]
                if not ids:
                    raise ValueError(
                        f"{serve.requests}:{i + 1}: empty prompt")
                # Id bounds are checked against the BUILT model's
                # vocab in serve_run (like generate_only): with
                # synthetic_vocab unset the family default (e.g.
                # 50257 for gpt_lm small) is the real bound.
                # graftcheck: disable=host-sync-in-loop -- request-file
                # parsing runs once, before the engine exists; this
                # materializes host JSON, not device buffers
                reqs.append(Request(
                    rid=len(reqs), prompt=np.asarray(ids, np.int32),
                    max_new_tokens=int(obj.get("max_new_tokens",
                                               serve.max_new_tokens)),
                    eos_id=int(obj.get("eos_id", serve.eos_id)),
                    arrival_s=float(obj.get("arrival_s", 0.0))))
        if not reqs:
            raise ValueError(f"{serve.requests} names no requests")
        return reqs
    # Synthetic open-loop workload: mixed lengths, deterministic by
    # seed, uniformly spaced arrivals at the configured rate.
    rng = np.random.default_rng(cfg.seed)
    reqs = []
    for i in range(serve.num_requests):
        plen = int(rng.integers(serve.prompt_len_min,
                                serve.prompt_len_max + 1))
        prompt = rng.integers(0, vocab_size, size=plen).astype(np.int32)
        arrival = (i / serve.arrival_rate if serve.arrival_rate > 0
                   else 0.0)
        reqs.append(Request(rid=i, prompt=prompt,
                            max_new_tokens=serve.max_new_tokens,
                            eos_id=serve.eos_id, arrival_s=arrival))
    return reqs


def serve_run(cfg: TrainConfig) -> Dict:
    """Run the serve workload; returns the summary dict (per-request
    records ride the observe JSONL)."""
    cfg.validate()
    from tensorflow_distributed_tpu.observe import (
        device as observe_device)
    from tensorflow_distributed_tpu.observe import (
        registry as registry_mod)
    from tensorflow_distributed_tpu.observe.registry import (
        JsonlSink, MetricsRegistry, host_tags)
    from tensorflow_distributed_tpu.parallel.mesh import (
        bootstrap, is_chief, make_mesh)
    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    from tensorflow_distributed_tpu.train.loop import (
        _build_model_and_state, _GenTask)

    bootstrap()
    mesh = make_mesh(cfg.mesh)

    encode = None
    if cfg.dataset == "text":
        from tensorflow_distributed_tpu.data.lm import text_codec
        encode, _, vocab = text_codec(cfg.data_dir, cfg.text_tokenizer,
                                      cfg.bpe_vocab_size)
    else:
        vocab = cfg.synthetic_vocab or 64
    requests = _workload(cfg, vocab, encode)

    max_prompt = max(len(r.prompt) for r in requests)
    # Per-request trajectory bound (what actually has to fit the
    # cache); bucket padding is prefill-only slack and is clamped to
    # the cache length by the ladder cap below.
    need = max(len(r.prompt) + r.max_new_tokens for r in requests)
    if cfg.seq_len and need > cfg.seq_len:
        raise ValueError(
            f"--seq-len {cfg.seq_len} cannot hold the workload: the "
            f"longest request (prompt + new tokens) needs a "
            f"{need}-token cache")
    if not cfg.seq_len:
        # Size the cache to the workload (fresh-init serving). A
        # checkpointed model's max_len is pinned by training — set
        # --seq-len to the trained length explicitly.
        cfg = dataclasses.replace(cfg, seq_len=max(need, 32))
    buckets = (parse_buckets(cfg.serve.buckets) if cfg.serve.buckets
               else default_buckets(max_prompt, cap=cfg.seq_len))

    shim = _GenTask(vocab_size=vocab, sample_input=np.zeros(
        (max(2, dict(mesh.shape).get("data", 1)), cfg.seq_len),
        np.int32))
    model, state = _build_model_and_state(cfg, mesh, shim)
    if cfg.dataset != "text":
        # The embedding gather would silently CLAMP out-of-range ids —
        # bound-check against the BUILT model's vocabulary (the family
        # default when synthetic_vocab is unset), like generate_only.
        for r in requests:
            bad = [int(t) for t in r.prompt
                   if not 0 <= t < model.cfg.vocab_size]
            if bad:
                raise ValueError(
                    f"request {r.rid}: prompt ids {bad} outside the "
                    f"model vocabulary [0, {model.cfg.vocab_size})")
    restored = False
    if cfg.checkpoint_dir:
        # Same restore semantics as mode=generate: local-SGD
        # checkpoints persist the replica stack — average it into the
        # plain template (train/loop.py::generate_only).
        if cfg.param_sync_every > 1:
            state = ckpt.restore_averaged(cfg.checkpoint_dir, state)
        else:
            state = ckpt.restore(cfg.checkpoint_dir, state)
        restored = True
    params = state.params if state.ema is None else state.ema

    sinks = []
    if cfg.observe.metrics_jsonl:
        sinks.append(JsonlSink(cfg.observe.metrics_jsonl))
    registry = MetricsRegistry(sinks=sinks, enabled=is_chief(),
                               tags=host_tags(mesh, cfg),
                               max_records=cfg.observe.max_records)
    # Install as the process's active registry so library-level events
    # (the engine's compiled-program registrations, generate's
    # compile-cache misses) land in this run's JSONL; arm the program
    # registry under the same sink-configured condition the training
    # Observatory uses.
    registry_mod.set_active(registry)
    programs_armed = bool(sinks) and cfg.observe.programs
    if programs_armed:
        observe_device.set_enabled(True)
    on_token = None
    if cfg.serve.stream and is_chief():
        def on_token(rid: int, tok: int, done: bool) -> None:
            print(f"[serve] rid={rid} tok={tok}"
                  + (" <done>" if done else ""), flush=True)

    engine = SlotDecodeEngine(model, params, cfg.serve.num_slots,
                              buckets=buckets, check=cfg.check)
    sched = Scheduler(engine, decode_priority=cfg.serve.decode_priority,
                      registry=registry, on_token=on_token)
    try:
        done = sched.run(requests)
        if programs_armed:
            budget = observe_device.hbm_budget()
            if budget:
                registry.emit("hbm_budget", **budget)
    finally:
        if programs_armed:
            observe_device.set_enabled(False)
        if registry_mod.get_active() is registry:
            registry_mod.set_active(None)
        registry.close()
    summary = dict(sched.summary)
    ttfts = np.asarray([c.ttft_s for c in done])
    summary["ttft_ms_p50"] = round(1e3 * float(np.percentile(ttfts, 50)), 3)
    summary["ttft_ms_p95"] = round(1e3 * float(np.percentile(ttfts, 95)), 3)
    summary["tok_ms_mean"] = round(
        float(np.mean([c.tok_ms for c in done])), 4)
    summary["params"] = "checkpoint" if restored else "fresh-init"
    if is_chief():
        print(f"[serve] {summary['requests']} requests, "
              f"{summary['total_new_tokens']} tokens in "
              f"{summary['wall_s']}s — "
              f"{summary['tokens_per_sec']} tok/s, occupancy "
              f"{summary['mean_slot_occupancy']}, ttft p50 "
              f"{summary['ttft_ms_p50']}ms / p95 "
              f"{summary['ttft_ms_p95']}ms, "
              f"{summary['prefill_compiles']} prefill programs "
              f"(buckets {summary['buckets']}), "
              f"{summary['params']} params", flush=True)
        if cfg.observe.metrics_jsonl:
            print(f"[observe] serve metrics: "
                  f"{cfg.observe.metrics_jsonl} (summarize: python -m "
                  f"tensorflow_distributed_tpu.observe.report "
                  f"{cfg.observe.metrics_jsonl})", flush=True)
    return summary
