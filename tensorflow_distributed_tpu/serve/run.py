"""``mode=serve`` driver: build/restore a causal LM, run a request
workload through the continuous-batching engine, report.

Workloads: ``--serve.requests file.jsonl`` (one JSON object per line:
``{"prompt": [ids...], "max_new_tokens": 32, "eos_id": 5,
"arrival_s": 0.25}`` — ``prompt`` may be a ``"text"`` string instead
when ``--dataset text`` supplies a tokenizer) or, with no file, a
synthetic open-loop workload: ``--serve.num-requests`` random prompts
with mixed lengths in [``--serve.prompt-len-min``,
``--serve.prompt-len-max``], arriving at ``--serve.arrival-rate``
req/s (0 = all queued at t=0). ``--serve.trace`` reshapes the
synthetic arrival process: ``poisson`` (exponential interarrivals),
``bursty`` (whole bursts land at once), ``diurnal`` (sinusoidally
modulated rate — a day compressed into the run), or a ``.jsonl`` file
of per-request ``{"arrival_s": t}`` offsets.

``--checkpoint-dir`` restores trained weights (EMA preferred, like
mode=eval/generate); without one the model serves FRESH-INIT params —
a load-testing/benchmarking mode, clearly labeled in the output.

Serve observatory (README "Serve tracing & SLO monitoring"):
``--observe.trace`` writes the per-request Perfetto span tree,
``--observe.slo`` arms the live burn-rate monitor (with a periodic
one-line status print), and ``--observe.export-every`` /
``--observe.export-path`` dump atomic rolling-metrics snapshots — all
bundled by :class:`observe.hub.ServeObservatory` and continued across
a journal resume (trace and JSONL both).

Serve-under-fire wiring (README "Serving under faults"): a
``--resilience.fault-plan`` with serve kinds drives the scheduler's
containment paths, ``--resilience.sync-timeout-s`` arms the decode
watchdog, ``--serve.journal`` makes progress crash-durable (an
existing non-empty journal means RESUME: finished requests skip,
in-flight ones re-admit as continuations), and ``--checkpoint-dir``
doubles as the live-weight-swap source (``reload@K`` faults, via
train.checkpoint.restore_params).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List

import numpy as np

from tensorflow_distributed_tpu.config import TrainConfig
from tensorflow_distributed_tpu.serve import journal as journal_mod
from tensorflow_distributed_tpu.serve.buckets import (
    default_buckets, parse_buckets)
from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
from tensorflow_distributed_tpu.serve.scheduler import Request, Scheduler


def _arrivals(serve, n: int, rng) -> List[float]:
    """Arrival offsets for the synthetic workload, shaped by
    ``serve.trace`` (all deterministic under the run seed):

    - ``""``: uniformly spaced at ``arrival_rate`` (0 = all at t=0);
    - ``poisson``: exponential interarrivals at the same mean rate —
      the memoryless open-loop process real traffic approximates;
    - ``bursty``: bursts of ~4 requests landing TOGETHER, bursts
      spaced to keep the mean rate — the pathological arrival shape a
      starvation bound exists for;
    - ``diurnal``: rate modulated sinusoidally between 0.25x and
      1.75x over the workload span — a traffic day compressed into
      one run;
    - ``*.jsonl``: explicit per-request ``{"arrival_s": t}`` lines
      (row i feeds request i; the file must cover the workload).
    """
    rate = serve.arrival_rate
    trace = serve.trace
    if trace.endswith(".jsonl"):
        offs = []
        with open(trace) as f:
            for line in f:
                line = line.strip()
                if line:
                    offs.append(float(json.loads(line)["arrival_s"]))
        if len(offs) < n:
            raise ValueError(
                f"--serve.trace {trace}: {len(offs)} arrival rows < "
                f"{n} requests")
        return offs[:n]
    if not rate:
        return [0.0] * n
    if trace == "poisson":
        return list(np.cumsum(rng.exponential(1.0 / rate, size=n)))
    if trace == "bursty":
        burst = 4
        return [(i // burst) * (burst / rate) for i in range(n)]
    if trace == "diurnal":
        out, t = [], 0.0
        for i in range(n):
            # Instantaneous rate sweeps one full "day" over the
            # workload: 1.75x at the peak, 0.25x in the trough.
            lam = rate * (1.0 + 0.75 * np.sin(2 * np.pi * i / max(n, 1)))
            out.append(t)
            t += 1.0 / lam
        return out
    return [i / rate for i in range(n)]


def _workload(cfg: TrainConfig, vocab_size: int,
              encode=None) -> List[Request]:
    serve = cfg.serve
    if serve.requests:
        reqs = []
        with open(serve.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                if "text" in obj:
                    if encode is None:
                        raise ValueError(
                            f"{serve.requests}:{i + 1}: string prompts "
                            f"need --dataset text (its tokenizer "
                            f"defines the vocabulary)")
                    ids = encode(obj["text"])
                else:
                    ids = [int(t) for t in obj["prompt"]]
                if not ids:
                    raise ValueError(
                        f"{serve.requests}:{i + 1}: empty prompt")
                # Id bounds are checked against the BUILT model's
                # vocab in serve_run (like generate_only): with
                # synthetic_vocab unset the family default (e.g.
                # 50257 for gpt_lm small) is the real bound.
                slo = str(obj.get("slo", "standard"))
                from tensorflow_distributed_tpu.serve.scheduler import (
                    SLO_CLASSES)
                if slo not in SLO_CLASSES:
                    raise ValueError(
                        f"{serve.requests}:{i + 1}: unknown slo "
                        f"{slo!r}; have {SLO_CLASSES}")
                # graftcheck: disable=host-sync-in-loop -- request-file
                # parsing runs once, before the engine exists; this
                # materializes host JSON, not device buffers
                reqs.append(Request(
                    rid=len(reqs), prompt=np.asarray(ids, np.int32),
                    max_new_tokens=int(obj.get("max_new_tokens",
                                               serve.max_new_tokens)),
                    eos_id=int(obj.get("eos_id", serve.eos_id)),
                    arrival_s=float(obj.get("arrival_s", 0.0)),
                    slo=slo, tenant=str(obj.get("tenant", "")),
                    session=str(obj.get("session", ""))))
        if not reqs:
            raise ValueError(f"{serve.requests} names no requests")
        return reqs
    # Synthetic open-loop workload: mixed lengths, deterministic by
    # seed, arrivals shaped by the trace (prompt draws happen BEFORE
    # the arrival draws so the token content is identical across
    # traces — a trace A/B compares arrival shape, nothing else; the
    # class draws come after BOTH for the same reason).
    rng = np.random.default_rng(cfg.seed)
    prompts = []
    for _ in range(serve.num_requests):
        plen = int(rng.integers(serve.prompt_len_min,
                                serve.prompt_len_max + 1))
        prompts.append(
            rng.integers(0, vocab_size, size=plen).astype(np.int32))
    sessions = [""] * serve.num_requests
    if serve.session_turns > 1:
        # Multi-turn conversations: consecutive requests group into
        # sessions; each turn's prompt EXTENDS the previous turn's (a
        # client re-sending the conversation so far plus new text).
        # Drawn AFTER the base prompts so the first turns' content is
        # identical to the session-less workload at the same seed.
        k = serve.session_turns
        for g in range(0, serve.num_requests, k):
            sid = f"s{g // k}"
            for j in range(g, min(g + k, serve.num_requests)):
                sessions[j] = sid
                if j > g:
                    prompts[j] = np.concatenate(
                        [prompts[j - 1], prompts[j]])
    arrivals = _arrivals(serve, serve.num_requests, rng)
    slos = ["standard"] * serve.num_requests
    if serve.slo_mix:
        from tensorflow_distributed_tpu.serve.scheduler import (
            SLO_CLASSES, parse_slo_mix)
        mix = parse_slo_mix(serve.slo_mix)
        edges = np.cumsum([mix.get(c, 0.0) for c in SLO_CLASSES])
        draws = rng.random(serve.num_requests)
        slos = [SLO_CLASSES[int(np.searchsorted(edges, d,
                                                side="right").clip(
                                                    0, len(edges) - 1))]
                for d in draws]
    return [Request(rid=i, prompt=p,
                    max_new_tokens=serve.max_new_tokens,
                    eos_id=serve.eos_id, arrival_s=float(a),
                    slo=slos[i],
                    tenant=(f"t{i % serve.tenants}"
                            if serve.tenants > 1 else ""),
                    session=sessions[i])
            for i, (p, a) in enumerate(zip(prompts, arrivals))]


def serve_run(cfg: TrainConfig) -> Dict:
    """Run the serve workload; returns the summary dict (per-request
    records ride the observe JSONL)."""
    cfg.validate()
    from tensorflow_distributed_tpu.observe import (
        device as observe_device)
    from tensorflow_distributed_tpu.observe.hub import ServeObservatory
    from tensorflow_distributed_tpu.observe.registry import host_tags
    from tensorflow_distributed_tpu.parallel.mesh import (
        bootstrap, is_chief, make_mesh)
    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    from tensorflow_distributed_tpu.train.loop import (
        _build_model_and_state, _GenTask)

    bootstrap()
    mesh = make_mesh(cfg.mesh)
    tp = cfg.serve.mesh_model
    if tp > 1:
        # Tensor-parallel replica: the engine's programs build over a
        # [data=1, model=tp] mesh of this replica's own — attention
        # heads / MLP width / the cache's head axis shard over
        # "model" (README "Tensor-parallel serving"). Validated here,
        # where devices and the model facts are both known; the
        # config layer only vets tp >= 1.
        import jax
        from tensorflow_distributed_tpu.analysis.planner.candidates \
            import MODEL_FAMILIES, model_facts
        from tensorflow_distributed_tpu.config import MeshConfig
        devs = jax.devices()
        if len(devs) < tp:
            raise ValueError(
                f"--serve.mesh-model {tp} needs {tp} devices, have "
                f"{len(devs)}")
        facts = model_facts(MODEL_FAMILIES[cfg.model],
                            cfg.model_size or "")
        nk = cfg.n_kv_heads or facts.n_heads
        if facts.n_heads % tp or nk % tp:
            raise ValueError(
                f"--serve.mesh-model {tp} must divide n_heads "
                f"{facts.n_heads} and n_kv_heads {nk}: attention "
                f"heads and the KV cache's head axis shard over the "
                f"model axis")
        if (cfg.dataset != "text" and not cfg.shard_vocab
                and facts.vocab_size % tp):
            raise ValueError(
                f"--serve.mesh-model {tp} must divide the vocab "
                f"{facts.vocab_size}: the TP head is vocab-parallel. "
                f"Pass --shard-vocab true (pads the table to a "
                f"multiple of the model axis; the checkpoint must be "
                f"trained with the same flag) or pick a width that "
                f"divides")
        mesh = make_mesh(MeshConfig(data=1, model=tp), devs[:tp])
        if is_chief():
            print(f"[serve] tensor-parallel replica: model={tp} over "
                  f"{tp} device(s) (params + KV cache head-sharded)",
                  flush=True)

    encode = None
    if cfg.dataset == "text":
        from tensorflow_distributed_tpu.data.lm import text_codec
        encode, _, vocab = text_codec(cfg.data_dir, cfg.text_tokenizer,
                                      cfg.bpe_vocab_size)
        # The model vocab follows the tokenizer here, so the TP
        # head's divisibility is only checkable now.
        if tp > 1 and not cfg.shard_vocab and vocab % tp:
            raise ValueError(
                f"--serve.mesh-model {tp} must divide the tokenizer "
                f"vocab {vocab} (the TP head is vocab-parallel); "
                f"pass --shard-vocab true to pad it")
    else:
        vocab = cfg.synthetic_vocab or 64
    # Fleet-replica intake (--serve.inbox; fleet/replica.py): no
    # workload of our own — requests stream in from the router, and
    # the scheduler runs until a drain command lands. The journal/
    # snapshot paths are per-epoch (a restarted replica starts empty;
    # the router re-dispatched the dead epoch's work from its
    # journal), so there is no resume either.
    inbox_mode = bool(cfg.serve.inbox)
    requests = [] if inbox_mode else _workload(cfg, vocab, encode)

    # Journal resume: a non-empty journal at the configured path means
    # a previous leg died mid-traffic (the supervisor re-runs the SAME
    # command) — finished requests drop, in-flight ones re-admit as
    # continuations (prompt + journaled tokens, remaining budget), so
    # the kill cost is re-decoding at most the unflushed in-flight
    # tokens.
    resumed_journal = False
    if cfg.serve.journal and not inbox_mode:
        played = journal_mod.replay(cfg.serve.journal)
        if played:
            requests = journal_mod.apply_replay(requests, played)
            resumed_journal = True
            if is_chief():
                done_n = sum(1 for e in played.values() if e["done"])
                print(f"[serve] journal resume: {done_n} requests "
                      f"already complete, {len(requests)} to serve "
                      f"({cfg.serve.journal})", flush=True)
    if not requests and not inbox_mode:
        if is_chief():
            print("[serve] journal resume: every request already "
                  "complete — nothing to serve", flush=True)
        return {"requests": 0, "total_new_tokens": 0,
                "resumed": resumed_journal}

    from tensorflow_distributed_tpu.resilience.faults import (
        FaultPlan, parse_fault_plan)
    plan = (parse_fault_plan(cfg.resilience.fault_plan)
            if cfg.resilience.fault_plan else FaultPlan())
    if resumed_journal and plan:
        # The restarted leg IS the recovery under test: consume every
        # planned event (same contract as the train loop's
        # bind(start_step) — a resumed leg must terminate).
        plan.bind(1 << 30)

    # int8 KV-cache serving: --serve.kv-dtype is the serve-side
    # spelling of the model-level kv_cache_quant knob (the decode
    # cache quantizes on write, dequantizes inside attention via
    # exact scale-adjusted dots — models/transformer.py). An explicit
    # --kv-cache-quant int8 means the same thing and passes through.
    if (cfg.serve.kv_dtype == "int8"
            and cfg.kv_cache_quant == "none"):
        cfg = dataclasses.replace(cfg, kv_cache_quant="int8")

    if inbox_mode:
        # No workload to measure: the explicit --seq-len (validated
        # present) IS the per-request bound, and continuations can
        # re-prefill at any depth — cover the whole cache.
        max_prompt = need = cfg.seq_len
    else:
        max_prompt = max(len(r.prompt) for r in requests)
        # Per-request trajectory bound (what actually has to fit the
        # cache); bucket padding is prefill-only slack and is clamped
        # to the cache length by the ladder cap below.
        need = max(len(r.prompt) + r.max_new_tokens for r in requests)
    if cfg.seq_len and need > cfg.seq_len:
        raise ValueError(
            f"--seq-len {cfg.seq_len} cannot hold the workload: the "
            f"longest request (prompt + new tokens) needs a "
            f"{need}-token cache")
    if not cfg.seq_len:
        # Size the cache to the workload (fresh-init serving). A
        # checkpointed model's max_len is pinned by training — set
        # --seq-len to the trained length explicitly. Speculation gets
        # spec_tokens of verify write headroom past the last useful
        # position (a user-pinned tight seq_len instead falls back to
        # plain decode near each request's end — engine.can_verify).
        auto_len = max(need + cfg.serve.spec_tokens, 32)
        if cfg.serve.paged:
            # The paged cache is page-granular: round the auto-sized
            # length up to a whole page (an EXPLICIT --seq-len that
            # page_size does not divide is rejected by the engine —
            # a trained model's max_len is not ours to round).
            ps = cfg.serve.page_size
            auto_len = -(-auto_len // ps) * ps
        cfg = dataclasses.replace(cfg, seq_len=auto_len)
    # With a fault plan armed (or a resumed journal, or the SLO
    # scheduler's preemption), slot-retry / replay / preemption
    # continuations can carry prompts up to prompt+new-1 tokens —
    # size the default ladder to the full trajectory so a re-prefill
    # never outgrows the largest bucket.
    cover = (need if (plan or resumed_journal or inbox_mode
                      or cfg.serve.policy == "slo") else max_prompt)
    buckets = (parse_buckets(cfg.serve.buckets) if cfg.serve.buckets
               else default_buckets(cover, cap=cfg.seq_len))

    shim = _GenTask(vocab_size=vocab, sample_input=np.zeros(
        (max(2, dict(mesh.shape).get("data", 1)), cfg.seq_len),
        np.int32))
    model, state = _build_model_and_state(cfg, mesh, shim)
    if cfg.dataset != "text":
        # The embedding gather would silently CLAMP out-of-range ids —
        # bound-check against the BUILT model's vocabulary (the family
        # default when synthetic_vocab is unset), like generate_only.
        for r in requests:
            bad = [int(t) for t in r.prompt
                   if not 0 <= t < model.cfg.vocab_size]
            if bad:
                raise ValueError(
                    f"request {r.rid}: prompt ids {bad} outside the "
                    f"model vocabulary [0, {model.cfg.vocab_size})")
    restored = False
    ckpt_step0 = None
    if cfg.checkpoint_dir:
        # Same restore semantics as mode=generate: local-SGD
        # checkpoints persist the replica stack — average it into the
        # plain template (train/loop.py::generate_only).
        if cfg.param_sync_every > 1:
            state = ckpt.restore_averaged(cfg.checkpoint_dir, state)
        else:
            state = ckpt.restore(cfg.checkpoint_dir, state)
        restored = True
        # Which trained step these weights came from — rides
        # metrics_snapshot as ckpt_step (the fleet controller's
        # model-staleness feed; _swap keeps it current).
        ckpt_step0 = int(state.step)
    params = state.params if state.ema is None else state.ema

    # The serve observatory (observe/hub.py): metrics registry +
    # per-request tracer + SLO monitor + snapshot export, with the
    # process-level installs (active registry, compiled-program
    # registration) owned and torn down in obs.close(). Trace and
    # JSONL both continue across a journal resume.
    tags = host_tags(mesh, cfg)
    obs = ServeObservatory(cfg.observe, chief=is_chief(), tags=tags,
                           process_index=int(tags.get("process_index",
                                                      0)),
                           resumed=resumed_journal, run_config=cfg)
    registry = obs.registry
    on_token = None
    if cfg.serve.stream and is_chief():
        def on_token(rid: int, tok: int, done: bool) -> None:
            print(f"[serve] rid={rid} tok={tok}"
                  + (" <done>" if done else ""), flush=True)

    watchdog = None
    if cfg.resilience.sync_timeout_s > 0:
        from tensorflow_distributed_tpu.resilience.watchdog import (
            Watchdog)
        watchdog = Watchdog(sync_timeout_s=cfg.resilience.sync_timeout_s)
    if cfg.serve.paged:
        from tensorflow_distributed_tpu.serve.paging.engine import (
            PagedSlotEngine, auto_num_pages, page_bytes_estimate)
        num_pages = cfg.serve.num_pages
        if not num_pages:
            # Auto-size the page pool from the workload's trajectory
            # bound, a previous run's OBSERVED slot_pages_peak (read
            # from the still-standing --observe.export-path snapshot
            # when one exists), and the --serve.hbm-budget-gb cap
            # with the params' resident bytes subtracted — replacing
            # the old blind 2x heuristic (ROADMAP item-2 follow-up).
            ps = cfg.serve.page_size
            observed_peak = 0
            if cfg.observe.export_path and os.path.exists(
                    cfg.observe.export_path):
                try:
                    with open(cfg.observe.export_path) as f:
                        observed_peak = int(
                            json.load(f).get("slot_pages_peak", 0))
                except (OSError, ValueError):
                    observed_peak = 0
            import jax
            reserved = sum(
                int(np.prod(x.shape)) * x.dtype.itemsize
                for x in jax.tree_util.tree_leaves(params))
            num_pages, rationale = auto_num_pages(
                num_slots=cfg.serve.num_slots,
                need_pages=-(-need // ps),
                page_bytes=page_bytes_estimate(model.cfg, ps, tp=tp),
                budget_bytes=int(cfg.serve.hbm_budget_gb * 2 ** 30),
                reserved_bytes=reserved,
                observed_peak=observed_peak)
            if is_chief():
                for line in rationale:
                    print(f"[serve] paged auto-size: {line}",
                          flush=True)
        engine = PagedSlotEngine(model, params, cfg.serve.num_slots,
                                 page_size=cfg.serve.page_size,
                                 num_pages=num_pages,
                                 radix=cfg.serve.radix,
                                 buckets=buckets, check=cfg.check,
                                 fault_plan=plan if plan else None,
                                 watchdog=watchdog,
                                 spec_tokens=cfg.serve.spec_tokens,
                                 tracer=obs.tracer)
        if obs.autopilot is not None:
            # Loop 2's advisory half: the autopilot re-runs the SAME
            # one-shot sizer against the peak it OBSERVED, via this
            # closure — the controller itself stays jax-free and
            # never re-derives page-bytes arithmetic.
            def _recommend_pages(observed_peak: int,
                                 _ps=cfg.serve.page_size):
                import jax
                reserved = sum(
                    int(np.prod(x.shape)) * x.dtype.itemsize
                    for x in jax.tree_util.tree_leaves(params))
                return auto_num_pages(
                    num_slots=cfg.serve.num_slots,
                    need_pages=-(-need // _ps),
                    page_bytes=page_bytes_estimate(model.cfg, _ps,
                                                   tp=tp),
                    budget_bytes=int(
                        cfg.serve.hbm_budget_gb * 2 ** 30),
                    reserved_bytes=reserved,
                    observed_peak=int(observed_peak))
            obs.autopilot.bind_paging(num_pages=num_pages,
                                      recommend=_recommend_pages)
    else:
        engine = SlotDecodeEngine(model, params, cfg.serve.num_slots,
                                  buckets=buckets, check=cfg.check,
                                  fault_plan=plan if plan else None,
                                  watchdog=watchdog,
                                  spec_tokens=cfg.serve.spec_tokens,
                                  tracer=obs.tracer)
    # Speculative decoding: the proposer (k-gram self-draft, or a
    # draft model mirroring the slot cache — serve/speculate.py).
    from tensorflow_distributed_tpu.serve.speculate import (
        build_speculator)
    speculator = build_speculator(cfg, model, cfg.seed + 1,
                                  cfg.serve.num_slots, buckets)
    # Every program — the engine's AND a draft speculator's mirror —
    # dispatches once BEFORE the scheduler's clock starts:
    # first-request TTFT (and, on a supervised restart, the recovery
    # window) pays compute, not compile/cache-load, and the measured
    # serving wall (tokens/s) starts clean after warmup.
    engine.warmup(speculator)
    if obs.autopilot is not None:
        # The bucket ladder the run booted with — the baseline the
        # prompt-distribution advisory compares against.
        obs.autopilot.bind_buckets(buckets)
    reload_fn = None
    if cfg.checkpoint_dir:
        def reload_fn():
            # Live weight swap source: newest VERIFIABLE checkpoint
            # (sha256 + finite-params walk-back), placed with the live
            # params' shardings so the engine's swap is a jit cache
            # hit.
            return ckpt.restore_params(cfg.checkpoint_dir,
                                       engine.params)
    journal = (journal_mod.RequestJournal(cfg.serve.journal)
               if cfg.serve.journal else None)
    trace_name = cfg.serve.trace or (
        "file" if cfg.serve.requests else "uniform")
    status_fn = None
    if is_chief() and obs.status_every:
        def status_fn(line: str) -> None:
            print(line, flush=True)
    feed = None
    if inbox_mode:
        from tensorflow_distributed_tpu.fleet.replica import InboxFeed
        feed = InboxFeed(cfg.serve.inbox,
                         default_max_new=cfg.serve.max_new_tokens,
                         default_eos=cfg.serve.eos_id)
        if is_chief():
            print(f"[serve] fleet replica: inbox {cfg.serve.inbox} "
                  f"(serving until a drain command)", flush=True)
    sched = Scheduler(engine, decode_priority=cfg.serve.decode_priority,
                      on_token=on_token,
                      feed=feed, served_ckpt_step=ckpt_step0,
                      fault_plan=plan if plan else None,
                      journal=journal, reload_fn=reload_fn,
                      slot_retries=cfg.serve.slot_retries,
                      policy=cfg.serve.policy,
                      tenant_quota=cfg.serve.tenant_quota,
                      preempt=cfg.serve.preempt,
                      speculator=speculator,
                      status_fn=status_fn,
                      summary_extra={"seed": cfg.seed,
                                     "trace": trace_name,
                                     "resumed": resumed_journal},
                      **obs.scheduler_kwargs())
    try:
        if cfg.profile_dir and is_chief():
            # Whole-serving-window capture (warmup already dispatched
            # every program, so the trace is steady-state serving):
            # the Perfetto export is parsed below into device_time
            # records per engine program (decode/verify/prefill
            # buckets/insert) — observe/xprof.py.
            from tensorflow_distributed_tpu.utils.profiling import (
                trace as profile_trace)
            with profile_trace(cfg.profile_dir):
                done = sched.run(requests)
            obs.emit_device_time(cfg.profile_dir,
                                 calibration=cfg.plan_calibration)
        else:
            done = sched.run(requests)
        if obs.programs_armed:
            budget = observe_device.hbm_budget()
            if budget:
                registry.emit("hbm_budget", **budget)
    finally:
        if journal is not None:
            journal.close()
        if watchdog is not None:
            watchdog.close()
        obs.close()
    summary = dict(sched.summary)
    if done:
        # An inbox-mode replica can drain without ever serving a
        # request — the percentile math needs at least one.
        ttfts = np.asarray([c.ttft_s for c in done])
        summary["ttft_ms_p50"] = round(
            1e3 * float(np.percentile(ttfts, 50)), 3)
        summary["ttft_ms_p95"] = round(
            1e3 * float(np.percentile(ttfts, 95)), 3)
        summary["ttft_ms_p99"] = round(
            1e3 * float(np.percentile(ttfts, 99)), 3)
        summary["tok_ms_mean"] = round(
            float(np.mean([c.tok_ms for c in done])), 4)
    # Per-SLO-class TTFT p95: the number the SLO scheduler exists to
    # move (servebench's p95_ttft_under_load gate reads the high
    # class). Emitted per class actually present, FIFO runs included —
    # a FIFO baseline with the same class mix is the A/B.
    by_class: Dict[str, list] = {}
    for c in done:
        by_class.setdefault(c.slo, []).append(c.ttft_s)
    for cls, vals in sorted(by_class.items()):
        # graftcheck: disable=host-sync-in-loop -- post-run summary
        # math over HOST completion floats; the engine is done
        summary[f"ttft_ms_p95_{cls}"] = round(
            1e3 * float(np.percentile(np.asarray(vals), 95)), 3)
    summary["params"] = "checkpoint" if restored else "fresh-init"
    if is_chief():
        print(f"[serve] {summary['requests']} requests, "
              f"{summary['total_new_tokens']} tokens in "
              f"{summary['wall_s']}s — "
              f"{summary['tokens_per_sec']} tok/s, occupancy "
              f"{summary['mean_slot_occupancy']}, ttft p50 "
              f"{summary.get('ttft_ms_p50')}ms / p95 "
              f"{summary.get('ttft_ms_p95')}ms, "
              f"{summary['prefill_compiles']} prefill programs "
              f"(buckets {summary['buckets']}), "
              f"{summary['params']} params", flush=True)
        if cfg.serve.spec_tokens:
            print(f"[serve] speculative: k={summary.get('spec_tokens')} "
                  f"accept_rate={summary.get('accept_rate')} "
                  f"verify_steps={summary.get('verify_steps')}",
                  flush=True)
        if cfg.serve.paged:
            print(f"[serve] paged: prefix_hit_rate="
                  f"{summary.get('prefix_hit_rate')} pool_occupancy="
                  f"{summary.get('pool_occupancy')} pages_peak="
                  f"{summary.get('pages_peak')}/"
                  f"{summary.get('num_pages')} evictions="
                  f"{summary.get('page_evictions')} cow="
                  f"{summary.get('cow_copies')} sessions="
                  f"{summary.get('sessions')}", flush=True)
        if cfg.serve.policy == "slo":
            cls_bits = " ".join(
                f"{k.rsplit('_', 1)[-1]}={summary[k]}ms"
                for k in sorted(summary)
                if k.startswith("ttft_ms_p95_"))
            print(f"[serve] slo: preemptions={summary['preemptions']} "
                  f"p95 ttft by class: {cls_bits}", flush=True)
        if plan or resumed_journal:
            print(f"[serve] fire: retries={summary['retries']} "
                  f"swaps={summary['swaps']} "
                  f"swap_s={summary['swap_seconds']} "
                  f"resumed={summary['resumed']} "
                  f"ttft p99 {summary.get('ttft_ms_p99')}ms",
                  flush=True)
        if cfg.observe.slo:
            print(f"[serve] slo monitor: "
                  f"alerts={summary.get('slo_alerts', 0)} "
                  f"budget_remaining_min="
                  f"{summary.get('slo_budget_remaining_min')} "
                  f"targets={summary.get('slo_targets')}", flush=True)
        if cfg.observe.trace:
            print(f"[observe] serve trace: {cfg.observe.trace} "
                  f"(open at https://ui.perfetto.dev)", flush=True)
        if cfg.observe.export_path:
            print(f"[observe] metrics snapshot: "
                  f"{cfg.observe.export_path} (atomic; rewritten "
                  f"every {cfg.observe.export_every or 'run-end'}"
                  f"{'s' if cfg.observe.export_every else ''})",
                  flush=True)
        if cfg.observe.metrics_jsonl:
            print(f"[observe] serve metrics: "
                  f"{cfg.observe.metrics_jsonl} (summarize: python -m "
                  f"tensorflow_distributed_tpu.observe.report "
                  f"{cfg.observe.metrics_jsonl})", flush=True)
    return summary
