"""Speculative decoding for the slot engine: propose k, verify once.

The decode loop's cost is one program dispatch per token per slot.
Speculation changes the exchange rate: a cheap DRAFT proposes
``spec_tokens`` tokens per slot, and ONE jitted verify program
(serve/engine.py::_compiled_verify) scores every proposal against the
target model in a single forward over the slot's KV cache — the
longest greedy-consistent prefix is accepted, plus the verify's own
next token (the "bonus"), so each dispatch yields ``accepted + 1``
tokens instead of 1. Output is TOKEN-IDENTICAL to non-speculative
greedy decode by construction: every emitted token is the target
model's own argmax given the accepted prefix; the draft only decides
how many of them one dispatch gets to emit (pinned in
tests/test_serve_slo.py next to servebench's identity gate).

Two proposers:

- :class:`SelfDraft` (the default, ``--serve.draft-config`` unset):
  k-gram prompt-lookup over the request's OWN history (prompt + tokens
  so far) — find the most recent earlier occurrence of the current
  ``spec_kgram``-token suffix and propose what followed it. Pure host
  work, no second model, no extra device programs; repetitive greedy
  tails (the common case) make it accurate.
- :class:`DraftSpeculator` (``--serve.draft-config "tiny"`` or
  ``"size=tiny,n_layers=1"``): a smaller model of the same transformer
  family runs its own slot cache in lockstep (mirrored prefill/insert
  via the engine's program factories, one jitted ``serve_draft_k*``
  scan per proposal round). Fresh-init params — the draft's QUALITY
  only moves the accept rate, never the output.

Static-shape discipline: the draft scan and the verify program are
fixed-shape per (model, k) and censused in the jaxpr goldens
(``serve_verify``); rollback-on-reject is position bookkeeping, not a
program — rejected cache rows sit PAST every slot's authoritative
position and are overwritten by the next verify's writes before
anything can attend them (see ``SlotDecodeEngine.verify_step``).

Known draft-model limitation (ROADMAP item 1 follow-up): plain-step
FALLBACK rounds (engine.can_verify false) advance the engine without
running the draft, so ``DraftSpeculator.sync_from`` adopts positions
whose draft-cache rows were never written. Output stays correct (the
draft only proposes), but subsequent draft attends read those holes
and the accept rate can quietly degrade after fallback rounds — a
draft re-prefill on resync would close it.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.models.generate import (
    decode_token, lookup_program)
from tensorflow_distributed_tpu.observe import device as observe_device


def accept_length(props: np.ndarray, nxt: np.ndarray) -> int:
    """Longest greedy-consistent prefix: how many leading proposals
    match the target model's own argmax chain. ``props`` [K] is what
    the draft proposed, ``nxt`` [K+1] is the verify program's argmax at
    each fed position (``nxt[j]`` = the target's token after consuming
    the prefix through proposal j-1). Pure host, jax-free — the fake
    engines share it."""
    props = np.asarray(props).reshape(-1)
    nxt = np.asarray(nxt).reshape(-1)
    k = len(props)
    if len(nxt) != k + 1:
        raise ValueError(
            f"verify returned {len(nxt)} tokens for {k} proposals "
            f"(want k + 1: one per proposal plus the bonus)")
    a = 0
    while a < k and props[a] == nxt[a]:
        a += 1
    return a


def kgram_propose(history: Sequence[int], k: int, g: int = 3
                  ) -> List[int]:
    """Prompt-lookup proposal: find the most recent EARLIER occurrence
    of the history's last-``g`` suffix and propose the ``k`` tokens
    that followed it (a continuation shorter than ``k`` pads by
    repeating its final token). No match — or history shorter than the
    suffix — falls back to repeating the last token, which is exactly
    right for the degenerate argmax loops fresh-init models settle
    into."""
    hist = [int(t) for t in history]
    if not hist:
        return [0] * k
    n = len(hist)
    g = min(g, n)
    suffix = hist[n - g:]
    # Scan right-to-left for the most recent earlier match (the suffix
    # itself ends at n, so candidate starts end before n - 1).
    for i in range(n - g - 1, -1, -1):
        if hist[i:i + g] == suffix:
            out = hist[i + g:i + g + k]
            while len(out) < k:
                out.append(out[-1] if out else hist[-1])
            return out
    return [hist[-1]] * k


class SelfDraft:
    """k-gram self-draft (no draft model): proposals come from each
    live request's own token history. Host-only; the scheduler feeds
    histories per live slot."""

    #: The scheduler builds per-slot history lists only for proposers
    #: that read them (O(prompt + decoded) host work per step).
    needs_histories = True

    def __init__(self, num_slots: int, k: int, g: int = 3):
        if k < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {k}")
        self.num_slots = num_slots
        self.k = k
        self.g = g

    def set_k(self, k: int) -> None:
        """Live depth change (autopilot loop 3): the k-gram proposer
        is host-only, so a new k is just a wider/narrower lookup."""
        if k < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {k}")
        self.k = int(k)

    def propose(self, histories: Dict[int, Sequence[int]]
                ) -> np.ndarray:
        """[num_slots, k] int32 proposals; rows without a history
        (inactive slots) are zeros — the verify program runs them as
        padding the scheduler never reads."""
        props = np.zeros((self.num_slots, self.k), np.int32)
        for slot, hist in histories.items():
            props[slot] = kgram_propose(hist, self.k, self.g)
        return props

    # Lifecycle hooks the scheduler calls uniformly; the self-draft
    # carries no device state, so they are no-ops.
    def observe_admit(self, slot, prompt, first_tok):  # pragma: no cover
        pass

    def observe_free(self, slot):  # pragma: no cover
        pass

    def sync_from(self, engine):  # pragma: no cover
        pass

    def warmup(self):  # pragma: no cover - nothing to compile
        pass


@functools.lru_cache(maxsize=8)
def _compiled_draft(model, k: int):
    """The draft proposal program: ``k`` greedy tokens for every slot
    at its own depth, one ``lax.scan`` under jit. The scan runs k + 1
    decode ticks: the extra tick FEEDS the last proposal so its K/V
    lands in the draft cache — without it, a fully-accepted round
    leaves a permanent hole at the old frontier that every later draft
    step would attend (the target cache never has this problem: its
    verify always re-feeds the pending token)."""

    @jax.jit
    def run(params, cache, tok, pos):
        def body(carry, _):
            cache, tok, pos = carry
            last, cache = decode_token(model, params, cache, tok, pos)
            nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return (cache, nxt, pos + 1), nxt

        (cache, _, _), toks = jax.lax.scan(
            body, (cache, tok, pos), None, length=k + 1)
        return cache, toks.T[:, :k]            # [S, k]

    return observe_device.instrument(f"serve_draft_k{k}", run)


def parse_draft_config(spec: str) -> dict:
    """``--serve.draft-config`` grammar: a bare size preset ("tiny")
    or comma-separated ``key=value`` TransformerConfig overrides with
    an optional ``size=`` entry (ints parsed, everything else kept as
    a string). Returns {"size": ..., "overrides": {...}}."""
    spec = spec.strip()
    if not spec:
        raise ValueError("empty draft_config")
    if "=" not in spec:
        return {"size": spec, "overrides": {}}
    size = "tiny"
    overrides = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"draft_config entry {part!r} is not key=value (or "
                f"pass a bare size preset like 'tiny')")
        key, val = (x.strip() for x in part.split("=", 1))
        if key == "size":
            size = val
            continue
        try:
            overrides[key] = int(val)
        except ValueError:
            overrides[key] = val
    return {"size": size, "overrides": overrides}


class DraftSpeculator:
    """A draft MODEL proposing ``k`` tokens per round from its own
    mirrored slot cache. The mirror reuses the engine's program
    factories (bucketed prefill + traced-slot row insert), so the
    draft admits with the same bounded-program discipline; its
    positions re-sync from the engine after every verify, and rejected
    draft rows are overwritten before attention can see them — the
    same argument as the target cache (module docstring)."""

    needs_histories = False   # the draft's cache IS its history

    def __init__(self, model, params, num_slots: int,
                 buckets: Sequence[int], k: int):
        from tensorflow_distributed_tpu.serve.engine import (
            _insert_row, _compiled_prefill, zero_cache)
        if k < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {k}")
        self.model = model
        self.params = params
        self.num_slots = num_slots
        self.buckets = tuple(buckets)
        self.k = k
        self._insert = _insert_row
        self._prefill_factory = _compiled_prefill
        self.cache = zero_cache(model, params, num_slots)
        self.tok = np.zeros((num_slots,), np.int32)
        self.pos = np.zeros((num_slots,), np.int32)
        self._propose_fn = lookup_program(_compiled_draft, model, k)

    def set_k(self, k: int) -> None:
        """Live depth change (autopilot loop 3): rebind the proposal
        scan at the new k through the same ``lookup_program`` cache
        the ctor used — a revisited k is a dict hit, a new one
        compiles on the next propose. The draft cache/positions are
        untouched: the scan length is the only thing k shapes."""
        if k < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {k}")
        if int(k) == self.k:
            return
        self.k = int(k)
        self._propose_fn = lookup_program(_compiled_draft, self.model,
                                          self.k)

    def observe_admit(self, slot: int, prompt, first_tok: int) -> None:
        """Mirror an engine admission: prefill the draft cache row for
        ``slot``; the pending token is the TARGET's first token (the
        draft's own prediction is discarded — it proposes, never
        emits)."""
        from tensorflow_distributed_tpu.serve.buckets import pick_bucket
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        bucket = pick_bucket(len(prompt), self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(prompt)] = prompt
        fn = lookup_program(self._prefill_factory, self.model, bucket)
        row, _ = fn(self.params, jnp.asarray(padded),
                    jnp.asarray(len(prompt), jnp.int32))
        self.cache = self._insert(self.cache, row,
                                  jnp.asarray(slot, jnp.int32))
        self.tok[slot] = first_tok
        self.pos[slot] = len(prompt)

    def observe_free(self, slot: int) -> None:
        self.tok[slot] = 0
        self.pos[slot] = 0

    def warmup(self) -> None:
        """Dispatch every draft-mirror program once — each bucket's
        prefill, the row insert, the proposal scan — so the FIRST
        speculative round pays compute, not compile.
        ``SlotDecodeEngine.warmup(speculator)`` calls this right after
        warming its own programs; the pre-warmup cache object is
        restored, so a warmed draft is byte-identical to a fresh one
        (compile-counter pinned in tests/test_serve_observe.py)."""
        cache0 = self.cache
        for b in self.buckets:
            fn = lookup_program(self._prefill_factory, self.model, b)
            row, _ = fn(self.params, jnp.zeros((1, b), jnp.int32),
                        jnp.asarray(1, jnp.int32))
            self.cache = self._insert(self.cache, row,
                                      jnp.asarray(0, jnp.int32))
        out = self._propose_fn(self.params, self.cache,
                               jnp.asarray(self.tok),
                               jnp.asarray(self.pos))
        # graftcheck: disable=host-sync-in-loop -- startup-only drain
        # of the warmup dispatches; runs once per process, never in
        # the decode loop
        jax.block_until_ready(out)
        self.cache = cache0

    def sync_from(self, engine) -> None:
        """Adopt the engine's authoritative pending token/position per
        slot after a verify (or fallback plain step) retired — the
        draft's cache rows past these positions are dead and will be
        overwritten by its next propose."""
        self.tok[:] = engine.tok
        self.pos[:] = engine.pos

    def propose(self, histories: Dict[int, Sequence[int]]
                ) -> np.ndarray:
        """[num_slots, k] proposals from the draft model (histories
        are ignored — the draft's cache IS its history)."""
        self.cache, props = self._propose_fn(
            self.params, self.cache, jnp.asarray(self.tok),
            jnp.asarray(self.pos))
        # graftcheck: disable=host-sync-in-loop -- the draft's OUTPUT:
        # proposals must reach the host to drive the verify call; one
        # [num_slots, k] fetch per proposal round is the contract
        return np.asarray(jax.device_get(props), np.int32)


def build_speculator(cfg, model, params_seed: int, num_slots: int,
                     buckets: Sequence[int]) -> Optional[object]:
    """serve_run's factory: ``spec_tokens == 0`` -> None;
    ``draft_config`` unset -> :class:`SelfDraft`; otherwise build the
    draft model (same family/vocab/max_len as the target, fresh-init
    params — draft quality moves accept rate, never output) and wrap
    it in a :class:`DraftSpeculator`. The draft is built MESH-LESS,
    matching today's single-device-set engine; threading the serve
    mesh through is part of ROADMAP item 1's open sharded-serving
    half."""
    serve = cfg.serve
    if not serve.spec_tokens:
        return None
    if not serve.draft_config:
        return SelfDraft(num_slots, serve.spec_tokens,
                         g=serve.spec_kgram)
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    parsed = parse_draft_config(serve.draft_config)
    overrides = dict(parsed["overrides"])
    overrides.setdefault("vocab_size", model.cfg.vocab_size)
    overrides.setdefault("max_len", model.cfg.max_len)
    overrides.setdefault("compute_dtype", model.cfg.compute_dtype)
    draft = gpt_lm(mesh=None, size=parsed["size"], dropout_rate=0.0,
                   **overrides)
    params = draft.init(
        jax.random.key(params_seed),
        jnp.zeros((1, 8), jnp.int32))["params"]
    return DraftSpeculator(draft, params, num_slots, buckets,
                           serve.spec_tokens)
