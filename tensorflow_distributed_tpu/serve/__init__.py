"""Continuous-batching LM inference engine (in-flight batching).

The repo's one-shot ``models/generate.py`` prefills and decodes a
fixed batch to completion: short requests wait for long ones, and the
device idles between calls. This package serves a dynamically changing
request set from ONE hot compiled decode program instead:

- :mod:`serve.engine` — slot-based decode engine: one jitted
  single-token step over a fixed ``[num_slots, max_len]`` KV cache
  whose slots are independently occupied/freed (insert = a
  ``dynamic_update_slice`` of a prefilled row; free = host-side), so
  requests join and leave the batch between steps with ZERO
  recompilation; plus bucketed prefill (prompt lengths padded to a
  small set of buckets, bounding the prefill program count);
- :mod:`serve.buckets` — the bucket ladder and pick logic;
- :mod:`serve.scheduler` — FIFO admission with a decode-priority /
  bounded-starvation interleaving policy, per-request EOS and
  max-token termination, host-side token streaming, and per-request
  metrics (TTFT, per-token latency, queue steps) through observe/;
- :mod:`serve.run` — the ``mode=serve`` CLI driver (request-file or
  synthetic open-loop workload).

Correctness contract (pinned in tests/test_serve.py): engine outputs
are token-identical to one-shot greedy ``generate()`` per request —
batching must not change results.
"""

from tensorflow_distributed_tpu.serve.buckets import (  # noqa: F401
    default_buckets, parse_buckets, pick_bucket)
from tensorflow_distributed_tpu.serve.engine import (  # noqa: F401
    SlotDecodeEngine)
from tensorflow_distributed_tpu.serve.scheduler import (  # noqa: F401
    Completion, Request, Scheduler)
