"""Prefill length buckets.

Every distinct prompt shape fed to a jitted prefill is a fresh XLA
trace+compile — an open request stream with arbitrary lengths is a
retrace storm. Padding prompts up to a small ladder of bucket lengths
bounds the compiled-program count to ``len(buckets)`` for the life of
the process (amortized further across runs by the persistent compile
cache, utils/compilecache.py). Padding is pure slack: the causal mask
keeps positions >= the true length from influencing any real token,
and the engine samples the first token from the TRUE last position.
"""

from __future__ import annotations

from typing import Sequence, Tuple


def parse_buckets(spec: str) -> Tuple[int, ...]:
    """``"32,64,128"`` -> (32, 64, 128), validated ascending unique."""
    try:
        vals = tuple(int(tok) for tok in spec.split(",") if tok.strip())
    except ValueError:
        raise ValueError(
            f"buckets spec {spec!r} is not comma-separated ints") from None
    if not vals:
        raise ValueError(f"buckets spec {spec!r} names no buckets")
    if any(v < 1 for v in vals):
        raise ValueError(f"bucket lengths must be >= 1, got {vals}")
    if tuple(sorted(set(vals))) != vals:
        raise ValueError(
            f"buckets must be strictly ascending, got {vals}")
    return vals


def default_buckets(max_prompt_len: int, min_bucket: int = 16,
                    cap: int | None = None) -> Tuple[int, ...]:
    """Power-of-two ladder covering prompts up to ``max_prompt_len``:
    (min_bucket, 2*min_bucket, ...) — at most log2 buckets, <2x padding
    waste per prompt. ``cap`` (e.g. the model's max_len) clamps the
    ladder: rungs past it drop and the top rung becomes ``cap`` itself
    when the power-of-two would overshoot — a 100-token cache gets
    (16, 32, 64, 100), not an unusable 128."""
    if max_prompt_len < 1:
        raise ValueError(
            f"max_prompt_len must be >= 1, got {max_prompt_len}")
    if min_bucket < 1:
        raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
    if cap is not None and max_prompt_len > cap:
        raise ValueError(
            f"max_prompt_len {max_prompt_len} exceeds the bucket cap "
            f"{cap}")
    out = [min_bucket]
    while out[-1] < max_prompt_len:
        out.append(out[-1] * 2)
    if cap is not None:
        out = [b for b in out if b <= cap]
        if not out or out[-1] < max_prompt_len:
            out.append(cap)
    return tuple(out)


def pick_bucket(prompt_len: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits the prompt."""
    for b in buckets:
        if prompt_len <= b:
            return b
    raise ValueError(
        f"prompt length {prompt_len} exceeds the largest bucket "
        f"{max(buckets)}")
