"""FIFO admission + prefill/decode interleaving for the slot engine.

Policy: **decode-priority with a starvation bound**. Decoding a full
batch is the throughput-optimal steady state, so the scheduler keeps
stepping while requests wait — but a queued request with a free slot
is admitted after at most ``decode_priority`` decode steps (the
starvation clock only ticks while BOTH hold: someone is waiting and a
slot is free — capacity waits don't count against the policy). An
idle engine admits immediately.

Termination is per request (EOS or its max-token budget), tokens
stream to the host as they retire (``on_token``), and every request's
lifecycle lands in the observe registry: ``serve_request`` records
(TTFT, per-token latency, queue steps) plus one final
``serve_summary`` (aggregate tokens/s, mean slot occupancy) —
summarized by ``observe.report`` next to the training numbers.

Serve-under-fire (all optional; zero cost unconfigured):

- **fault plan**: consulted between decode steps on the engine's
  decode-step clock — slot_nan poisons a KV row, reload triggers a
  live weight swap, sigterm/sigkill self-signal (resilience/faults.py;
  decode_stall is consumed inside the engine's watched fetch).
- **slot-level retry**: a slot whose decode step produced non-finite
  logits is quarantined — freed and its request re-queued at the head
  as a CONTINUATION (prompt + the good tokens so far, remaining
  budget) — so one poisoned slot costs one re-prefill, never an
  engine restart, and greedy determinism keeps the final token stream
  identical. A per-request retry budget (``slot_retries``) turns
  repeated quarantine of the SAME request into
  :class:`SlotRetryExhausted` — the serve-mode divergence signal
  (exit 2; the supervisor does not hot-loop restarts on it).
- **journal**: admits/tokens/completions append to a
  :class:`serve.journal.RequestJournal`, flushed per scheduler
  iteration, so a SIGKILL'd leg is resumable at token granularity.
- **live weight swap**: ``reload_fn`` (serve/run.py wires it to
  train.checkpoint.restore_params) supplies fresh params; the engine
  swaps them in between steps with slots live; swap latency lands in
  the summary and a ``weight_swap`` recovery event.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine


class SlotRetryExhausted(RuntimeError):
    """The same request was slot-quarantined past its retry budget —
    serve mode's DIVERGED equivalent (deterministic greedy decode will
    poison the same way again; restarting would hot-loop). The CLI
    maps this to exit code 2, which the supervisor refuses to
    restart."""


@dataclasses.dataclass
class Request:
    """One inference request. ``arrival_s`` is the open-loop offset
    (seconds from run start) at which the request becomes visible to
    the scheduler; 0 = present from the start."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: int = -1          # -1 = run to the full budget
    arrival_s: float = 0.0


@dataclasses.dataclass
class Completion:
    """A finished request with its serving metrics."""

    rid: int
    prompt_len: int
    tokens: List[int]
    finish: str               # "eos" | "length"
    ttft_s: float             # arrival -> first token (queue + prefill)
    decode_s: float           # first token -> last token
    queue_steps: int          # decode steps endured while admittable
    retries: int = 0          # slot quarantines this request survived
    recovery_window: bool = False  # a recovery event (quarantine/
    #                                swap/restart continuation) fell
    #                                inside arrival->first token —
    #                                firebench's p99-TTFT-during-
    #                                recovery population
    decoded: int = 0          # tokens decoded THIS leg (excludes a
    #                           continuation's journal-replayed base —
    #                           those were decoded by the dead leg)

    @property
    def tok_ms(self) -> float:
        """Mean inter-token latency (ms) over THIS leg's decode phase
        (a continuation's base tokens were decoded by the dead leg —
        charging them here would deflate the latency)."""
        n = self.decoded or len(self.tokens)
        return 1e3 * self.decode_s / max(1, n - 1)


@dataclasses.dataclass
class _Live:
    req: Request
    slot: int
    tokens: List[int]
    t_first: float
    queue_steps: int
    base: List[int]           # tokens from before a continuation
    #                           (journal replay or slot retry) — the
    #                           completion reports base + tokens


class Scheduler:
    """Drives a :class:`SlotDecodeEngine` over a request workload."""

    def __init__(self, engine: SlotDecodeEngine, decode_priority: int = 8,
                 registry=None,
                 on_token: Optional[Callable[[int, int, bool], None]] = None,
                 clock=time.perf_counter, fault_plan=None, journal=None,
                 reload_fn=None, slot_retries: int = 2,
                 summary_extra=None):
        if decode_priority < 1:
            raise ValueError(
                f"decode_priority must be >= 1, got {decode_priority}")
        if slot_retries < 0:
            raise ValueError(
                f"slot_retries must be >= 0, got {slot_retries}")
        self.engine = engine
        self.decode_priority = decode_priority
        self.registry = registry
        self.on_token = on_token
        self.clock = clock
        self.fault_plan = fault_plan
        self.journal = journal
        self.reload_fn = reload_fn    # () -> (params, ckpt_step)
        self.slot_retries = slot_retries
        # Run-identity fields (seed, trace name) merged into the
        # serve_summary RECORD so the JSONL artifact is reproducible
        # standalone (FIREBENCH re-derives workloads from it).
        self.summary_extra = dict(summary_extra or {})

    def _emit(self, event: str, **fields) -> None:
        if self.registry is not None:
            self.registry.emit(event, **fields)

    def run(self, requests: Sequence[Request]) -> List[Completion]:
        """Serve every request to completion; returns completions in
        finish order (sort by ``rid`` for submission order)."""
        eng = self.engine
        plan = self.fault_plan
        for r in requests:
            if not eng.fits(len(r.prompt), r.max_new_tokens):
                raise ValueError(
                    f"request {r.rid}: prompt {len(r.prompt)} + "
                    f"{r.max_new_tokens} new tokens does not fit "
                    f"(buckets up to {max(eng.buckets)}, max_len "
                    f"{eng.max_len})")
            if r.max_new_tokens < 1:
                raise ValueError(
                    f"request {r.rid}: max_new_tokens must be >= 1")
        pending = collections.deque(
            sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        queue: collections.deque = collections.deque()
        live: dict = {}                       # slot -> _Live
        done: List[Completion] = []
        t0 = self.clock()
        steps_since_admit = 0
        occupancy_sum = 0.0
        run_steps = 0  # THIS run's decode steps (the engine counter
        #                spans its whole lifetime — reuse would skew
        #                the occupancy mean)
        retries: dict = {}            # rid -> quarantines survived
        first_seen: dict = {}         # rid -> first-token time (the
        #                               TTFT point survives retries)
        total_retries = 0
        self._swap_seconds = 0.0
        recovery_ts: List[float] = []  # quarantine/swap times, for the
        #                                recovery-window TTFT flag

        def now() -> float:
            return self.clock() - t0

        def finish(lv: _Live, why: str) -> None:
            t = now()
            eng.free(lv.slot)
            del live[lv.slot]
            tokens = lv.base + lv.tokens
            t_first = first_seen.get(lv.req.rid, lv.t_first)
            n_retries = retries.get(lv.req.rid, 0)
            # Recovery population: a quarantine/swap fell inside this
            # request's arrival->first-token window, OR the request is
            # a restart continuation (its base tokens crossed a
            # process death — the resumed leg consumed the plan, so
            # recovery_ts alone would miss exactly the requests the
            # restart hit).
            window = (any(lv.req.arrival_s <= rt <= t_first
                          for rt in recovery_ts)
                      or bool(lv.base))
            comp = Completion(
                rid=lv.req.rid,
                prompt_len=len(lv.req.prompt) - len(lv.base),
                tokens=tokens, finish=why,
                ttft_s=t_first - lv.req.arrival_s,
                decode_s=t - t_first, queue_steps=lv.queue_steps,
                retries=n_retries, recovery_window=window,
                decoded=len(lv.tokens))
            done.append(comp)
            self._emit("serve_request", rid=comp.rid,
                       prompt_len=comp.prompt_len,
                       new_tokens=len(comp.tokens), finish=why,
                       ttft_ms=round(1e3 * comp.ttft_s, 3),
                       tok_ms=round(comp.tok_ms, 4),
                       queue_steps=comp.queue_steps,
                       retries=n_retries,
                       recovery_window=window,
                       arrival_s=round(lv.req.arrival_s, 4),
                       t_first_s=round(t_first, 4))
            if self.journal is not None:
                self.journal.done(comp.rid)
            if self.on_token is not None:
                self.on_token(comp.rid, comp.tokens[-1], True)

        def admit() -> None:
            req = queue.popleft()
            slot = eng.free_slots()[0]
            first = eng.prefill(req.prompt, slot)
            base = list(getattr(req, "_base_tokens", ()))
            lv = _Live(req=req, slot=slot, tokens=[first],
                       t_first=now(), queue_steps=req._waited,
                       base=base)
            live[slot] = lv
            if req.rid not in first_seen:
                if not base and self.journal is not None:
                    # First-ever admission of this request (a replayed
                    # continuation was journaled by the previous leg).
                    self.journal.admit(req.rid, req.prompt,
                                       req.max_new_tokens, req.eos_id)
                first_seen[req.rid] = lv.t_first
            if self.journal is not None:
                self.journal.token(req.rid, first, now())
            if self.on_token is not None and not (
                    first == req.eos_id or req.max_new_tokens == 1):
                self.on_token(req.rid, first, False)
            if first == req.eos_id:
                finish(lv, "eos")
            elif req.max_new_tokens == 1:
                finish(lv, "length")

        def quarantine(lv: _Live) -> None:
            """Contain one poisoned slot: free it, re-queue the
            request as a continuation at the head (prompt + good
            tokens, remaining budget). Greedy decode is deterministic,
            so the re-prefilled continuation emits exactly the tokens
            the poisoned step would have — token identity is preserved
            (pinned in tests/test_serve_fire.py)."""
            nonlocal total_retries, steps_since_admit
            eng.free(lv.slot)
            del live[lv.slot]
            rid = lv.req.rid
            n = retries[rid] = retries.get(rid, 0) + 1
            if n > self.slot_retries:
                raise SlotRetryExhausted(
                    f"request {rid} slot-quarantined {n} times "
                    f"(budget {self.slot_retries}): repeated NaN on "
                    f"the same request is a divergence, not a "
                    f"transient — halting instead of hot-looping "
                    f"re-prefills")
            total_retries += 1
            t = now()
            recovery_ts.append(t)
            self._emit("recovery", kind="slot_quarantine", rid=rid,
                       slot=lv.slot, retry=n, t_s=round(t, 4))
            good = lv.base + lv.tokens
            # graftcheck: disable=host-sync-in-loop -- builds the
            # continuation prompt from HOST token lists (no device
            # value involved); runs once per quarantine, not per step
            cont = Request(
                rid=rid,
                prompt=np.concatenate(
                    [np.asarray(lv.req.prompt, np.int32),
                     np.asarray(lv.tokens, np.int32)])
                if lv.tokens else np.asarray(lv.req.prompt, np.int32),
                max_new_tokens=lv.req.max_new_tokens - len(lv.tokens),
                eos_id=lv.req.eos_id, arrival_s=lv.req.arrival_s)
            if len(cont.prompt) > max(eng.buckets):
                raise ValueError(
                    f"request {rid}: continuation prompt "
                    f"{len(cont.prompt)} exceeds the largest bucket "
                    f"{max(eng.buckets)} — slot retry needs the "
                    f"ladder sized to prompt+new tokens (serve/run.py "
                    f"does this when a fault plan is armed; with "
                    f"--serve.buckets, cover the full trajectory)")
            cont._base_tokens = good
            cont._waited = lv.queue_steps
            queue.appendleft(cont)
            # Re-admit without waiting out the decode-priority clock:
            # the request was already being served.
            steps_since_admit = self.decode_priority

        while pending or queue or live:
            # Open-loop arrivals: everything whose time has come.
            while pending and pending[0].arrival_s <= now():
                req = pending.popleft()
                req._waited = 0
                queue.append(req)
            if queue and eng.free_slots() and (
                    not live or steps_since_admit
                    >= self.decode_priority):
                admit()
                steps_since_admit = 0
                if self.journal is not None:
                    self.journal.flush()
                continue
            if not live:
                if pending:
                    # Nothing to decode, nothing admittable: sleep to
                    # the next arrival instead of spinning.
                    time.sleep(max(0.0, pending[0].arrival_s - now()))
                    continue
                break  # queue must be empty too (free slots exist)
            if plan:
                # The serve-phase fault points, on the decode-step
                # clock (resilience/faults.py): poison, swap, signal.
                # decode_stall is consumed inside the engine's watched
                # fetch.
                nstep = eng.decode_steps + 1
                bad_slot = plan.take_slot_nan(nstep)
                if bad_slot is not None:
                    if bad_slot not in live:
                        # The drill wants a SERVING slot: the named one
                        # is momentarily empty (freed last step, next
                        # insert pending — whose full-row overwrite
                        # would neutralize the poison), so redirect to
                        # the lowest live slot. live is non-empty here
                        # (the not-live branch above already continued).
                        bad_slot = min(live)
                    eng.poison_slot(bad_slot)
                if plan.take_reload(nstep):
                    self._swap(now, recovery_ts)
                plan.maybe_signal(nstep)
            nxt = eng.step()
            occupancy_sum += eng.occupancy()
            run_steps += 1
            if queue and eng.free_slots():
                # The starvation clock: a decode step taken WHILE the
                # head-of-queue request waited with a free slot
                # available. The bound the policy guarantees (and
                # tests/test_serve.py pins) is head-of-line: admission
                # within decode_priority such steps.
                steps_since_admit += 1
                queue[0]._waited += 1
            # Containment BEFORE token retirement: a poisoned slot's
            # token is garbage — quarantine drops it (never appended,
            # never journaled) and the continuation re-derives it.
            for slot in getattr(eng, "take_bad_slots", lambda: [])():
                if slot in live:
                    quarantine(live[slot])
            for slot in list(live):
                lv = live[slot]
                tok = int(nxt[slot])
                lv.tokens.append(tok)
                if self.journal is not None:
                    self.journal.token(lv.req.rid, tok, now())
                if tok == lv.req.eos_id:
                    finish(lv, "eos")
                elif len(lv.tokens) >= lv.req.max_new_tokens:
                    finish(lv, "length")
                elif self.on_token is not None:
                    self.on_token(lv.req.rid, tok, False)
            if self.journal is not None:
                self.journal.flush()

        wall = now()
        total_new = sum(len(c.tokens) for c in done)
        # Throughput counts only tokens DECODED this leg: a resumed
        # leg's continuations deliver their journal-replayed base
        # tokens too (total_new_tokens — the user-facing count), but
        # those were the dead leg's work; dividing them by this leg's
        # wall would overstate tokens/s exactly when it matters.
        decoded = sum(c.decoded or len(c.tokens) for c in done)
        summary = {
            "requests": len(done),
            "total_new_tokens": total_new,
            "decoded_tokens": decoded,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(decoded / max(wall, 1e-9), 2),
            "mean_slot_occupancy": round(
                occupancy_sum / max(1, run_steps), 4),
            "decode_steps": run_steps,
            "prefills": eng.prefills,
            "prefill_compiles": eng.prefill_compiles,
            "buckets": ",".join(str(b) for b in eng.buckets),
            "num_slots": eng.num_slots,
            "decode_priority": self.decode_priority,
            "retries": total_retries,
            "swaps": getattr(eng, "swaps", 0),
            "swap_seconds": round(self._swap_seconds, 4),
            **self.summary_extra,
        }
        self._emit("serve_summary", **summary)
        self.summary = summary
        if self.journal is not None:
            self.journal.flush()
        return done

    def _swap(self, now, recovery_ts: List[float]) -> None:
        """One live weight swap: fetch fresh params via ``reload_fn``
        (integrity-verified, fallback-to-newest-verifiable —
        train.checkpoint.restore_params), hand them to the engine
        between decode steps, account the latency."""
        if self.reload_fn is None:
            raise ValueError(
                "fault plan requests a reload but no reload_fn is "
                "wired (mode=serve needs --checkpoint-dir for live "
                "weight swap)")
        t0 = self.clock()
        params, ckpt_step = self.reload_fn()
        self.engine.swap_params(params)
        dt = self.clock() - t0
        self._swap_seconds += dt
        t = now()
        recovery_ts.append(t)
        self._emit("recovery", kind="weight_swap",
                   seconds=round(dt, 4), ckpt_step=ckpt_step,
                   t_s=round(t, 4))
