"""FIFO admission + prefill/decode interleaving for the slot engine.

Policy: **decode-priority with a starvation bound**. Decoding a full
batch is the throughput-optimal steady state, so the scheduler keeps
stepping while requests wait — but a queued request with a free slot
is admitted after at most ``decode_priority`` decode steps (the
starvation clock only ticks while BOTH hold: someone is waiting and a
slot is free — capacity waits don't count against the policy). An
idle engine admits immediately.

Termination is per request (EOS or its max-token budget), tokens
stream to the host as they retire (``on_token``), and every request's
lifecycle lands in the observe registry: ``serve_request`` records
(TTFT, per-token latency, queue steps) plus one final
``serve_summary`` (aggregate tokens/s, mean slot occupancy) —
summarized by ``observe.report`` next to the training numbers.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine


@dataclasses.dataclass
class Request:
    """One inference request. ``arrival_s`` is the open-loop offset
    (seconds from run start) at which the request becomes visible to
    the scheduler; 0 = present from the start."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: int = -1          # -1 = run to the full budget
    arrival_s: float = 0.0


@dataclasses.dataclass
class Completion:
    """A finished request with its serving metrics."""

    rid: int
    prompt_len: int
    tokens: List[int]
    finish: str               # "eos" | "length"
    ttft_s: float             # arrival -> first token (queue + prefill)
    decode_s: float           # first token -> last token
    queue_steps: int          # decode steps endured while admittable

    @property
    def tok_ms(self) -> float:
        """Mean inter-token latency (ms) over the decode phase."""
        return 1e3 * self.decode_s / max(1, len(self.tokens) - 1)


@dataclasses.dataclass
class _Live:
    req: Request
    slot: int
    tokens: List[int]
    t_first: float
    queue_steps: int


class Scheduler:
    """Drives a :class:`SlotDecodeEngine` over a request workload."""

    def __init__(self, engine: SlotDecodeEngine, decode_priority: int = 8,
                 registry=None,
                 on_token: Optional[Callable[[int, int, bool], None]] = None,
                 clock=time.perf_counter):
        if decode_priority < 1:
            raise ValueError(
                f"decode_priority must be >= 1, got {decode_priority}")
        self.engine = engine
        self.decode_priority = decode_priority
        self.registry = registry
        self.on_token = on_token
        self.clock = clock

    def _emit(self, event: str, **fields) -> None:
        if self.registry is not None:
            self.registry.emit(event, **fields)

    def run(self, requests: Sequence[Request]) -> List[Completion]:
        """Serve every request to completion; returns completions in
        finish order (sort by ``rid`` for submission order)."""
        eng = self.engine
        for r in requests:
            if not eng.fits(len(r.prompt), r.max_new_tokens):
                raise ValueError(
                    f"request {r.rid}: prompt {len(r.prompt)} + "
                    f"{r.max_new_tokens} new tokens does not fit "
                    f"(buckets up to {max(eng.buckets)}, max_len "
                    f"{eng.max_len})")
            if r.max_new_tokens < 1:
                raise ValueError(
                    f"request {r.rid}: max_new_tokens must be >= 1")
        pending = collections.deque(
            sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        queue: collections.deque = collections.deque()
        live: dict = {}                       # slot -> _Live
        done: List[Completion] = []
        t0 = self.clock()
        steps_since_admit = 0
        occupancy_sum = 0.0
        run_steps = 0  # THIS run's decode steps (the engine counter
        #                spans its whole lifetime — reuse would skew
        #                the occupancy mean)

        def now() -> float:
            return self.clock() - t0

        def finish(lv: _Live, why: str) -> None:
            t = now()
            eng.free(lv.slot)
            del live[lv.slot]
            comp = Completion(
                rid=lv.req.rid, prompt_len=len(lv.req.prompt),
                tokens=lv.tokens, finish=why,
                ttft_s=lv.t_first - lv.req.arrival_s,
                decode_s=t - lv.t_first, queue_steps=lv.queue_steps)
            done.append(comp)
            self._emit("serve_request", rid=comp.rid,
                       prompt_len=comp.prompt_len,
                       new_tokens=len(comp.tokens), finish=why,
                       ttft_ms=round(1e3 * comp.ttft_s, 3),
                       tok_ms=round(comp.tok_ms, 4),
                       queue_steps=comp.queue_steps)
            if self.on_token is not None:
                self.on_token(comp.rid, comp.tokens[-1], True)

        def admit() -> None:
            req = queue.popleft()
            slot = eng.free_slots()[0]
            first = eng.prefill(req.prompt, slot)
            lv = _Live(req=req, slot=slot, tokens=[first],
                       t_first=now(), queue_steps=req._waited)
            live[slot] = lv
            if self.on_token is not None and not (
                    first == req.eos_id or req.max_new_tokens == 1):
                self.on_token(req.rid, first, False)
            if first == req.eos_id:
                finish(lv, "eos")
            elif req.max_new_tokens == 1:
                finish(lv, "length")

        while pending or queue or live:
            # Open-loop arrivals: everything whose time has come.
            while pending and pending[0].arrival_s <= now():
                req = pending.popleft()
                req._waited = 0
                queue.append(req)
            if queue and eng.free_slots() and (
                    not live or steps_since_admit
                    >= self.decode_priority):
                admit()
                steps_since_admit = 0
                continue
            if not live:
                if pending:
                    # Nothing to decode, nothing admittable: sleep to
                    # the next arrival instead of spinning.
                    time.sleep(max(0.0, pending[0].arrival_s - now()))
                    continue
                break  # queue must be empty too (free slots exist)
            nxt = eng.step()
            occupancy_sum += eng.occupancy()
            run_steps += 1
            if queue and eng.free_slots():
                # The starvation clock: a decode step taken WHILE the
                # head-of-queue request waited with a free slot
                # available. The bound the policy guarantees (and
                # tests/test_serve.py pins) is head-of-line: admission
                # within decode_priority such steps.
                steps_since_admit += 1
                queue[0]._waited += 1
            for slot in list(live):
                lv = live[slot]
                tok = int(nxt[slot])
                lv.tokens.append(tok)
                if tok == lv.req.eos_id:
                    finish(lv, "eos")
                elif len(lv.tokens) >= lv.req.max_new_tokens:
                    finish(lv, "length")
                elif self.on_token is not None:
                    self.on_token(lv.req.rid, tok, False)

        wall = now()
        total_new = sum(len(c.tokens) for c in done)
        summary = {
            "requests": len(done),
            "total_new_tokens": total_new,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(total_new / max(wall, 1e-9), 2),
            "mean_slot_occupancy": round(
                occupancy_sum / max(1, run_steps), 4),
            "decode_steps": run_steps,
            "prefills": eng.prefills,
            "prefill_compiles": eng.prefill_compiles,
            "buckets": ",".join(str(b) for b in eng.buckets),
            "num_slots": eng.num_slots,
            "decode_priority": self.decode_priority,
        }
        self._emit("serve_summary", **summary)
        self.summary = summary
        return done
