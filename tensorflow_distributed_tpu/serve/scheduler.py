"""Admission + prefill/decode interleaving for the slot engine.

Policy: **decode-priority with a starvation bound**. Decoding a full
batch is the throughput-optimal steady state, so the scheduler keeps
stepping while requests wait — but a queued request with a free slot
is admitted after at most ``decode_priority`` decode steps (the
starvation clock only ticks while BOTH hold: someone is waiting and a
slot is free — capacity waits don't count against the policy). An
idle engine admits immediately.

Admission order is the **policy** knob:

- ``fifo`` (default): arrival order, the original behavior.
- ``slo``: SLO classes (``high`` > ``standard`` > ``batch``) pick the
  admitted request — a high-class arrival never queues behind a
  lower class while a slot frees (pinned in tests/test_serve_slo.py).
  Two more levers ride the class order:

  * **per-tenant token quotas** (``tenant_quota``): a tenant at/over
    its decoded-token quota is DEFERRED while any under-quota request
    waits — requeued behind, never dropped, and still served when
    nothing under-quota is waiting (work-conserving, so exhaustion
    cannot starve).
  * **preempt-and-requeue** (``preempt``): when a higher-class
    request has waited out the decode-priority clock with no free
    slot, the worst live lower-class (or over-quota) request is
    preempted — freed and re-queued as a CONTINUATION (prompt +
    tokens-so-far, remaining budget; the PR-6 machinery, so it is
    journal-compatible) — and greedy determinism makes its final
    stream token-identical to the unpreempted run.

**Speculative decoding** (``speculator`` + an engine built with
``spec_tokens > 0``): each decode iteration proposes k tokens per
slot (serve/speculate.py) and retires ``accepted + 1`` of them from
ONE verify dispatch — token-identical to plain greedy, with
accepted-length telemetry in the summary (``accept_rate``). Falls
back to the plain step whenever a slot lacks verify headroom.

Termination is per request (EOS or its max-token budget), tokens
stream to the host as they retire (``on_token``), and every request's
lifecycle lands in the observe registry: ``serve_request`` records
(TTFT, per-token latency, queue steps, class/tenant) plus one final
``serve_summary`` (aggregate tokens/s, mean slot occupancy, accept
rate, preemptions) — summarized by ``observe.report`` next to the
training numbers.

Serve-under-fire (all optional; zero cost unconfigured):

- **fault plan**: consulted between decode steps on the engine's
  decode-step clock — slot_nan poisons a KV row, reload triggers a
  live weight swap, sigterm/sigkill self-signal (resilience/faults.py;
  decode_stall is consumed inside the engine's watched fetch).
- **slot-level retry**: a slot whose decode step produced non-finite
  logits is quarantined — freed and its request re-queued at the head
  as a CONTINUATION (prompt + the good tokens so far, remaining
  budget) — so one poisoned slot costs one re-prefill, never an
  engine restart, and greedy determinism keeps the final token stream
  identical. A per-request retry budget (``slot_retries``) turns
  repeated quarantine of the SAME request into
  :class:`SlotRetryExhausted` — the serve-mode divergence signal
  (exit 2; the supervisor does not hot-loop restarts on it).
- **journal**: admits/tokens/completions append to a
  :class:`serve.journal.RequestJournal`, flushed per scheduler
  iteration, so a SIGKILL'd leg is resumable at token granularity.
- **live weight swap**: ``reload_fn`` (serve/run.py wires it to
  train.checkpoint.restore_params) supplies fresh params; the engine
  swaps them in between steps with slots live; swap latency lands in
  the summary and a ``weight_swap`` recovery event.

Serve observatory (README "Serve tracing & SLO monitoring"; all
optional, zero cost unconfigured):

- **tracer** (observe/serve_trace.py): every request becomes an async
  span tree in one Perfetto trace (queue -> prefill -> decode),
  quarantine/swap/preempt drop instant markers, and counter tracks
  carry occupancy/queue/tokens-per-s/accept-rate per decode step.
- **slo_monitor** (observe/slo.py): per-completion window accounting
  + per-step multi-window burn-rate evaluation on the decode-step
  clock; ``slo_alert``/``slo_ok`` records flow through the registry.
- **metrics_snapshot() / export**: a point-in-time JSON-able view of
  the engine (queue depth, occupancy, rolling tokens/s, per-class
  TTFT percentiles, SLO budget state), emitted as
  ``metrics_snapshot`` records on ``export_every`` and atomically
  rewritten at ``export_path`` for a router/supervisor to poll.
- **status_fn/status_every**: the periodic one-line live status
  print.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json
from tensorflow_distributed_tpu.observe.slo import percentile
from tensorflow_distributed_tpu.serve.buckets import pick_bucket
from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

#: SLO classes, best first — admission under policy="slo" prefers the
#: lowest rank; everything else (request files without a class, the
#: synthetic default) is "standard".
SLO_CLASSES = ("high", "standard", "batch")
_RANK = {c: i for i, c in enumerate(SLO_CLASSES)}


def parse_slo_mix(spec: str) -> Dict[str, float]:
    """``--serve.slo-mix`` grammar: ``"high:0.25,batch:0.25"`` —
    class:fraction pairs, remainder implicitly "standard". Returns the
    full {class: fraction} map (standard filled in)."""
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"slo_mix entry {part!r} is not class:fraction")
        name, frac = (x.strip() for x in part.split(":", 1))
        if name not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {name!r}; have {SLO_CLASSES}")
        f = float(frac)
        if not 0.0 <= f <= 1.0:
            raise ValueError(
                f"slo_mix fraction for {name!r} must be in [0, 1], "
                f"got {f}")
        if name in out:
            raise ValueError(f"slo_mix names {name!r} twice")
        out[name] = f
    rest = 1.0 - sum(out.values())
    if rest < -1e-9:
        raise ValueError(
            f"slo_mix fractions sum to {sum(out.values()):g} > 1")
    out["standard"] = out.get("standard", 0.0) + max(rest, 0.0)
    return out


class SlotRetryExhausted(RuntimeError):
    """The same request was slot-quarantined past its retry budget —
    serve mode's DIVERGED equivalent (deterministic greedy decode will
    poison the same way again; restarting would hot-loop). The CLI
    maps this to exit code 2, which the supervisor refuses to
    restart."""


@dataclasses.dataclass
class Request:
    """One inference request. ``arrival_s`` is the open-loop offset
    (seconds from run start) at which the request becomes visible to
    the scheduler; 0 = present from the start. ``slo``/``tenant``
    drive the SLO scheduler (policy="slo"); FIFO ignores them."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: int = -1          # -1 = run to the full budget
    arrival_s: float = 0.0
    slo: str = "standard"     # high | standard | batch
    tenant: str = ""          # quota bucket (policy="slo")
    # Multi-turn conversation id (serve/paging): a finished request
    # tagged with a session retains its KV pages under this key, and
    # a follow-up turn whose prompt extends the conversation
    # re-attaches them instead of re-prefilling. Journaled with the
    # admit record, so a resumed leg keeps the linkage. Ignored by
    # the dense engine (turns still serve correctly — they just
    # recompute).
    session: str = ""


@dataclasses.dataclass
class Completion:
    """A finished request with its serving metrics."""

    rid: int
    prompt_len: int
    tokens: List[int]
    finish: str               # "eos" | "length"
    ttft_s: float             # arrival -> first token (queue + prefill)
    decode_s: float           # first token -> last token
    queue_steps: int          # decode steps endured while admittable
    retries: int = 0          # slot quarantines this request survived
    preempts: int = 0         # SLO preemptions this request survived
    slo: str = "standard"
    tenant: str = ""
    recovery_window: bool = False  # a recovery event (quarantine/
    #                                swap/restart continuation) fell
    #                                inside arrival->first token —
    #                                firebench's p99-TTFT-during-
    #                                recovery population
    decoded: int = 0          # tokens decoded THIS leg (excludes a
    #                           continuation's journal-replayed base —
    #                           those were decoded by the dead leg)

    @property
    def tok_ms(self) -> float:
        """Mean inter-token latency (ms) over THIS leg's decode phase
        (a continuation's base tokens were decoded by the dead leg —
        charging them here would deflate the latency)."""
        n = self.decoded or len(self.tokens)
        return 1e3 * self.decode_s / max(1, n - 1)


@dataclasses.dataclass
class _Live:
    req: Request
    slot: int
    tokens: List[int]
    t_first: float
    queue_steps: int
    base: List[int]           # tokens from before a continuation
    #                           (journal replay, slot retry, or SLO
    #                           preemption) — the completion reports
    #                           base + tokens


class Scheduler:
    """Drives a :class:`SlotDecodeEngine` over a request workload."""

    def __init__(self, engine: SlotDecodeEngine, decode_priority: int = 8,
                 registry=None,
                 on_token: Optional[Callable[[int, int, bool], None]] = None,
                 clock=time.perf_counter, fault_plan=None, journal=None,
                 reload_fn=None, slot_retries: int = 2,
                 summary_extra=None, policy: str = "fifo",
                 tenant_quota: int = 0, preempt: bool = True,
                 speculator=None, tracer=None, slo_monitor=None,
                 anomaly_hub=None, autopilot=None,
                 export_every: float = 0.0, export_path: str = "",
                 status_fn=None, status_every: int = 0,
                 feed=None, served_ckpt_step=None):
        if decode_priority < 1:
            raise ValueError(
                f"decode_priority must be >= 1, got {decode_priority}")
        if slot_retries < 0:
            raise ValueError(
                f"slot_retries must be >= 0, got {slot_retries}")
        if policy not in ("fifo", "slo"):
            raise ValueError(
                f"unknown policy {policy!r}; have ('fifo', 'slo')")
        if tenant_quota < 0:
            raise ValueError(
                f"tenant_quota must be >= 0, got {tenant_quota}")
        self.engine = engine
        self.decode_priority = decode_priority
        self.registry = registry
        self.on_token = on_token
        self.clock = clock
        self.fault_plan = fault_plan
        self.journal = journal
        self.reload_fn = reload_fn    # () -> (params, ckpt_step)
        self.slot_retries = slot_retries
        self.policy = policy
        self.tenant_quota = tenant_quota
        self.preempt = preempt
        self.speculator = speculator
        # The serve observatory (observe/serve_trace.py + observe/
        # slo.py + snapshot export): every hook below is None-safe so
        # an unobserved run pays nothing.
        self.tracer = tracer
        self.slo_monitor = slo_monitor
        # Incident detection (observe/anomaly.py): fed the TTFT /
        # decode-dispatch-wall / queue-depth values this loop already
        # holds on host, on the deterministic decode-step clock.
        self.anomaly_hub = anomaly_hub
        # The online controller (observe/autopilot.py): consulted on
        # the decode-step clock; its decisions come back as "tune"
        # commands through the SAME control path fleet drain/swap/
        # cancel commands take, so every actuation lands between
        # decode steps and token identity holds by construction.
        self.autopilot = autopilot
        # Effective live-slot cap, tunable below the engine's
        # allocated num_slots (loop 2: fewer live slots pin fewer
        # pages). 0 = uncapped.
        self._slot_cap = 0
        self._tunes = 0
        if autopilot is not None:
            autopilot.bind_scheduler(
                num_slots=int(getattr(engine, "num_slots", 0) or 0),
                spec_k=int(getattr(engine, "spec_tokens", 0) or 0),
                decode_priority=decode_priority,
                has_spec=speculator is not None)
        if export_every < 0:
            raise ValueError(
                f"export_every must be >= 0, got {export_every}")
        self.export_every = float(export_every)
        self.export_path = export_path
        self.status_fn = status_fn
        self.status_every = int(status_every)
        # Streaming intake (fleet/replica.py InboxFeed, or any object
        # with poll() -> (requests, commands)): with a feed, run()
        # serves an OPEN-ENDED stream — it keeps polling for work and
        # control commands ("swap"/"drain"/"cancel"/"hold_export")
        # until a drain command lands and the engine runs dry.
        self.feed = feed
        # The checkpoint step the served weights came from (run.py
        # sets it from the startup restore; _swap updates it) — the
        # fleet controller's model-staleness feed.
        self.served_ckpt_step = served_ckpt_step
        self.draining = False
        self._export_hold_until = 0.0
        # Monotonic snapshot sequence + wall timestamp + pid: a
        # poller can tell a FROZEN snapshot file (stale seq) from a
        # healthy idle replica (seq keeps advancing) — the fleet
        # router's liveness probe.
        self._snap_seq = 0
        # Run-identity fields (seed, trace name) merged into the
        # serve_summary RECORD so the JSONL artifact is reproducible
        # standalone (FIREBENCH re-derives workloads from it).
        self.summary_extra = dict(summary_extra or {})
        self._snap_state: Optional[dict] = None

    def _emit(self, event: str, **fields) -> None:
        if self.registry is not None:
            self.registry.emit(event, **fields)

    def _trace_instant(self, name: str, **args) -> None:
        if self.tracer is not None:
            self.tracer.instant(name, **args)

    # -- SLO selection helpers -------------------------------------------

    def _over_quota(self, tenant: str, tenant_tokens: Dict[str, int]
                    ) -> bool:
        return (self.tenant_quota > 0
                and tenant_tokens.get(tenant, 0) >= self.tenant_quota)

    def _pick_index(self, queue: List[Request],
                    tenant_tokens: Dict[str, int],
                    skip: frozenset = frozenset()) -> int:
        """Which queued request admits next. FIFO: the head. SLO:
        under-quota before over-quota (deferral, never starvation —
        over-quota requests win when nothing else waits), then class
        rank, then arrival order. ``skip``: rids NOT admissible this
        iteration (session turns waiting on an earlier turn); -1 when
        nothing qualifies."""
        if self.policy != "slo" or len(queue) <= 1:
            if not skip:
                return 0
            for i, req in enumerate(queue):
                if req.rid not in skip:
                    return i
            return -1
        best, best_key = -1, None
        for i, req in enumerate(queue):
            if req.rid in skip:
                continue
            key = (1 if self._over_quota(req.tenant, tenant_tokens)
                   else 0, _RANK.get(req.slo, 1), i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    @staticmethod
    def _session_blocked(pending, queue, live) -> frozenset:
        """Queued rids whose session has an EARLIER unfinished turn —
        a client cannot send turn j+1 before it has turn j's reply, so
        those arrivals wait for their predecessor (which also makes
        the paged engine's session re-attach deterministic instead of
        an interleaving-dependent cache miss). Requests without a
        session are never blocked."""
        earliest: Dict[str, int] = {}
        for r in list(pending) + list(queue) + [
                lv.req for lv in live.values()]:
            s = getattr(r, "session", "")
            if s and (s not in earliest or r.rid < earliest[s]):
                earliest[s] = r.rid
        out = set()
        for r in queue:
            s = getattr(r, "session", "")
            if s and earliest.get(s) != r.rid:
                out.add(r.rid)
        return frozenset(out)

    def _pick_victim(self, live: Dict[int, _Live], cand: Request,
                     tenant_tokens: Dict[str, int]) -> Optional[_Live]:
        """The live request SLO preemption evicts for ``cand``:
        strictly lower class (or over-quota while cand's tenant is
        under) — among those, the lowest class with the most tokens
        already delivered (it loses the least). None = nobody
        preemptible (equal-class work is never evicted — that would
        just swap places and thrash). A victim whose continuation
        prompt would outgrow the bucket ladder is skipped too:
        preemption is ELECTIVE, and crashing the run over a
        user-pinned tight --serve.buckets would turn policy into
        failure (quarantine keeps the loud error — its slot is
        unrecoverable either way)."""
        cand_rank = _RANK.get(cand.slo, 1)
        cand_over = self._over_quota(cand.tenant, tenant_tokens)
        ladder = max(self.engine.buckets)
        victims = []
        for lv in live.values():
            lower = _RANK.get(lv.req.slo, 1) > cand_rank
            quota_evict = (not cand_over and self._over_quota(
                lv.req.tenant, tenant_tokens)
                and lv.req.tenant != cand.tenant)
            fits_ladder = (len(lv.req.prompt) + len(lv.tokens)
                           <= ladder)
            if (lower or quota_evict) and fits_ladder:
                victims.append(lv)
        if not victims:
            return None
        return max(victims, key=lambda lv: (_RANK.get(lv.req.slo, 1),
                                            len(lv.tokens)))

    def run(self, requests: Sequence[Request]) -> List[Completion]:
        """Serve every request to completion; returns completions in
        finish order (sort by ``rid`` for submission order)."""
        eng = self.engine
        plan = self.fault_plan
        spec = self.speculator
        for r in requests:
            if not eng.fits(len(r.prompt), r.max_new_tokens):
                raise ValueError(
                    f"request {r.rid}: prompt {len(r.prompt)} + "
                    f"{r.max_new_tokens} new tokens does not fit "
                    f"(buckets up to {max(eng.buckets)}, max_len "
                    f"{eng.max_len})")
            if r.max_new_tokens < 1:
                raise ValueError(
                    f"request {r.rid}: max_new_tokens must be >= 1")
        pending = collections.deque(
            sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        queue: List[Request] = []
        live: Dict[int, _Live] = {}           # slot -> _Live
        done: List[Completion] = []
        t0 = self.clock()
        steps_since_admit = 0
        retries: dict = {}            # rid -> quarantines survived
        preempts: dict = {}           # rid -> SLO preemptions survived
        first_seen: dict = {}         # rid -> first-token time (the
        #                               TTFT point survives retries)
        tenant_tokens: Dict[str, int] = {}  # decoded tokens this run
        total_retries = 0
        total_preempts = 0
        spec_stats = {"verify_steps": 0, "proposed": 0, "accepted": 0,
                      "fallback_slots": 0}
        self._swap_seconds = 0.0
        recovery_ts: List[float] = []  # quarantine/swap times, for the
        #                                recovery-window TTFT flag
        tracer = self.tracer
        slo = self.slo_monitor
        # Session turn-ordering applies only when some request carries
        # a session id — a plain workload must not pay a per-iteration
        # scan of pending+queue+live for a constraint that cannot bind.
        has_sessions = any(getattr(r, "session", "")
                           for r in requests)
        # THIS run's decode-step tallies (the engine counters span its
        # whole lifetime — reuse would skew the occupancy mean) plus
        # the decoded-token count, shared with metrics_snapshot().
        tally = {"steps": 0, "occ_sum": 0.0, "decoded": 0}
        # Rolling (t, decoded) samples for the tokens/s counter track
        # and the snapshot's windowed rate.
        rate_win: collections.deque = collections.deque(maxlen=64)
        # Rolling (t, accepted_cum, proposed_cum) samples: the
        # windowed accept rate beside the cumulative one — a regime
        # shift in acceptance is invisible to any controller reading
        # only the lifetime ratio.
        spec_win: collections.deque = collections.deque(maxlen=64)
        self._snap_state = {
            "t0": t0, "tally": tally, "rate_win": rate_win,
            "queue": queue, "live": live, "done": done,
            "pending": pending, "retries_map": retries,
            "preempts_map": preempts, "spec_stats": spec_stats,
            "spec_win": spec_win,
        }
        self._last_export = t0

        def now() -> float:
            return self.clock() - t0

        def free_slot(lv: _Live, retain: bool) -> None:
            """Release the slot — through the paged engine's
            retention path when it has one (``retain``: the request's
            full sequence feeds the prefix cache / its session;
            quarantine passes False — poisoned pages must never be
            cached), else the plain free every engine (and the test
            fakes) implements."""
            rel = getattr(eng, "release", None)
            if rel is None:
                eng.free(lv.slot)
            elif retain:
                # graftcheck: disable=host-sync-in-loop -- builds the
                # retention token list from HOST arrays (no device
                # value); once per request lifetime event
                rel(lv.slot,
                    tokens=[int(t) for t in lv.req.prompt] + lv.tokens,
                    session=getattr(lv.req, "session", ""))
            else:
                rel(lv.slot)

        def finish(lv: _Live, why: str) -> None:
            t = now()
            free_slot(lv, retain=True)
            del live[lv.slot]
            if spec is not None:
                spec.observe_free(lv.slot)
            tokens = lv.base + lv.tokens
            t_first = first_seen.get(lv.req.rid, lv.t_first)
            n_retries = retries.get(lv.req.rid, 0)
            n_preempts = preempts.get(lv.req.rid, 0)
            # Recovery population: a quarantine/swap fell inside this
            # request's arrival->first-token window, OR the request is
            # a restart continuation (its base tokens crossed a
            # process death — the resumed leg consumed the plan, so
            # recovery_ts alone would miss exactly the requests the
            # restart hit). A PREEMPTION continuation's base is policy,
            # not recovery — excluded.
            window = (any(lv.req.arrival_s <= rt <= t_first
                          for rt in recovery_ts)
                      or (bool(lv.base)
                          and not getattr(lv.req, "_policy_base",
                                          False)))
            comp = Completion(
                rid=lv.req.rid,
                prompt_len=len(lv.req.prompt) - len(lv.base),
                tokens=tokens, finish=why,
                ttft_s=t_first - lv.req.arrival_s,
                decode_s=t - t_first, queue_steps=lv.queue_steps,
                retries=n_retries, preempts=n_preempts,
                slo=lv.req.slo, tenant=lv.req.tenant,
                recovery_window=window,
                decoded=len(lv.tokens))
            done.append(comp)
            if slo is not None:
                slo.observe(comp.slo, 1e3 * comp.ttft_s, comp.tok_ms,
                            tally["steps"])
            if self.anomaly_hub is not None:
                self.anomaly_hub.observe_completion(
                    tally["steps"], 1e3 * comp.ttft_s)
            if tracer is not None:
                tracer.request_done(comp.rid, why, len(comp.tokens),
                                    1e3 * comp.ttft_s)
            self._emit("serve_request", rid=comp.rid,
                       prompt_len=comp.prompt_len,
                       new_tokens=len(comp.tokens), finish=why,
                       ttft_ms=round(1e3 * comp.ttft_s, 3),
                       tok_ms=round(comp.tok_ms, 4),
                       queue_steps=comp.queue_steps,
                       retries=n_retries, preempts=n_preempts,
                       slo=comp.slo, tenant=comp.tenant,
                       recovery_window=window,
                       arrival_s=round(lv.req.arrival_s, 4),
                       t_first_s=round(t_first, 4))
            if self.journal is not None:
                self.journal.done(comp.rid)
            if self.on_token is not None:
                self.on_token(comp.rid, comp.tokens[-1], True)

        def count_token(req: Request) -> None:
            if req.tenant:
                tenant_tokens[req.tenant] = (
                    tenant_tokens.get(req.tenant, 0) + 1)

        def admit(pick: int) -> None:
            req = queue.pop(pick)
            if self.autopilot is not None:
                # One host int per admission: the prompt-length
                # distribution the bucket/num-pages advisories size
                # from.
                self.autopilot.observe_prompt(len(req.prompt))
            slot = eng.free_slots()[0]
            ctx = (tracer.prefill(req.rid,
                                  pick_bucket(len(req.prompt),
                                              eng.buckets), slot)
                   if tracer is not None else contextlib.nullcontext())
            with ctx:
                if getattr(eng, "paged", False):
                    # Admission context the paged engine needs: the
                    # budget sizes its page reservation, the session
                    # keys conversation re-attach.
                    first = eng.prefill(
                        req.prompt, slot,
                        max_new_tokens=req.max_new_tokens,
                        session=getattr(req, "session", ""))
                else:
                    first = eng.prefill(req.prompt, slot)
            tally["decoded"] += 1
            if spec is not None:
                spec.observe_admit(slot, req.prompt, first)
            base = list(getattr(req, "_base_tokens", ()))
            lv = _Live(req=req, slot=slot, tokens=[first],
                       t_first=now(), queue_steps=req._waited,
                       base=base)
            live[slot] = lv
            if req.rid not in first_seen:
                if not base and self.journal is not None:
                    # First-ever admission of this request (a replayed
                    # continuation was journaled by the previous leg).
                    self.journal.admit(req.rid, req.prompt,
                                       req.max_new_tokens, req.eos_id,
                                       slo=req.slo, tenant=req.tenant,
                                       session=getattr(req, "session",
                                                       ""))
                first_seen[req.rid] = lv.t_first
            if self.journal is not None:
                self.journal.token(req.rid, first, now())
            count_token(req)
            if self.on_token is not None and not (
                    first == req.eos_id or req.max_new_tokens == 1):
                self.on_token(req.rid, first, False)
            if first == req.eos_id:
                finish(lv, "eos")
            elif req.max_new_tokens == 1:
                finish(lv, "length")

        def continuation(lv: _Live) -> Request:
            """The PR-6 continuation: prompt + the good tokens so far,
            remaining budget, class/tenant preserved — greedy decode
            is deterministic, so the re-prefilled continuation emits
            exactly the tokens the original slot would have (token
            identity pinned in tests/test_serve_fire.py and
            tests/test_serve_slo.py)."""
            # graftcheck: disable=host-sync-in-loop -- builds the
            # continuation prompt from HOST token lists (no device
            # value involved); runs once per quarantine/preemption,
            # not per step
            cont = dataclasses.replace(
                lv.req,
                prompt=np.concatenate(
                    [np.asarray(lv.req.prompt, np.int32),
                     np.asarray(lv.tokens, np.int32)])
                if lv.tokens else np.asarray(lv.req.prompt, np.int32),
                max_new_tokens=lv.req.max_new_tokens - len(lv.tokens))
            if len(cont.prompt) > max(eng.buckets):
                raise ValueError(
                    f"request {lv.req.rid}: continuation prompt "
                    f"{len(cont.prompt)} exceeds the largest bucket "
                    f"{max(eng.buckets)} — re-admission needs the "
                    f"ladder sized to prompt+new tokens (serve/run.py "
                    f"does this when a fault plan, journal resume, or "
                    f"policy=slo is armed; with --serve.buckets, "
                    f"cover the full trajectory)")
            cont._base_tokens = lv.base + lv.tokens
            cont._waited = lv.queue_steps
            return cont

        def quarantine(lv: _Live) -> None:
            """Contain one poisoned slot: free it, re-queue the
            request as a continuation at the head (prompt + good
            tokens, remaining budget)."""
            nonlocal total_retries, steps_since_admit
            free_slot(lv, retain=False)
            del live[lv.slot]
            if spec is not None:
                spec.observe_free(lv.slot)
            rid = lv.req.rid
            n = retries[rid] = retries.get(rid, 0) + 1
            if n > self.slot_retries:
                raise SlotRetryExhausted(
                    f"request {rid} slot-quarantined {n} times "
                    f"(budget {self.slot_retries}): repeated NaN on "
                    f"the same request is a divergence, not a "
                    f"transient — halting instead of hot-looping "
                    f"re-prefills")
            total_retries += 1
            t = now()
            recovery_ts.append(t)
            if self.anomaly_hub is not None:
                # The engine's per-slot finiteness flag IS the
                # detection (already fetched with the step's tokens);
                # surface it as a critical anomaly beside the
                # containment's recovery record.
                self.anomaly_hub.note_slot_nonfinite(
                    tally["steps"], slot=lv.slot, rid=rid)
            self._emit("recovery", kind="slot_quarantine", rid=rid,
                       slot=lv.slot, retry=n, t_s=round(t, 4))
            if tracer is not None:
                tracer.instant("slot_quarantine", rid=rid,
                               slot=lv.slot, retry=n)
                tracer.request_evicted(rid, "quarantine")
            # graftcheck: disable=host-sync-in-loop -- builds the
            # continuation prompt from HOST token lists (no device
            # value involved); runs once per quarantine, not per step
            queue.insert(0, continuation(lv))
            # Re-admit without waiting out the decode-priority clock:
            # the request was already being served.
            steps_since_admit = self.decode_priority

        def preempt_one(lv: _Live) -> None:
            """SLO preemption: evict a live lower-class / over-quota
            request so the waiting higher-class one gets its slot.
            Same continuation machinery as quarantine (journal-
            compatible, token-identical), but no retry charge, no
            recovery event — this is policy, not failure."""
            nonlocal total_preempts
            # Retain: the victim's KV is valid, and its continuation
            # re-admits with this exact sequence as its prompt — on a
            # paged engine the preemption's re-prefill becomes a
            # prefix-cache hit instead of a full recompute.
            free_slot(lv, retain=True)
            del live[lv.slot]
            if spec is not None:
                spec.observe_free(lv.slot)
            rid = lv.req.rid
            preempts[rid] = preempts.get(rid, 0) + 1
            total_preempts += 1
            cont = continuation(lv)
            # Mark the base as policy-only — UNLESS this request
            # already carried recovery base tokens (a prior quarantine
            # or journal replay): preemption must not erase that
            # provenance, or the completion would drop out of the
            # recovery-window population.
            if not lv.base or getattr(lv.req, "_policy_base", False):
                cont._policy_base = True
            queue.append(cont)     # class selection orders the queue
            self._emit("preempt", rid=rid, slot=lv.slot,
                       slo=lv.req.slo, tenant=lv.req.tenant,
                       served=len(lv.base) + len(lv.tokens),
                       t_s=round(now(), 4))
            if tracer is not None:
                tracer.instant("preempt", cat="policy", rid=rid,
                               slot=lv.slot, slo=lv.req.slo)
                tracer.request_evicted(rid, "preempt")

        def cancel_rid(rid: int) -> None:
            """Fleet router moved this request elsewhere: drop it
            wherever it is (queue, pending, or a live slot — freed
            with retention, its KV is valid) without a completion; the
            new owner re-derives the stream (greedy determinism)."""
            for i, r in enumerate(queue):
                if r.rid == rid:
                    queue.pop(i)
                    self._emit("serve_cancel", rid=rid, where="queue")
                    return
            for i, r in enumerate(pending):
                if r.rid == rid:
                    del pending[i]
                    self._emit("serve_cancel", rid=rid,
                               where="pending")
                    return
            for slot, lv in list(live.items()):
                if lv.req.rid == rid:
                    free_slot(lv, retain=True)
                    del live[slot]
                    if spec is not None:
                        spec.observe_free(slot)
                    self._emit("serve_cancel", rid=rid, where="live",
                               slot=slot)
                    return

        def feed_cmd(cmd) -> None:
            kind = cmd.get("cmd")
            if kind == "drain":
                self.draining = True
            elif kind == "swap":
                self._swap(now, recovery_ts)
            elif kind == "cancel":
                cancel_rid(int(cmd.get("rid", -1)))
            elif kind == "hold_export":
                self._export_hold_until = (
                    self.clock() + float(cmd.get("secs", 0.0)))
            elif kind == "tune":
                self._apply_tune(cmd)

        def feed_request(r) -> None:
            nonlocal has_sessions
            bad = (self.draining or r.max_new_tokens < 1
                   or not eng.fits(len(r.prompt), r.max_new_tokens))
            if not bad:
                # Paged pool feasibility: a reservation that can
                # NEVER fit (even with the prefix cache fully
                # evicted; +1 = the worst-case COW page while the
                # radix cache is armed — can_admit's rule) must be
                # rejected here — the idle-engine admission path
                # raises, and a replica must never crash on a bad
                # dispatch.
                pf = getattr(eng, "pages_for", None)
                if pf is not None:
                    need = pf(len(r.prompt), r.max_new_tokens)
                    if getattr(eng, "radix", None) is not None:
                        need += 1
                    bad = need > eng.pool.capacity
            if bad:
                self._emit("serve_reject", rid=r.rid,
                           prompt_len=len(r.prompt),
                           max_new=r.max_new_tokens,
                           draining=self.draining)
                if self.journal is not None:
                    self.journal.reject(r.rid)
                    self.journal.flush()
                return
            # A duplicate of an already-present rid SUPERSEDES it (a
            # router double-send must not interleave two token
            # streams into one journal entry).
            cancel_rid(r.rid)
            r.arrival_s = now()
            pending.append(r)
            if getattr(r, "session", ""):
                has_sessions = True

        def poll_feed() -> None:
            """Streamed intake: new requests join ``pending`` due
            immediately; control commands act between decode steps.
            Items are processed in FILE ORDER — a stalled replica can
            read a dispatch, its cancel, and the re-dispatched
            continuation in ONE batch, and only line order makes that
            sequence mean what the router intended. An unservable
            request is REJECTED into the journal (the router sheds
            it) instead of crashing the replica."""
            for item in self.feed.poll():
                if isinstance(item, dict):
                    feed_cmd(item)
                else:
                    feed_request(item)

        while pending or queue or live or (
                self.feed is not None and not self.draining):
            if self.feed is not None:
                poll_feed()
            # Open-loop arrivals: everything whose time has come.
            while pending and pending[0].arrival_s <= now():
                req = pending.popleft()
                req._waited = 0
                queue.append(req)
                if tracer is not None:
                    tracer.request_queued(req.rid, slo=req.slo,
                                          prompt_len=len(req.prompt),
                                          tenant=req.tenant)
            if queue and eng.free_slots() and (
                    not self._slot_cap
                    or len(live) < self._slot_cap) and (
                    not live or steps_since_admit
                    >= self.decode_priority):
                # Page-pool pressure (paged engine only): the pick's
                # worst-case reservation must fit the pool after LRU
                # eviction of every reclaimable cached page. While
                # live slots hold the shortfall, keep decoding — they
                # free pages as they finish; an IDLE engine that still
                # cannot admit will never be able to, so fail loudly
                # instead of spinning.
                pick = self._pick_index(
                    queue, tenant_tokens,
                    skip=(self._session_blocked(pending, queue, live)
                          if has_sessions else frozenset()))
                if pick >= 0:
                    head = queue[pick]
                    can = getattr(eng, "can_admit", None)
                    if can is None or can(len(head.prompt),
                                          head.max_new_tokens):
                        admit(pick)
                        steps_since_admit = 0
                        if self.journal is not None:
                            self.journal.flush()
                        continue
                    if not live:
                        raise RuntimeError(
                            f"request {head.rid}: page pool cannot "
                            f"hold its reservation even with the "
                            f"engine idle and the prefix cache fully "
                            f"evicted — raise --serve.num-pages (or "
                            f"lower the request budget)")
            if (self.policy == "slo" and self.preempt and queue
                    and live and not eng.free_slots()
                    and steps_since_admit >= self.decode_priority):
                pick = self._pick_index(
                    queue, tenant_tokens,
                    skip=(self._session_blocked(pending, queue, live)
                          if has_sessions else frozenset()))
                if pick >= 0:
                    cand = queue[pick]
                    victim = self._pick_victim(live, cand,
                                               tenant_tokens)
                    if victim is not None:
                        preempt_one(victim)
                        continue   # slot freed — the admission branch
                        #            admits cand next iteration
            if not live:
                if pending:
                    # Nothing to decode, nothing admittable: sleep to
                    # the next arrival instead of spinning (bounded
                    # with a feed — new work or a command can land
                    # before the next synthetic arrival).
                    delay = max(0.0, pending[0].arrival_s - now())
                    if self.feed is not None:
                        delay = min(delay, 0.02)
                    time.sleep(delay)
                    continue
                if self.feed is not None and not self.draining:
                    # Idle but open for business: keep the snapshot
                    # export fresh (the router's liveness signal) and
                    # poll again shortly.
                    self._maybe_export()
                    time.sleep(0.02)
                    continue
                break  # queue must be empty too (free slots exist)
            if plan:
                # The serve-phase fault points, on the decode-step
                # clock (resilience/faults.py): poison, swap, signal.
                # decode_stall is consumed inside the engine's watched
                # fetch.
                nstep = eng.decode_steps + 1
                bad_slot = plan.take_slot_nan(nstep)
                if bad_slot is not None:
                    if bad_slot not in live:
                        # The drill wants a SERVING slot: the named one
                        # is momentarily empty (freed last step, next
                        # insert pending — whose full-row overwrite
                        # would neutralize the poison), so redirect to
                        # the lowest live slot. live is non-empty here
                        # (the not-live branch above already continued).
                        bad_slot = min(live)
                    eng.poison_slot(bad_slot)
                if plan.take_reload(nstep):
                    self._swap(now, recovery_ts)
                plan.maybe_signal(nstep)
            # ONE program dispatch, one host fetch — speculative when
            # armed, plain otherwise. ``emitted`` maps slot -> the
            # tokens the target model produced this dispatch, in
            # order. ``fb`` is the verify plan: None = whole-batch
            # plain step, [] = full verify, a slot list = MIXED
            # dispatch (those slots take the plain path INSIDE the
            # verify program — engine.verify_fallback_slots; fake
            # engines that only implement can_verify() keep the old
            # all-or-nothing semantics).
            # Dispatch wall for the decode-stall detector: just the
            # engine dispatch + its watched token fetch (admission /
            # prefill time excluded — a re-prefill is routine, not an
            # incident).
            t_disp = self.clock() if self.anomaly_hub is not None \
                else 0.0
            fb = None
            if spec is not None:
                fb_fn = getattr(eng, "verify_fallback_slots", None)
                if fb_fn is not None:
                    fb = fb_fn()
                elif getattr(eng, "can_verify", lambda: False)():
                    fb = []
            if fb is not None:
                # Full per-slot histories are O(prompt + decoded) host
                # work per step — built only for proposers that read
                # them (the k-gram self-draft; a draft MODEL's cache
                # IS its history and ignores the argument).
                hists = ({s: list(map(int, lv.req.prompt)) + lv.tokens
                          for s, lv in live.items()}
                         if getattr(spec, "needs_histories", True)
                         else {s: () for s in live})
                props = spec.propose(hists)
                if fb:
                    # graftcheck: disable=host-sync-in-loop -- builds
                    # the fallback slots' HOST history tails (no
                    # device value); only tight slots, only the rare
                    # headroom-starved iterations
                    tails = {s: list(map(int, live[s].req.prompt))
                             + live[s].tokens for s in fb}
                    toks, acc = eng.verify_step(props, tails=tails)
                else:
                    toks, acc = eng.verify_step(props)
                fb_set = set(getattr(eng, "last_verify_fallback", fb))
                emitted = {s: [int(t) for t in toks[s, :acc[s]]]
                           for s in live}
                spec_stats["verify_steps"] += 1
                spec_live = [s for s in live if s not in fb_set]
                spec_stats["proposed"] += int(
                    eng.spec_tokens * len(spec_live))
                spec_stats["accepted"] += int(
                    sum(acc[s] - 1 for s in spec_live))
                spec_stats["fallback_slots"] += len(
                    fb_set & set(live))
                spec.sync_from(eng)
            else:
                nxt = eng.step()
                emitted = {s: [int(nxt[s])] for s in live}
                if spec is not None:
                    spec.sync_from(eng)
            tally["occ_sum"] += eng.occupancy()
            tally["steps"] += 1
            if self.anomaly_hub is not None:
                self.anomaly_hub.observe_decode_step(
                    tally["steps"], queue_depth=len(queue),
                    step_wall_ms=1e3 * (self.clock() - t_disp))
            if queue and eng.free_slots():
                # The starvation clock: a decode step taken WHILE a
                # queued request waited with a free slot available.
                # The bound the policy guarantees (and tests pin) is
                # head-of-line: the request the policy would admit
                # waits at most decode_priority such steps.
                steps_since_admit += 1
                queue[self._pick_index(queue,
                                       tenant_tokens)]._waited += 1
            elif queue and self.policy == "slo" and self.preempt:
                # The PREEMPTION wait clock: under policy="slo" a
                # queued request facing a FULL engine also accrues
                # wait — without this the admission reset that filled
                # the last slot would freeze the clock at 0 and the
                # preemption branch above could never trigger. FIFO
                # (and slo with preempt off) keeps the original
                # free-slot-only clock: capacity waits don't count
                # against the decode-priority policy there.
                steps_since_admit += 1
                queue[self._pick_index(queue,
                                       tenant_tokens)]._waited += 1
            # Containment BEFORE token retirement: a poisoned slot's
            # tokens are garbage — quarantine drops them (never
            # appended, never journaled) and the continuation
            # re-derives them.
            for slot in getattr(eng, "take_bad_slots", lambda: [])():
                if slot in live:
                    quarantine(live[slot])
            for slot in list(live):
                lv = live[slot]
                for tok in emitted.get(slot, ()):
                    lv.tokens.append(tok)
                    tally["decoded"] += 1
                    if self.journal is not None:
                        self.journal.token(lv.req.rid, tok, now())
                    count_token(lv.req)
                    if tok == lv.req.eos_id:
                        finish(lv, "eos")
                        break
                    if len(lv.tokens) >= lv.req.max_new_tokens:
                        finish(lv, "length")
                        break
                    if self.on_token is not None:
                        self.on_token(lv.req.rid, tok, False)
            if self.journal is not None:
                self.journal.flush()
            # --- live observability, on the decode-step clock -------
            rate_win.append((now(), tally["decoded"]))
            if spec is not None:
                spec_win.append((now(), spec_stats["accepted"],
                                 spec_stats["proposed"]))
            if tracer is not None:
                counters = {"slots": eng.occupancy(),
                            "queue": float(len(queue))}
                rate = self._window_rate()
                if rate is not None:
                    counters["tokens_per_s"] = round(rate, 2)
                if spec is not None and spec_stats["proposed"]:
                    counters["accept_rate"] = round(
                        spec_stats["accepted"]
                        / spec_stats["proposed"], 4)
                tracer.counters(**counters)
            if slo is not None:
                slo.on_step(tally["steps"])
            if (self.status_fn is not None and self.status_every > 0
                    and tally["steps"] % self.status_every == 0):
                self.status_fn(self.status_line())
            if self.autopilot is not None:
                # The controller evaluates on its own cadence (the
                # off-cadence cost is one modulo — the snapshot is
                # only built on eval ticks) and its decisions route
                # through feed_cmd like any fleet command: applied
                # HERE, between decode steps, where continuation
                # semantics + greedy determinism keep every live
                # stream token-identical.
                for tc in self.autopilot.maybe_step(
                        tally["steps"], self.metrics_snapshot):
                    feed_cmd(tc)
            self._maybe_export()

        wall = now()
        total_new = sum(len(c.tokens) for c in done)
        # Throughput counts only tokens DECODED this leg: a resumed
        # leg's continuations deliver their journal-replayed base
        # tokens too (total_new_tokens — the user-facing count), but
        # those were the dead leg's work; dividing them by this leg's
        # wall would overstate tokens/s exactly when it matters.
        decoded = sum(c.decoded or len(c.tokens) for c in done)
        summary = {
            "requests": len(done),
            "total_new_tokens": total_new,
            "decoded_tokens": decoded,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(decoded / max(wall, 1e-9), 2),
            "mean_slot_occupancy": round(
                tally["occ_sum"] / max(1, tally["steps"]), 4),
            "decode_steps": tally["steps"],
            "prefills": eng.prefills,
            "prefill_compiles": eng.prefill_compiles,
            "buckets": ",".join(str(b) for b in eng.buckets),
            "num_slots": eng.num_slots,
            "decode_priority": self.decode_priority,
            "policy": self.policy,
            "preemptions": total_preempts,
            "retries": total_retries,
            "swaps": getattr(eng, "swaps", 0),
            "swap_seconds": round(self._swap_seconds, 4),
            **self._capacity_fields(),
            **self.summary_extra,
        }
        if spec is not None:
            summary.update(
                spec_tokens=getattr(eng, "spec_tokens", 0),
                verify_steps=spec_stats["verify_steps"],
                spec_proposed=spec_stats["proposed"],
                spec_accepted=spec_stats["accepted"],
                spec_fallback_slots=spec_stats["fallback_slots"],
                accept_rate=round(
                    spec_stats["accepted"]
                    / max(1, spec_stats["proposed"]), 4))
        if slo is not None:
            summary.update(slo.summary())
        if self.anomaly_hub is not None:
            summary["anomalies"] = self.anomaly_hub.count
        pstats = getattr(eng, "paging_stats", None)
        if pstats is not None:
            # Page-pool occupancy + prefix hit rate + evictions: the
            # capacity feed the item-1 router / item-5 Fleetbench
            # poll, and PAGEBENCH's FLOPs-saved arithmetic.
            summary.update(pstats())
        if self.autopilot is not None:
            summary["tune_actions"] = self._tunes
        self._emit("serve_summary", **summary)
        self.summary = summary
        if self.autopilot is not None:
            # Run-end rollup: the decision ledger plus the advisory
            # recommendations for the boot-time knobs (num_pages,
            # bucket ladder) sized from THIS run's observed peaks.
            self.autopilot.emit_summary(tally["steps"],
                                        self.metrics_snapshot())
        # One FINAL snapshot covering every completion, so the export
        # artifact's last point agrees exactly with the post-run
        # report's per-class percentiles (slobench gates this).
        if self.export_every or self.export_path:
            self._maybe_export(force=True)
        if self.journal is not None:
            self.journal.flush()
        return done

    # -- exportable rolling metrics ---------------------------------------

    def _window_rate(self) -> Optional[float]:
        """Decoded tokens/s over the rolling rate window (None until
        two samples exist)."""
        st = self._snap_state
        if st is None or len(st["rate_win"]) < 2:
            return None
        (ta, da), (tb, db) = st["rate_win"][0], st["rate_win"][-1]
        if tb <= ta:
            return None
        return (db - da) / (tb - ta)

    def _window_accept(self) -> Optional[float]:
        """Accept rate over the rolling window — accepted/proposed
        deltas between the window's endpoints (None until speculation
        has proposed inside the window). The cumulative
        ``accept_rate`` stays beside it: a regime shift moves the
        window long before it moves the lifetime ratio."""
        st = self._snap_state
        if st is None or len(st.get("spec_win", ())) < 2:
            return None
        a, b = st["spec_win"][0], st["spec_win"][-1]
        dp = b[2] - a[2]
        if dp <= 0:
            return None
        return (b[1] - a[1]) / dp

    def _apply_tune(self, cmd: Dict[str, Any]) -> None:
        """One live knob change, between decode steps (the autopilot's
        actuation path — also reachable from a fleet inbox ``tune``
        command). Values are clamped, unknown knobs are ignored (a
        replica never crashes on a bad dispatch), and every applied
        change counts into ``tune_actions``."""
        knob = cmd.get("knob")
        value = cmd.get("value")
        if knob == "decode_priority":
            self.decode_priority = max(1, int(value))
        elif knob == "slot_cap":
            ns = int(getattr(self.engine, "num_slots", 0) or 0)
            cap = max(1, int(value))
            self._slot_cap = min(cap, ns) if ns else cap
        elif knob == "preempt":
            self.preempt = bool(value)
        elif knob == "spec_k":
            k = max(1, int(value))
            set_k = getattr(self.engine, "set_spec_k", None)
            if set_k is None:
                return
            set_k(k)
            sp_set = getattr(self.speculator, "set_k", None)
            if sp_set is not None:
                sp_set(k)
        else:
            return
        self._tunes += 1

    def _capacity_fields(self) -> Dict[str, Any]:
        """HBM-capacity facts for the fleet side, PER-DEVICE honest:
        a tensor-parallel replica's cache is head-sharded over its
        mesh, so each device holds 1/tp_width of the logical bytes —
        a router pre-checking headroom from the logical figure would
        overcount a TP replica's spend tp_width-fold. Rides both
        ``serve_summary`` and :meth:`metrics_snapshot`. getattr-safe
        throughout (test fakes model neither a cache nor a mesh)."""
        out: Dict[str, Any] = {
            "tp_width": int(getattr(self.engine, "tp_width", 1))}
        bps = getattr(self.engine, "cache_bytes_per_slot", None)
        if callable(bps):
            out["per_device_cache_bytes"] = int(
                bps() * getattr(self.engine, "num_slots", 0))
        mesh = getattr(getattr(self.engine, "model", None), "mesh",
                       None)
        if mesh is not None:
            from tensorflow_distributed_tpu.parallel.mesh import (
                mesh_shape_dict)
            # "engine_mesh", not "mesh": every registry record already
            # carries the compact host "mesh" tag (observe/registry.py
            # host_tags), and fields override tags on emit.
            out["engine_mesh"] = mesh_shape_dict(mesh)
        return out

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Atomic point-in-time view of the serving engine — the exact
        payload a router / fleet supervisor polls (``--observe.
        export-every`` dumps it; ROADMAP item 1's replica router and
        item 5's Fleetbench read these fields). Callable between
        decode steps and after :meth:`run` returns; everything is a
        plain JSON-able scalar. Per-class TTFT percentiles use the
        same nearest-rank formula as ``observe.report``, so the final
        snapshot agrees exactly with the post-run report."""
        st = self._snap_state
        if st is None:
            raise RuntimeError(
                "metrics_snapshot() is available once run() has "
                "started")
        tally = st["tally"]
        now = self.clock() - st["t0"]
        self._snap_seq += 1
        snap: Dict[str, Any] = {
            # Liveness triplet: monotonic seq + wall-clock timestamp +
            # pid, so a poller (fleet/router.py) can tell a frozen
            # snapshot from a healthy idle replica — and a restarted
            # process from the one it replaced.
            "seq": self._snap_seq,
            "wall_ts": round(time.time(), 3),
            "pid": os.getpid(),
            "t_s": round(now, 4),
            "decode_steps": tally["steps"],
            "requests_done": len(st["done"]),
            "requests_live": len(st["live"]),
            "queue_depth": len(st["queue"]),
            "pending_arrivals": len(st["pending"]),
            "slot_occupancy": round(self.engine.occupancy(), 4),
            "mean_slot_occupancy": round(
                tally["occ_sum"] / max(1, tally["steps"]), 4),
            "decoded_tokens": tally["decoded"],
            "tokens_per_sec": round(
                tally["decoded"] / max(now, 1e-9), 2),
            "retries": sum(st["retries_map"].values()),
            "preemptions": sum(st["preempts_map"].values()),
            "swaps": getattr(self.engine, "swaps", 0),
            "policy": self.policy,
            # Capacity facts a router needs to pre-check dispatches
            # (engine limits are not otherwise visible fleet-side;
            # getattr: test fakes may not model a cache length).
            "num_slots": getattr(self.engine, "num_slots", 0),
            "max_len": getattr(self.engine, "max_len", 0),
        }
        snap.update(self._capacity_fields())
        if self.served_ckpt_step is not None:
            # The fleet controller's model-staleness feed: which
            # trained step these weights came from.
            snap["ckpt_step"] = int(self.served_ckpt_step)
        if self.draining:
            snap["draining"] = True
        rate = self._window_rate()
        if rate is not None:
            snap["tokens_per_sec_window"] = round(rate, 2)
        spec_stats = st["spec_stats"]
        if self.speculator is not None and spec_stats["proposed"]:
            snap["accept_rate"] = round(
                spec_stats["accepted"] / spec_stats["proposed"], 4)
            snap["spec_tokens"] = int(
                getattr(self.engine, "spec_tokens", 0) or 0)
        aw = self._window_accept()
        if aw is not None:
            snap["accept_rate_window"] = round(aw, 4)
        if self.autopilot is not None:
            snap["tune_actions"] = self._tunes
        by_cls: Dict[str, List[float]] = {}
        for c in st["done"]:
            by_cls.setdefault(c.slo, []).append(1e3 * c.ttft_s)
        for cls, vals in sorted(by_cls.items()):
            vals.sort()
            snap[f"ttft_ms_p50_{cls}"] = round(percentile(vals, 50), 3)
            snap[f"ttft_ms_p95_{cls}"] = round(percentile(vals, 95), 3)
        pstats = getattr(self.engine, "paging_stats", None)
        if pstats is not None:
            snap.update(pstats())
        lag_stats = getattr(self.feed, "lag_stats", None)
        if lag_stats is not None:
            # Inbox-poll lag (fleet replica mode): dispatch-file write
            # -> feed intake, from the router's enq_ts stamp — the
            # fleet latency decomposition's replica-side anchor and an
            # early warning for a wedged feed.
            snap.update(lag_stats())
        if self.slo_monitor is not None:
            snap["slo"] = self.slo_monitor.snapshot()
        if self.anomaly_hub is not None:
            # Live incident state (observe/anomaly.py): active
            # detectors, counts, last anomaly — so the export-path
            # pollers (ROADMAP item-1 router, item-5 Fleetbench) see
            # incident health, not just throughput.
            snap["anomaly"] = self.anomaly_hub.snapshot()
        return snap

    def _maybe_export(self, force: bool = False) -> None:
        """On the export cadence (or forced at run end): emit one
        ``metrics_snapshot`` record through the registry (the durable
        history) and atomically rewrite ``export_path`` (tmp+rename —
        the single file a poller reads is always a complete
        point-in-time snapshot, never a torn write)."""
        if not force and not self.export_every:
            return
        now = self.clock()
        if not force and now - self._last_export < self.export_every:
            return
        if not force and now < self._export_hold_until:
            # The stale-snapshot drill (fleet "hold_export" command):
            # exports freeze, the file's seq stops advancing, and the
            # router must quarantine on staleness — exactly what this
            # window exists to prove.
            return
        self._last_export = now
        snap = self.metrics_snapshot()
        self._emit("metrics_snapshot", **snap)
        if self.export_path:
            atomic_write_json(self.export_path, snap)

    def status_line(self) -> str:
        """The periodic one-line live status: occupancy, queue depth,
        throughput, and (when the monitor is armed) per-target window
        percentiles + budget burn."""
        snap = self.metrics_snapshot()
        rate = snap.get("tokens_per_sec_window",
                        snap.get("tokens_per_sec", 0.0))
        line = (f"[serve] step={snap['decode_steps']} "
                f"occ={snap['slot_occupancy']:.2f} "
                f"queue={snap['queue_depth']} "
                f"done={snap['requests_done']} "
                f"tok/s={rate:.1f}")
        if self.slo_monitor is not None:
            line += " | " + self.slo_monitor.status_bits()
        return line

    def _swap(self, now, recovery_ts: List[float]) -> None:
        """One live weight swap: fetch fresh params via ``reload_fn``
        (integrity-verified, fallback-to-newest-verifiable —
        train.checkpoint.restore_params), hand them to the engine
        between decode steps, account the latency."""
        if self.reload_fn is None:
            raise ValueError(
                "fault plan requests a reload but no reload_fn is "
                "wired (mode=serve needs --checkpoint-dir for live "
                "weight swap)")
        t0 = self.clock()
        params, ckpt_step = self.reload_fn()
        self.engine.swap_params(params)
        dt = self.clock() - t0
        self._swap_seconds += dt
        self.served_ckpt_step = ckpt_step
        t = now()
        recovery_ts.append(t)
        self._emit("recovery", kind="weight_swap",
                   seconds=round(dt, 4), ckpt_step=ckpt_step,
                   t_s=round(t, 4))
        self._trace_instant("weight_swap", seconds=round(dt, 4),
                            ckpt_step=ckpt_step)
