"""PagedSlotEngine: the slot engine over a page pool + prefix cache.

A drop-in :class:`~tensorflow_distributed_tpu.serve.engine.
SlotDecodeEngine` subclass (``--serve.paged``): the KV cache becomes a
``[num_pages, page_size, ...]`` pytree, slots hold page tables
(``[num_slots, max_pages]`` int32 fed to the jitted programs), and the
decode/verify/prefill executables gather pages through the table
INSIDE the same static-shape one-program discipline the dense engine
keeps (censused as ``serve_decode_paged`` / ``serve_verify_paged`` /
``serve_prefill_paged`` — zero collectives, drift-gated).

What paging buys (gated in benchmarks/pagebench.py -> PAGEBENCH.json):

- **no over-reserving**: a slot holds pages for its ACTUAL trajectory
  (prompt + budget, rounded up to pages), not a dense ``[max_len]``
  row — more slots fit a fixed HBM budget;
- **no recomputing**: the radix prefix cache maps a request's longest
  cached prefix (shared system prompts, few-shot headers, multi-turn
  ``session`` conversations) to refcounted pages, so prefill runs
  only on the uncached tail (bucketed as always) and TTFT on warm
  prefixes collapses. A hit attaches the ORIGINAL pages — the KV
  bytes are the ones recompute would produce, never approximated.

Correctness mechanics:

- **reserve-at-admit**: every page a request can ever touch is
  allocated (after prefix attach, after LRU eviction under pressure)
  before its prefill dispatches, so decode/verify never allocate
  mid-flight and a deterministic workload allocates deterministically;
- **copy-on-write**: when the matched prefix ends mid-page and that
  partial page is shared (refcount > 1), the engine copies it to a
  fresh page (one jitted traced-index program) before the tail
  overwrites it — the shared bytes survive for every other holder;
- **quarantine composes**: ``poison_slot`` NaN-fills only the slot's
  PRIVATE pages (shared prefix pages survive via refcounts), and a
  quarantined slot's private pages are scrubbed to zero before
  returning to the free list so poison can never leak into a later
  request through a masked column;
- **freed slots ride harmlessly**: a freed slot's table resets to the
  write-off page 0 (pool.GARBAGE_PAGE), the paged equivalent of the
  dense engine's own-row garbage writes.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.analysis import runtime as graftcheck
from tensorflow_distributed_tpu.models.generate import lookup_program
from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.observe.registry import emit_event
from tensorflow_distributed_tpu.serve.buckets import pick_bucket
from tensorflow_distributed_tpu.serve.engine import (
    SlotDecodeEngine, shard_cache)
from tensorflow_distributed_tpu.serve.paging.pool import (
    GARBAGE_PAGE, PagePool)
from tensorflow_distributed_tpu.serve.paging.radix import RadixCache


@functools.lru_cache(maxsize=64)
def _compiled_prefill_paged(model, bucket: int):
    """One jitted paged-prefill program per (model, bucket): the tail
    tokens write THROUGH the slot's page table into the pool at
    positions ``start .. start + bucket`` (``start`` = the matched
    prefix length; cached pages to the left are attended, never
    recomputed), and the greedy first token comes from the TRUE last
    tail position. Unlike the dense prefill there is no separate row
    insert — the scatter through the table IS the insert."""

    def run(params, cache, prompt, positions, table, true_len):
        logits, state = model.apply(
            {"params": params, "cache": cache}, prompt, decode=True,
            positions=positions, page_table=table, mutable=["cache"])
        last = jax.lax.dynamic_index_in_dim(
            logits, true_len - 1, axis=1, keepdims=False)   # [1, V]
        return (state["cache"],
                jnp.argmax(last, axis=-1).astype(jnp.int32))

    return observe_device.instrument_jit(
        f"serve_prefill_paged_b{bucket}", run)


@functools.lru_cache(maxsize=8)
def _compiled_step_paged(model):
    """THE paged decode program: the dense step plus the page-table
    input — attention gathers each slot's pages back into the same
    [num_slots, max_len] logical layout, so the math (and the per-slot
    finiteness flag) is the dense program's (census-pinned: zero
    collectives)."""

    def run(params, cache, tok, pos, tables):
        logits, state = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            decode=True, positions=pos[:, None], page_table=tables,
            mutable=["cache"])
        last = logits[:, -1, :]
        ok = jnp.isfinite(last).all(axis=-1)
        return (state["cache"],
                jnp.argmax(last, axis=-1).astype(jnp.int32), ok)

    return observe_device.instrument_jit("serve_decode_paged", run)


@functools.lru_cache(maxsize=8)
def _compiled_verify_paged(model, k: int):
    """THE paged speculative verify: identical to the dense verify
    (k + 1 fed positions, argmax chain, per-slot ok) with writes and
    reads routed through the page tables. Verify writes land in pages
    exactly like decode writes — rollback-on-reject stays position
    bookkeeping."""

    def run(params, cache, toks, pos, tables):
        positions = pos[:, None] + jnp.arange(k + 1)[None, :]
        logits, state = model.apply(
            {"params": params, "cache": cache}, toks, decode=True,
            positions=positions, page_table=tables, mutable=["cache"])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ok = jnp.isfinite(logits).all(axis=(-1, -2))
        return state["cache"], nxt, ok

    return observe_device.instrument_jit(f"serve_verify_paged_k{k}",
                                         run)


@jax.jit
def _copy_page_jit(cache, src, dst):
    """Copy one physical page (all cache leaves) — the COW program.
    ``src``/``dst`` are traced scalars: one executable for the
    engine's lifetime."""

    def cp(c):
        if getattr(c, "ndim", 0):
            return c.at[dst].set(c[src])
        return c

    return jax.tree_util.tree_map(cp, cache)


@jax.jit
def _scrub_pages_jit(cache, pids):
    """Zero-fill the listed pages (every cache leaf, int8 included) —
    quarantined slots' private pages are scrubbed before re-entering
    the free list so NaN poison cannot leak into a later request
    through a masked column. ``pids`` pads with the write-off page 0
    (zeroing it is harmless — it must stay finite)."""

    def z(c):
        if getattr(c, "ndim", 0):
            return c.at[pids].set(jnp.zeros((), c.dtype))
        return c

    return jax.tree_util.tree_map(z, cache)


@jax.jit
def _poison_pages_jit(cache, pids):
    """NaN-fill the float leaves of the listed pages (the slot_nan
    drill routed at PRIVATE pages only — shared prefix pages must
    survive a quarantine). ``pids`` pads by REPEATING a private page,
    never page 0 (the write-off page must stay finite)."""

    def bad(c):
        if (getattr(c, "ndim", 0)
                and jnp.issubdtype(c.dtype, jnp.floating)):
            return c.at[pids].set(jnp.full((), jnp.nan, c.dtype))
        return c

    return jax.tree_util.tree_map(bad, cache)


class PagedSlotEngine(SlotDecodeEngine):
    """The slot engine over a page pool (see module docstring). Extra
    ctor knobs: ``page_size`` (tokens per page; must divide the
    model's max_len), ``num_pages`` (pool size incl. the write-off
    page; 0 = auto: twice the dense worst case, half serving and half
    prefix cache), ``radix`` (False = paging without the prefix
    cache — pure allocation, for A/Bs)."""

    #: serve/scheduler.py keys admission context (max_new_tokens,
    #: session) and retention on this.
    paged = True

    def __init__(self, model, params, num_slots: int,
                 page_size: int = 16, num_pages: int = 0,
                 radix: bool = True, **kw):
        cfg = model.cfg
        if page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {page_size}")
        if cfg.max_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide the model's "
                f"max_len {cfg.max_len} (serve/run.py rounds --seq-len "
                f"up for you)")
        max_pages = cfg.max_len // page_size
        if num_pages <= 0:
            num_pages = 1 + 2 * num_slots * max_pages
        if num_pages < 1 + max_pages:
            raise ValueError(
                f"num_pages {num_pages} cannot hold even one "
                f"full-depth request ({max_pages} pages + the "
                f"write-off page)")
        self.page_size = int(page_size)
        self.max_pages = int(max_pages)
        # The paged model: same params, same family — only the cache
        # collection's layout (and the page_table input) differ.
        pcfg = dataclasses.replace(cfg, kv_page_size=int(page_size),
                                   kv_num_pages=int(num_pages))
        paged_model = type(model)(pcfg, model.mesh)
        self.pool = PagePool(num_pages, page_size)
        self.radix: Optional[RadixCache] = (RadixCache(self.pool)
                                            if radix else None)
        self.tables = np.zeros((num_slots, max_pages), np.int32)
        self.page_count = np.zeros((num_slots,), np.int32)
        # First position each slot may WRITE (the matched prefix
        # length): verify-fallback re-feeds must never dip into shared
        # pages (see verify_fallback_slots).
        self.private_start = np.zeros((num_slots,), np.int32)
        self._poisoned: set = set()
        # Slots whose decode flag went non-finite (sticky until the
        # slot is released or re-admitted): release() must not trust a
        # STALE _last_ok row for a slot that finished without another
        # decode step.
        self._flagged: set = set()
        # Cached device copy of the page tables (invalidated by the
        # two mutation sites: prefill and release).
        self._tables_dev = None
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.prefix_hits = 0
        self.prefill_tokens_computed = 0
        self.prefill_tokens_dense = 0
        self.cow_copies = 0
        self.page_evictions = 0
        # Peak DISTINCT pages held by live slots (shared prefix pages
        # counted once) — the serving working set an HBM budget must
        # actually cover; cached (radix/session) pages are evictable
        # under pressure and sit outside it. PAGEBENCH's
        # slots-at-budget gate divides the dense reservation by this.
        self.slot_pages_peak = 0
        super().__init__(paged_model, params, num_slots, **kw)

    # -- programs ----------------------------------------------------------

    def _build_programs(self) -> None:
        self._step_fn = lookup_program(_compiled_step_paged, self.model)
        self._verify_fn = (lookup_program(_compiled_verify_paged,
                                          self.model, self.spec_tokens)
                           if self.spec_tokens else None)

    def _tables_device(self):
        """Device-resident page tables, re-uploaded only after an
        admission/release mutated them — the decode loop must not pay
        a host-to-device table transfer per step (and, like the dense
        engine's slot scalars, the upload stays OUTSIDE the transfer
        guard: it is the designed input path)."""
        if self._tables_dev is None:
            self._tables_dev = self._h2d(self.tables)
        return self._tables_dev

    def _dispatch_step(self, tok, pos):
        tables = self._tables_device()
        with graftcheck.transfer_guard(self._check):
            return self._step_fn(self.params, self.cache, tok, pos,
                                 tables)

    def _dispatch_verify(self, tok, pos):
        tables = self._tables_device()
        with graftcheck.transfer_guard(self._check):
            return self._verify_fn(self.params, self.cache, tok, pos,
                                   tables)

    def _zero_cache(self):
        tok = jnp.zeros((self.num_slots, 1), jnp.int32)
        pos = jnp.zeros((self.num_slots, 1), jnp.int32)
        pt = jnp.zeros((self.num_slots, self.max_pages), jnp.int32)
        shapes = jax.eval_shape(
            lambda p, t, q, g: self.model.apply(
                {"params": p}, t, decode=True, positions=q,
                page_table=g, mutable=["cache"])[1]["cache"],
            self.params, tok, pos, pt)
        # The paged pool's head axis sits at dim 2 like the dense
        # cache's ([num_pages, page_size, nk, dh]) — the same TP
        # placement applies (no-op at width 1).
        return shard_cache(self.model, jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes))

    # -- accounting --------------------------------------------------------

    def page_bytes(self) -> int:
        """PER-DEVICE HBM per page summed over the cache leaves (int8
        scale leaves included) — the unit the "choosing num_slots
        under an HBM budget" arithmetic multiplies (README "Paged
        KV"). Under TP every pool leaf is head-sharded over "model"
        (shard_cache), so each device holds ``1/tp_width`` of a page's
        logical bytes — exact division, no-op at width 1."""
        return sum(
            int(np.prod(c.shape[1:])) * c.dtype.itemsize
            for c in jax.tree_util.tree_leaves(self.cache)
            if getattr(c, "ndim", 0)
            and c.shape[:1] == (self.pool.num_pages,)) // self.tp_width

    def cache_bytes_per_slot(self) -> int:
        """WORST-CASE bytes per slot (a full-depth request holds
        ``max_pages`` pages) — comparable to the dense engine's
        number. The paged win is that real requests hold
        ``ceil(trajectory / page_size)`` pages and shared prefixes
        are held once; ``paging_stats()`` carries the measured
        occupancy."""
        return self.page_bytes() * self.max_pages

    def pages_for(self, prompt_len: int, max_new_tokens: int) -> int:
        """Pages a request's full trajectory reserves at admission."""
        horizon = min(prompt_len + max(1, max_new_tokens),
                      self.max_len)
        return -(-horizon // self.page_size)

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Worst-case admission check (ignores prefix hits, which only
        reduce the need): the pool — after evicting every reclaimable
        cached page — can cover the reservation, PLUS the one extra
        page a copy-on-write may consume when a cached match ends
        mid-page (attached pages stop being evictable, so without the
        +1 a tight pool could pass here and still exhaust inside
        prefill — found in review, pinned in tests/test_paging.py).
        The scheduler defers admission while this is False and live
        slots will free pages; False with an IDLE engine means the
        pool is simply too small (loud error, never a silent hang)."""
        need = self.pages_for(prompt_len, max_new_tokens)
        if (self.radix is not None
                and self.radix.cached_pages > 0):
            need += 1                      # the potential COW page
        if need <= self.pool.free_count:   # fast path: no tree walk
            return True
        avail = self.pool.free_count + (
            self.radix.reclaimable_pages if self.radix is not None
            else 0)
        return need <= avail

    def paging_stats(self) -> dict:
        """The page-pool / prefix-cache view folded into
        ``serve_summary`` and ``metrics_snapshot`` (the ROADMAP item-1
        router and item-5 Fleetbench capacity feed)."""
        out = {
            "page_size": self.page_size,
            "num_pages": self.pool.capacity,
            "page_bytes": self.page_bytes(),
            "pages_per_max_len": self.max_pages,
            **self.pool.stats(),
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prompt_tokens": self.prompt_tokens,
            "prefix_hit_rate": round(
                self.prefix_hit_tokens / max(1, self.prompt_tokens),
                4),
            "prefill_tokens_computed": self.prefill_tokens_computed,
            "prefill_tokens_dense": self.prefill_tokens_dense,
            "cow_copies": self.cow_copies,
            "page_evictions": self.page_evictions,
            "slot_pages_peak": self.slot_pages_peak,
        }
        if self.radix is not None:
            out["cached_pages"] = self.radix.cached_pages
            out["sessions"] = self.radix.sessions_live
        return out

    # -- allocation --------------------------------------------------------

    def _acquire(self, n: int):
        """``n`` fresh pages, evicting LRU cached entries under
        pressure (each eviction emits a ``page_evict`` record)."""
        if n <= 0:
            return []
        evicted = 0
        while (self.pool.free_count < n and self.radix is not None
               and self.radix.evict_one()):
            evicted += 1
        if evicted:
            self.page_evictions += evicted
            emit_event("page_evict", evicted=evicted,
                       reason="pressure",
                       pages_free=self.pool.free_count,
                       pages_in_use=self.pool.pages_in_use)
        return self.pool.alloc(n)

    # -- admission ---------------------------------------------------------

    def prefill(self, prompt: np.ndarray, slot: int,
                max_new_tokens: int = 0, session: str = "") -> int:
        """Admit a request: longest-cached-prefix attach (radix or
        session), copy-on-write of a shared partial page, full-
        trajectory page reservation, then a bucketed prefill of ONLY
        the uncached tail. Returns the first generated token."""
        # graftcheck: disable=host-sync-in-loop -- normalizes the HOST
        # prompt the scheduler handed in; no device value involved
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        ps = self.page_size
        need_total = self.pages_for(plen, max_new_tokens)
        pages, m = [], 0
        if self.radix is not None:
            # At least one tail token must run (cap = plen - 1): the
            # first-token logits come from a computed position.
            pages, m, _src = self.radix.lookup(session, prompt,
                                               cap=plen - 1)
        fresh = self._acquire(need_total - len(pages))
        if m % ps and pages:
            # The tail's first write lands inside the matched chain's
            # last page. Shared -> copy-on-write (the cached bytes
            # survive for every other holder); sole-owned (a consumed
            # session's partial tail) -> write in place.
            li = m // ps
            if self.pool.ref[pages[li]] > 1:
                dst = self._acquire(1)[0]
                self.cache = _copy_page_jit(
                    self.cache, jnp.asarray(int(pages[li]), jnp.int32),
                    jnp.asarray(int(dst), jnp.int32))
                self.pool.release([pages[li]])
                pages[li] = dst
                self.cow_copies += 1
        table = [int(p) for p in pages] + fresh
        self.tables[slot, :] = GARBAGE_PAGE
        self.tables[slot, :len(table)] = table
        self.page_count[slot] = len(table)
        self.private_start[slot] = m
        self._tables_dev = None
        tail = prompt[m:]
        tlen = len(tail)
        bucket = pick_bucket(tlen, self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :tlen] = tail
        positions = m + np.arange(bucket, dtype=np.int32)[None, :]
        fn = lookup_program(_compiled_prefill_paged, self.model,
                            bucket)
        self._buckets_used.add(bucket)
        with self._span(f"prefill_b{bucket}", slot=slot,
                        prompt_len=plen):
            self.cache, first = fn(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(positions),
                jnp.asarray(self.tables[slot:slot + 1]),
                jnp.asarray(tlen, jnp.int32))
            # graftcheck: disable=host-sync-in-loop -- the TTFT point:
            # the first token must reach the host to be streamed; one
            # scalar per ADMISSION, not per decode step
            first_tok = int(jax.device_get(first)[0])
        self.tok[slot] = first_tok
        self.pos[slot] = plen
        self.active[slot] = True
        self.prefills += 1
        live = {int(p)
                for s in range(self.num_slots) if self.active[s]
                for p in self.tables[s, :int(self.page_count[s])]}
        self.slot_pages_peak = max(self.slot_pages_peak, len(live))
        self.prompt_tokens += plen
        self.prefill_tokens_computed += bucket
        self.prefill_tokens_dense += pick_bucket(
            plen, self.buckets) if plen <= max(self.buckets) else plen
        if m:
            self.prefix_hits += 1
            self.prefix_hit_tokens += m
            emit_event("prefix_hit", slot=slot, prompt_len=plen,
                       hit_tokens=m, tail_bucket=bucket,
                       session=session or None)
        return first_tok

    # -- release / retention ----------------------------------------------

    def release(self, slot: int, tokens=None, session: str = ""
                ) -> None:
        """Free a slot. With ``tokens`` (the request's full
        prompt + emitted sequence) the WRITTEN prefix is retained:
        full blocks into the radix tree, the whole thing (partial tail
        page included) under ``session`` when set. A slot whose last
        step flagged non-finite (or was poison-drilled) retains
        nothing — its private pages are scrubbed to zero before
        re-entering the free list; its SHARED pages survive untouched
        (refcounts guarantee no write ever reached them)."""
        n = int(self.page_count[slot])
        ids = [int(p) for p in self.tables[slot, :n]]
        bad = slot in self._poisoned or slot in self._flagged
        if bad and ids:
            priv = [p for p in ids if self.pool.ref[p] == 1]
            if priv:
                pids = np.full((self.max_pages,), GARBAGE_PAGE,
                               np.int32)
                pids[:len(priv)] = priv
                self.cache = _scrub_pages_jit(self.cache,
                                              jnp.asarray(pids))
        elif tokens is not None and self.radix is not None and ids:
            written = int(self.pos[slot])
            toks = [int(t) for t in tokens][:written]
            if toks:
                cover = -(-len(toks) // self.page_size)
                self.radix.insert(toks, ids)
                if session:
                    self.radix.session_store(session, toks,
                                             ids[:cover])
        self.pool.release(ids)
        self.tables[slot, :] = GARBAGE_PAGE
        self.page_count[slot] = 0
        self.private_start[slot] = 0
        self._poisoned.discard(slot)
        self._flagged.discard(slot)
        self._tables_dev = None
        super().free(slot)

    def free(self, slot: int) -> None:
        """Plain free (no retention) — quarantine and fake-engine-
        compatible scheduler paths land here."""
        self.release(slot)

    def take_bad_slots(self):
        out = super().take_bad_slots()
        self._flagged.update(out)
        return out

    # -- speculation -------------------------------------------------------

    def verify_fallback_slots(self):
        """Like the dense engine's, plus one paged guard: a fallback
        re-feed writes positions ``pos - k .. pos``, and if that dips
        below the slot's first PRIVATE position (a shared prefix page
        would be rewritten — bit-identity across programs is not a
        promise worth betting shared pages on), the whole batch takes
        the plain step instead."""
        out = super().verify_fallback_slots()
        if not out:
            return out
        k = self.spec_tokens
        for s in out:
            if self.pos[s] - k < self.private_start[s]:
                return None
        return out

    # -- fire drills -------------------------------------------------------

    def poison_slot(self, slot: int) -> None:
        """slot_nan drill, paged: NaN-fill the slot's PRIVATE pages
        only (refcount 1 — shared prefix pages must survive the
        quarantine; the satellite test pins that a later request
        still hits them and decodes correctly). Every admitted slot
        owns at least its tail page, so the poison always reaches an
        attended position."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(
                f"slot_nan slot {slot} out of range [0, "
                f"{self.num_slots})")
        floats = sum(
            1 for c in jax.tree_util.tree_leaves(self.cache)
            if getattr(c, "ndim", 0)
            and jnp.issubdtype(c.dtype, jnp.floating))
        if not floats:
            raise ValueError(
                "slot_nan: the decode cache has no float leaves to "
                "poison")
        n = int(self.page_count[slot])
        priv = [int(p) for p in self.tables[slot, :n]
                if self.pool.ref[p] == 1]
        if not priv:
            raise ValueError(
                f"slot_nan: slot {slot} holds no private pages "
                f"(is it admitted?)")
        pids = np.full((self.max_pages,), priv[0], np.int32)
        pids[:len(priv)] = priv
        self.cache = _poison_pages_jit(self.cache, jnp.asarray(pids))
        self._poisoned.add(slot)

    # -- warmup ------------------------------------------------------------

    def warmup(self, speculator=None) -> None:
        """Dispatch every paged program once (each bucket's prefill,
        the decode step, the verify when armed, the COW copy and the
        scrub) against the write-off page, then roll the cache back —
        same contract as the dense warmup: a warmed engine is
        byte-identical to a fresh one, and pool/table bookkeeping is
        untouched (warmup never allocates)."""
        cache0 = self.cache
        t1 = jnp.zeros((1, self.max_pages), jnp.int32)
        for b in self.buckets:
            fn = lookup_program(_compiled_prefill_paged, self.model, b)
            self.cache, _ = fn(
                self.params, self.cache, jnp.zeros((1, b), jnp.int32),
                jnp.zeros((1, b), jnp.int32), t1,
                jnp.asarray(1, jnp.int32))
        out = self._step_fn(self.params, self.cache,
                            jnp.asarray(self.tok),
                            jnp.asarray(self.pos),
                            jnp.asarray(self.tables))
        if self._verify_fn is not None:
            out = self._verify_fn(
                self.params, out[0],
                jnp.zeros((self.num_slots, self.spec_tokens + 1),
                          jnp.int32),
                jnp.zeros((self.num_slots,), jnp.int32),
                jnp.asarray(self.tables))
        zero = jnp.asarray(0, jnp.int32)
        self.cache = _copy_page_jit(out[0], zero, zero)
        pids = jnp.zeros((self.max_pages,), jnp.int32)
        # Poison then scrub the write-off page: both drill programs
        # warm, and page 0 ends finite (all-zero) as it must.
        self.cache = _poison_pages_jit(self.cache,
                                       jnp.asarray(
                                           np.full((self.max_pages,),
                                                   0, np.int32)))
        self.cache = _scrub_pages_jit(self.cache, pids)
        # graftcheck: disable=host-sync-in-loop -- startup-only drain
        # of the warmup dispatches; runs once per process, never in
        # the decode loop
        jax.block_until_ready(self.cache)
        self.cache = cache0
        warm = getattr(speculator, "warmup", None)
        if warm is not None:
            warm()


# -- num_pages auto-sizing (serve/run.py; README "Paged KV") ---------------

def page_bytes_estimate(cfg, page_size: int, tp: int = 1) -> int:
    """PER-DEVICE bytes one page will occupy, from the model CONFIG
    alone — so ``--serve.num-pages`` can be sized BEFORE any cache (or
    compiled program) exists. Mirrors the cache leaves
    models/transformer.py creates (K + V rows in the cache dtype, plus
    the f32 per-(token, head) absmax scales under int8), divided by
    the TP width ``tp`` (the pool is head-sharded over "model" —
    shard_cache); parity with the built engine's measured
    :meth:`PagedSlotEngine.page_bytes` is pinned in
    tests/test_fleet.py."""
    nk = cfg.n_kv_heads or cfg.n_heads
    dh = cfg.d_model // cfg.n_heads
    if cfg.kv_cache_quant == "int8":
        per_tok = 2 * nk * dh + 2 * nk * 4   # int8 rows + f32 scales
    else:
        per_tok = 2 * nk * dh * np.dtype(cfg.compute_dtype).itemsize
    return int(page_size) * int(cfg.n_layers) * int(per_tok) \
        // max(1, int(tp))


def auto_num_pages(*, num_slots: int, need_pages: int,
                   page_bytes: int, budget_bytes: int = 0,
                   reserved_bytes: int = 0, observed_peak: int = 0):
    """The ``--serve.num-pages`` default: ``(num_pages, rationale)``.

    Sizing, replacing the old blind ``1 + 2 * slots * max_pages``
    heuristic:

    - **serving reservation** ``S = num_slots * need_pages`` — what
      reserve-at-admit can pin with every slot holding a worst-case
      trajectory (``need_pages`` = the workload bound in pages);
    - **prefix-cache headroom** — ``observed_peak`` (a previous run's
      measured ``slot_pages_peak``: the distinct-page working set
      live slots actually held) when available, else ``S``: the cache
      gets room for about one measured working set instead of a
      second dense worst case;
    - **pool** = 1 write-off page + S + headroom, floored at
      ``2 + S`` (one COW page above the reservation — below that
      admission could never clear);
    - an ``hbm_budget_gb`` cap bounds the pool at
      ``(budget - reserved) / page_bytes`` (``reserved`` = the
      non-cache resident bytes, in practice the params), never below
      the floor — the pool must still hold the reservation.

    The rationale lines are printed by serve/run.py so a sizing
    decision is always auditable in the run log.
    """
    serving = int(num_slots) * int(need_pages)
    floor = 2 + serving
    headroom = int(observed_peak) if observed_peak else serving
    pool = 1 + serving + headroom
    lines = [
        f"serving reservation: {num_slots} slots x {need_pages} "
        f"pages = {serving} pages",
        ("prefix-cache headroom: observed slot_pages_peak "
         f"{observed_peak}" if observed_peak else
         f"prefix-cache headroom: {serving} pages (no observed "
         f"slot_pages_peak — worst case)"),
    ]
    if budget_bytes:
        avail = max(0, int(budget_bytes) - int(reserved_bytes))
        cap = avail // max(1, int(page_bytes))
        lines.append(
            f"hbm budget: ({budget_bytes} - {reserved_bytes} "
            f"reserved) / {page_bytes} B/page = {cap} pages")
        pool = min(pool, cap)
    pool = max(pool, floor)
    lines.append(f"num_pages = {pool} (floor {floor})")
    return pool, lines
