"""Radix prefix cache + multi-turn sessions over the page pool.

A radix tree over token ids at PAGE granularity: each edge is one full
page's worth of token ids (a ``page_size``-tuple), each node holds the
page whose KV encodes exactly those tokens in that left context. A new
request walks its prompt block by block and attaches the longest
matched chain of pages — prefill then runs only on the uncached tail.
Page-granular matching keeps the correctness story trivial: a cached
page is reused only when EVERY token to its left matches, so the KV
bytes are exactly what recomputation would produce (attention at a
position reads only tokens at or before it).

**Sessions** extend matching past full pages: a finished request tagged
with a ``session`` id retains ALL its pages — the partial tail page
included — keyed by the conversation's token sequence. A follow-up
turn whose prompt extends the conversation re-attaches everything,
including mid-page, and the engine copy-on-writes the partial page if
anything else still references it.

**Eviction**: cached entries (radix leaves and sessions) hold pool
references like any slot. Under pool pressure the engine asks for LRU
eviction, preferring entries whose release actually frees pages (a
cached page also attached to a live slot frees nothing yet). All host
bookkeeping, deterministic (a monotone touch clock, FIFO ties) —
pinned in tests/test_paging.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from tensorflow_distributed_tpu.serve.paging.pool import PagePool


class _Node:
    __slots__ = ("children", "page", "lru", "parent", "block")

    def __init__(self, page: Optional[int], parent, block):
        self.children: Dict[tuple, "_Node"] = {}
        self.page = page
        self.lru = 0
        self.parent = parent
        self.block = block


class _Session:
    __slots__ = ("tokens", "pages", "lru")

    def __init__(self, tokens: List[int], pages: List[int], lru: int):
        self.tokens = tokens
        self.pages = pages
        self.lru = lru


class RadixCache:
    """Host-side prefix cache; every page it holds carries one pool
    reference until evicted."""

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._root = _Node(None, None, None)
        self._sessions: Dict[str, _Session] = {}
        self._clock = 0
        self._nodes = 0
        self.evictions = 0
        self.hits = 0
        self.hit_tokens = 0
        self.lookups = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- lookup ------------------------------------------------------------

    def lookup(self, session: str, prompt: Sequence[int], cap: int
               ) -> Tuple[List[int], int, str]:
        """Longest cached prefix of ``prompt``, at most ``cap`` tokens
        (the engine caps at ``len(prompt) - 1``: at least one tail
        token must run so the first-token logits exist). Returns
        ``(pages, matched, source)`` — the caller OWNS one reference
        per returned page (session pages transfer theirs; radix pages
        are retained here) and must release them.

        A matching session (its recorded conversation is a prefix of
        ``prompt``) wins over the radix walk — it is at least as long
        (the radix holds only its full blocks) and carries the partial
        tail page. The session entry is consumed by the match (its
        references transfer to the slot); the finishing turn re-stores
        it. A session whose conversation is NOT a prefix of the new
        prompt has diverged and is dropped."""
        self.lookups += 1
        ps = self.page_size
        prompt = [int(t) for t in prompt]
        cap = max(0, min(cap, len(prompt)))
        if session and session in self._sessions:
            ent = self._sessions[session]
            n = len(ent.tokens)
            if n and n <= len(prompt) and ent.tokens == prompt[:n]:
                m = min(n, cap)
                keep = -(-m // ps) if m else 0
                pages = ent.pages[:keep]
                # Transfer: the session's refs on the kept pages move
                # to the caller; refs on the surplus are dropped.
                self.pool.release(ent.pages[keep:])
                del self._sessions[session]
                if m:
                    self.hits += 1
                    self.hit_tokens += m
                    return pages, m, "session"
                self.pool.release(pages)
                return [], 0, ""
            # Diverged conversation: the cached turn is stale.
            self.pool.release(ent.pages)
            del self._sessions[session]
        pages: List[int] = []
        node = self._root
        # Walk every full block of the PROMPT; the cap clamps after —
        # a fully-cached prompt then matches cap = plen - 1 tokens
        # mid-page, and the engine copy-on-writes that shared partial
        # page before the one-token tail overwrites it.
        for i in range(len(prompt) // ps):
            child = node.children.get(tuple(prompt[i * ps:(i + 1) * ps]))
            if child is None:
                break
            child.lru = self._tick()
            pages.append(child.page)
            node = child
        if not pages:
            return [], 0, ""
        self.pool.retain(pages)
        m = min(len(pages) * ps, cap)
        keep = -(-m // ps)
        if keep < len(pages):                # cap landed mid-chain
            self.pool.release(pages[keep:])
            pages = pages[:keep]
        self.hits += 1
        self.hit_tokens += m
        return pages, m, "radix"

    # -- insert / retention ------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]
               ) -> int:
        """Adopt the full-block prefix of ``tokens`` into the tree:
        ``pages[i]`` encodes tokens ``[i*ps, (i+1)*ps)``. Blocks
        already cached keep their EXISTING page (the offered duplicate
        stays the caller's to release); new blocks retain the offered
        page. Returns how many pages were adopted."""
        ps = self.page_size
        tokens = [int(t) for t in tokens]
        node, adopted = self._root, 0
        for i in range(len(tokens) // ps):
            block = tuple(tokens[i * ps:(i + 1) * ps])
            child = node.children.get(block)
            if child is None:
                if i >= len(pages):
                    break
                child = _Node(int(pages[i]), node, block)
                self.pool.retain([child.page])
                node.children[block] = child
                self._nodes += 1
                adopted += 1
            child.lru = self._tick()
            node = child
        return adopted

    def session_store(self, session: str, tokens: Sequence[int],
                      pages: Sequence[int]) -> None:
        """Retain a finished turn's full KV (partial tail page
        included) under its session id, replacing any stale entry."""
        if not session:
            return
        old = self._sessions.pop(session, None)
        if old is not None:
            self.pool.release(old.pages)
        pages = [int(p) for p in pages]
        self.pool.retain(pages)
        self._sessions[session] = _Session(
            [int(t) for t in tokens], pages, self._tick())

    # -- eviction ----------------------------------------------------------

    def _leaves(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            node = stack.pop()
            kids = list(node.children.values())
            if not kids and node is not self._root:
                out.append(node)
            stack.extend(kids)
        return out

    @property
    def reclaimable_pages(self) -> int:
        """Pages whose ONLY reference is this cache — what eviction
        could return to the pool right now (the engine's can_admit
        headroom)."""
        seen = set()
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if self.pool.ref[node.page] == 1:
                seen.add(node.page)
            stack.extend(node.children.values())
        for ent in self._sessions.values():
            for p in ent.pages:
                if self.pool.ref[p] == 1:
                    seen.add(p)
        return len(seen)

    @property
    def cached_pages(self) -> int:
        return self._nodes + sum(len(e.pages)
                                 for e in self._sessions.values())

    @property
    def sessions_live(self) -> int:
        return len(self._sessions)

    def evict_one(self) -> bool:
        """Evict the least-recently-used cached entry (one radix leaf
        or one whole session), preferring entries whose release frees
        at least one page. Returns False when nothing is evictable."""
        cands: List[Tuple[Tuple[int, int], str, object]] = []
        for node in self._leaves():
            frees = int(self.pool.ref[node.page] == 1)
            cands.append(((1 - frees, node.lru), "node", node))
        for sid, ent in self._sessions.items():
            frees = int(any(self.pool.ref[p] == 1 for p in ent.pages))
            cands.append(((1 - frees, ent.lru), "session", sid))
        if not cands:
            return False
        _, kind, obj = min(cands, key=lambda c: c[0])
        if kind == "node":
            node = obj
            self.pool.release([node.page])
            del node.parent.children[node.block]
            self._nodes -= 1
        else:
            ent = self._sessions.pop(obj)
            self.pool.release(ent.pages)
        self.evictions += 1
        return True

    def stats(self) -> dict:
        return {
            "prefix_hits": self.hits,
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_lookups": self.lookups,
            "cached_pages": self.cached_pages,
            "sessions": self.sessions_live,
            "page_evictions": self.evictions,
        }
