"""Page-pool allocator: host-side bookkeeping for the paged KV cache.

Pure stdlib + numpy, jax-free by design (the fast test tier exercises
every invariant without a device). The pool owns nothing on device —
it hands out PAGE IDS; the engine's jitted programs read/write the
``[num_pages, page_size, ...]`` cache pytree through per-slot page
tables built from those ids.

Invariants (pinned in tests/test_paging.py):

- page 0 is the **write-off page**: permanently referenced, never
  allocated, never exposed to an unmasked attention column. Freed
  slots keep riding the static-shape decode step (their page tables
  reset to all-zeros), so their garbage writes land here — the paged
  equivalent of the dense engine's "freed slots write their own row
  harmlessly".
- every other page is either FREE (refcount 0, on the free list) or
  referenced (refcount = slots holding it + radix nodes + sessions).
- ``release`` of a page the caller does not hold (double free) and
  ``alloc`` beyond capacity raise loudly — allocator corruption must
  never become silent KV corruption.
- allocation order is deterministic (FIFO free list), so a seeded run
  allocates, evicts, and copies the same pages every time.
"""

from __future__ import annotations

import collections
from typing import Iterable, List

import numpy as np

#: The write-off page id (see module docstring).
GARBAGE_PAGE = 0


class PoolExhausted(RuntimeError):
    """alloc() asked for more pages than the pool has free — after
    LRU eviction of every reclaimable cached page (the engine evicts
    BEFORE allocating). The run is misconfigured: the pool cannot hold
    the concurrent working set (raise --serve.num-pages, or lower
    --serve.num-slots / the per-request budget)."""


class PagePool:
    """Refcounted fixed-size page allocator (host side only)."""

    def __init__(self, num_pages: int, page_size: int):
        if page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {page_size}")
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the write-off "
                f"page), got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.ref = np.zeros((num_pages,), np.int32)
        self.ref[GARBAGE_PAGE] = 1          # permanently reserved
        self._free: collections.deque = collections.deque(
            range(1, num_pages))
        self.peak_in_use = 0
        self.allocs = 0

    # -- accounting --------------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        """Referenced pages, write-off page excluded."""
        return self.num_pages - 1 - len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable pages (write-off page excluded)."""
        return self.num_pages - 1

    # -- alloc / refcounts -------------------------------------------------

    def alloc(self, n: int) -> List[int]:
        """Hand out ``n`` fresh pages (refcount 0 -> 1), FIFO order."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)} free of "
                f"{self.capacity} (pool too small for the concurrent "
                f"working set — raise --serve.num-pages)")
        out = [self._free.popleft() for _ in range(n)]
        for p in out:
            if self.ref[p] != 0:
                raise RuntimeError(
                    f"free-list page {p} has refcount {self.ref[p]} "
                    f"(allocator corruption)")
            self.ref[p] = 1
        self.allocs += n
        self.peak_in_use = max(self.peak_in_use, self.pages_in_use)
        return out

    def retain(self, pids: Iterable[int]) -> None:
        """Add one reference per listed page (a second slot, a radix
        node, a session adopting it)."""
        for p in pids:
            p = int(p)
            if not 0 < p < self.num_pages:
                raise ValueError(f"retain of invalid page {p}")
            if self.ref[p] <= 0:
                raise RuntimeError(
                    f"retain of unreferenced page {p} (use alloc)")
            self.ref[p] += 1

    def release(self, pids: Iterable[int]) -> int:
        """Drop one reference per listed page; pages reaching 0 return
        to the free list. Returns how many were freed. Double frees
        raise (refcount below zero = allocator corruption)."""
        freed = 0
        for p in pids:
            p = int(p)
            if p == GARBAGE_PAGE:
                continue                    # tables pad with page 0
            if not 0 < p < self.num_pages or self.ref[p] <= 0:
                raise RuntimeError(
                    f"double free of page {p} (refcount "
                    f"{self.ref[p] if 0 <= p < self.num_pages else '?'}"
                    f")")
            self.ref[p] -= 1
            if self.ref[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    def stats(self) -> dict:
        return {
            "num_pages": self.capacity,
            "page_size": self.page_size,
            "pages_in_use": self.pages_in_use,
            "pages_peak": self.peak_in_use,
            "pool_occupancy": round(
                self.pages_in_use / max(1, self.capacity), 4),
        }
