"""Paged KV cache + radix prefix reuse for the serve engine.

The dense engine gives every slot a private ``[max_len]`` KV row and
prefills every request from scratch. At production traffic that is the
two biggest serving wastes at once: HBM is RESERVED at worst case per
slot (a 12-token request holds a 1024-token row), and shared prompt
prefixes (system prompts, few-shot headers, multi-turn conversations)
are RECOMPUTED per request. This package replaces the row cache with a
page pool and a host-side prefix cache:

- :mod:`pool` — the allocator: the KV cache becomes a
  ``[num_pages, page_size, ...]`` pytree; slots hold page tables
  (``[num_slots, max_pages]`` int32 fed to the jitted programs), pages
  are refcounted, and page 0 is the write-off page freed slots ride.
- :mod:`radix` — the prefix cache: a radix tree over token-id blocks
  maps a new request's longest cached prefix to refcounted pages, so
  prefill runs only on the uncached tail; multi-turn ``session``
  requests re-attach their conversation's pages (partial tail page
  included, copy-on-write when shared); refcount-0 cached pages evict
  LRU under pool pressure.
- :mod:`engine` — :class:`~engine.PagedSlotEngine`, the drop-in
  :class:`~tensorflow_distributed_tpu.serve.engine.SlotDecodeEngine`
  subclass dispatching the paged decode/verify/prefill executables
  (same one-program static-shape discipline, censused as
  ``serve_*_paged`` in the jaxpr goldens, zero collectives).

``--serve.paged`` arms it (default off: the dense engine code path is
untouched — byte-identical to the pre-paging tree); gated end to end
by ``benchmarks/pagebench.py`` -> the committed PAGEBENCH.json.
"""

from tensorflow_distributed_tpu.serve.paging.pool import (  # noqa: F401
    GARBAGE_PAGE, PagePool, PoolExhausted)
from tensorflow_distributed_tpu.serve.paging.radix import (  # noqa: F401
    RadixCache)
