"""ResNet family for the BASELINE.json scale-out configs.

The reference has no ResNet (its only model is the MNIST CNN,
mnist_python_m.py:104-128); these exist to prove the ps->allreduce port
generalizes past a toy convnet: ResNet-20/CIFAR-10 and
ResNet-50/ImageNet-shape reuse the identical train-step/mesh machinery
under pure data parallelism.

TPU notes:
- NHWC layout, 3x3/1x1 convs in ``compute_dtype`` (bfloat16 default) so
  they tile onto the MXU; BatchNorm statistics and residual adds in f32.
- BatchNorm runs in "sync BN" semantics for free: batch means/variances
  reduce over the *global* sharded batch inside jit, so XLA inserts the
  cross-replica allreduce — no wrapper module. The moving averages live
  in the ``batch_stats`` collection carried by ``TrainState.extra``.
- He-normal kernel init, zero-init for the final BN scale of each
  residual branch (the standard "zero-gamma" trick: blocks start as
  identity, stabilizing early large-batch training).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/20/34)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       kernel_init=nn.initializers.he_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), self.strides, name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), name="conv2")(y)
        y = norm(scale_init=nn.initializers.zeros_init(), name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), self.strides,
                            name="proj")(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 (x4) residual block (ResNet-50/101/152)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       kernel_init=nn.initializers.he_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), self.strides, name="conv2")(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(scale_init=nn.initializers.zeros_init(), name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), self.strides,
                            name="proj")(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet: CIFAR stem (3x3) or ImageNet stem (7x7/2+pool).

    stage_sizes: blocks per stage; filters double each stage.
    """

    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 10
    num_filters: int = 16
    cifar_stem: bool = True
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       kernel_init=nn.initializers.he_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.compute_dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), name="conv_stem")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_stem")(x)
        x = norm(name="bn_stem")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * (2 ** stage), strides=strides,
                    compute_dtype=self.compute_dtype,
                    name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global avg pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     kernel_init=nn.initializers.he_normal(), name="head")(x)
        return x.astype(jnp.float32)


def resnet20(num_classes: int = 10, compute_dtype: Dtype = jnp.bfloat16,
             **_ignored) -> ResNet:
    """CIFAR-10 ResNet-20: 3 stages x 3 basic blocks, 16/32/64 filters
    (6n+2 with n=3). ~0.27M params."""
    return ResNet(stage_sizes=(3, 3, 3), block_cls=BasicBlock,
                  num_classes=num_classes, num_filters=16, cifar_stem=True,
                  compute_dtype=compute_dtype)


def resnet50(num_classes: int = 1000, compute_dtype: Dtype = jnp.bfloat16,
             **_ignored) -> ResNet:
    """ImageNet ResNet-50: stages (3,4,6,3) of bottleneck blocks,
    64-base filters, 7x7/2 stem + maxpool. ~25.6M params."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock,
                  num_classes=num_classes, num_filters=64, cifar_stem=False,
                  compute_dtype=compute_dtype)
