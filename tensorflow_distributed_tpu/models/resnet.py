"""ResNet family for the BASELINE.json scale-out configs
(ResNet-20/CIFAR-10, ResNet-50/ImageNet). Implemented in a later
milestone of this round; importable now so the registry stays total."""

from __future__ import annotations


def resnet20(**kw):
    raise NotImplementedError("resnet20 lands in a later milestone")


def resnet50(**kw):
    raise NotImplementedError("resnet50 lands in a later milestone")
