"""The reference MNIST CNN, defined exactly once.

Architecture parity with ``conv_net`` (mnist_python_m.py:104-128, shapes
at :185-196; duplicated in mnist_single.py:55-88 and the notebook):

    5x5 conv  1->32, bias, ReLU        (wc1: [5,5,1,32])
    2x2 maxpool stride 2, SAME         (28 -> 14)
    5x5 conv 32->64, bias, ReLU        (wc2: [5,5,32,64])
    2x2 maxpool stride 2, SAME         (14 -> 7)
    flatten 7*7*64 = 3136
    dense 3136->1024, bias, ReLU       (wd1)
    dropout (keep 0.75 in the reference, fed as a literal feed at
             mnist_python_m.py:292)
    dense 1024->10 logits              (out)

Init schemes (config.init_scheme):
    "reference" — normal(stddev=1.0) for every weight AND bias, matching
        ``tf.random_normal`` defaults (mnist_python_m.py:185-196). This is
        what caps the reference's accuracy at ~95.75% (performance:6);
        kept for apples-to-apples comparison runs.
    "improved" (default) — He-normal kernels, zero biases; reaches the
        >=99% BASELINE.json target.

TPU notes: convs/matmuls run in ``compute_dtype`` (bfloat16 by default)
so they tile onto the MXU at full rate; params and loss math stay f32.
NHWC layout, which XLA:TPU prefers.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


def _kernel_init(scheme: str):
    if scheme == "reference":
        return nn.initializers.normal(stddev=1.0)
    return nn.initializers.he_normal()


def _bias_init(scheme: str):
    if scheme == "reference":
        return nn.initializers.normal(stddev=1.0)
    return nn.initializers.zeros_init()


class MnistCNN(nn.Module):
    num_classes: int = 10
    dropout_rate: float = 0.25  # = 1 - reference keep_prob 0.75
    init_scheme: str = "improved"
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        """x: [B, 28, 28, 1] float -> logits [B, 10] float32.

        Accepts flat [B, 784] too (the reference's placeholder shape,
        mnist_python_m.py:198, reshaped at :107-108)."""
        if x.ndim == 2:
            x = x.reshape(x.shape[0], 28, 28, 1)
        x = x.astype(self.compute_dtype)
        kinit, binit = _kernel_init(self.init_scheme), _bias_init(self.init_scheme)

        x = nn.Conv(32, (5, 5), padding="SAME", kernel_init=kinit,
                    bias_init=binit, dtype=self.compute_dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
        x = nn.Conv(64, (5, 5), padding="SAME", kernel_init=kinit,
                    bias_init=binit, dtype=self.compute_dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")

        x = x.reshape(x.shape[0], -1)  # [B, 3136]
        x = nn.Dense(1024, kernel_init=kinit, bias_init=binit,
                     dtype=self.compute_dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(rate=self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, kernel_init=kinit, bias_init=binit,
                     dtype=self.compute_dtype, name="out")(x)
        return x.astype(jnp.float32)
