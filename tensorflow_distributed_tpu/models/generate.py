"""Autoregressive generation with a KV cache.

The reference's "inference" was a timed validation pass over MNIST
(mnist_single.py:124-134) — classification only. The LM family here
gets the real thing: prefill the prompt in one pass, then decode one
token per step against per-layer KV caches ([B, max_len, H, Dh],
static shapes, updated in place via dynamic_update_slice), the whole
loop a single ``lax.scan`` under jit — no per-token host round-trips,
no recompilation, O(L) attention per new token instead of O(L^2)
re-forwarding.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.lru_cache(maxsize=32)
def _compiled(model, max_new_tokens: int, temperature: float):
    """One jitted prefill+decode program per (model, N, temperature).

    Cached so repeat generate() calls reuse the compiled executable
    (jit's cache is keyed on the function object — a closure rebuilt
    per call would retrace every time). Flax modules are frozen
    dataclasses, hence hashable cache keys.
    """

    @jax.jit
    def run(params, prompt, key):
        P = prompt.shape[1]
        # Prefill: one pass over the prompt populates every layer cache.
        logits, state = model.apply(
            {"params": params}, prompt, decode=True,
            positions=jnp.arange(P)[None, :], mutable=["cache"])
        cache = state["cache"]

        def pick(logits, key):
            last = logits[:, -1, :]
            if temperature == 0.0:
                return jnp.argmax(last, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                key, last / temperature, axis=-1).astype(jnp.int32)

        def step(carry, _):
            cache, tok, pos, key = carry
            key, sub = jax.random.split(key)
            logits, state = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                decode=True, positions=pos[None, None],
                mutable=["cache"])
            nxt = pick(logits, sub)
            return (state["cache"], nxt, pos + 1, key), nxt

        key, sub = jax.random.split(key)
        first = pick(logits, sub)
        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, first, jnp.asarray(P, jnp.int32), key),
            None, length=max_new_tokens - 1)
        return jnp.concatenate([first[:, None], toks.T], axis=1)

    return run


def generate(model, params, prompt: jax.Array, max_new_tokens: int, *,
             temperature: float = 0.0,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Continue ``prompt`` [B, P] by ``max_new_tokens`` greedy
    (temperature 0) or sampled tokens. Returns [B, max_new_tokens].

    ``model`` is a causal TransformerLM (models/transformer.py). The
    mesh's seq axis must be 1 (single-token steps can't be
    seq-sharded); batch stays sharded over "data" as usual.
    """
    cfg = model.cfg
    if not cfg.causal:
        raise ValueError("generate() needs a causal model")
    B, P = prompt.shape
    if P + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt {P} + {max_new_tokens} new > max_len {cfg.max_len}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    key = key if key is not None else jax.random.key(0)
    return _compiled(model, max_new_tokens, temperature)(params, prompt,
                                                         key)
